"""DCN gradient-path tuner: bucket-size x wire-format x layout sweep.

Local sizing companion to the DCN-aware gradient path
(edl_tpu/train/comm.py, doc/design_comm.md): one seeded tiny
transformer trained through every {bucket_mb} x {dense, topk, int8} x
{flat, hybrid} combination, printed as a markdown table of

  step time | per-chip cross-slice bytes/step | schedulable overlap %
  | parity vs the jit step

Seeded-exact: the model init, the batch, the bucket plan and the
compressed selections are all functions of --seed, so two runs on the
same machine produce the same table (timings jitter; every non-timing
column is stable). Runs on the CPU harness — where every byte rides
the same host links, so step-time columns are SCHEDULE-COST parity
checks, not a DCN win; the bytes columns are exact wire accounting
either way (what you'd save on real cross-slice fabric).

  python tools/comm_bench.py --buckets 0.05,0.25 --steps 4
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/comm_bench.py` puts tools/
    sys.path.insert(0, REPO)  # on sys.path, not the repo root


def build_world(seed: int):
    import jax
    import jax.numpy as jnp
    import optax
    from flax.core import meta

    from edl_tpu.models.transformer import (Transformer,
                                            TransformerConfig, lm_loss_fn)
    from edl_tpu.train.state import TrainState

    n_dev = len(jax.devices())
    if n_dev < 2 or n_dev % 2:
        raise SystemExit(f"need an even multi-device world (have "
                         f"{n_dev}); run under "
                         "XLA_FLAGS=--xla_force_host_platform_device_"
                         "count=8")
    vocab, seq = 128, 32
    cfg = TransformerConfig(vocab_size=vocab, d_model=64, n_heads=4,
                            n_layers=2, d_ff=256, max_len=seq,
                            dtype=jnp.float32, mesh=None)
    model = Transformer(cfg)
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, vocab,
                        size=(4 * n_dev, seq)).astype(np.int32)
    variables = meta.unbox(model.init(jax.random.PRNGKey(seed),
                                      jnp.asarray(toks), train=False))
    state = TrainState.create(apply_fn=model.apply,
                              params=variables["params"],
                              tx=optax.sgd(0.1, momentum=0.9))
    return lm_loss_fn, state, {"tokens": toks}, n_dev


def build_moe_world(seed: int):
    """Seeded MoE twin of build_world: same depth/width, expert MLPs
    (E = 2 * world) in place of the dense FFNs, loss FACTORY for the
    manual dispatch path + the plain loss for the jit reference."""
    import dataclasses
    import functools

    import jax
    import jax.numpy as jnp
    import optax
    from flax.core import meta

    from edl_tpu.models.transformer import (Transformer,
                                            TransformerConfig,
                                            lm_loss_moe)
    from edl_tpu.train.state import TrainState

    n_dev = len(jax.devices())
    vocab, seq = 128, 32
    cfg = TransformerConfig(vocab_size=vocab, d_model=64, n_heads=4,
                            n_layers=2, d_ff=256, max_len=seq,
                            dtype=jnp.float32, mesh=None, moe=True,
                            n_experts=2 * n_dev, moe_top_k=2)
    model = Transformer(cfg)
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, vocab,
                        size=(4 * n_dev, seq)).astype(np.int32)
    variables = meta.unbox(model.init(jax.random.PRNGKey(seed),
                                      jnp.asarray(toks), train=False))
    state = TrainState.create(apply_fn=model.apply,
                              params=variables["params"],
                              tx=optax.sgd(0.1, momentum=0.9))

    def loss_factory(wire):
        wired = Transformer(dataclasses.replace(cfg, moe_wire=wire))
        return functools.partial(lm_loss_moe,
                                 aux_weight=cfg.moe_aux_weight,
                                 apply_fn=wired.apply)

    jit_loss = functools.partial(lm_loss_moe,
                                 aux_weight=cfg.moe_aux_weight)
    return loss_factory, jit_loss, state, {"tokens": toks}, n_dev


def time_step(step_fn, state, placed, steps: int, mesh) -> float:
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    s = jax.tree.map(lambda a: jax.device_put(
        a, NamedSharding(mesh, P())), state)
    for _ in range(2):
        s, m = step_fn(s, placed)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        s, m = step_fn(s, placed)
    float(m["loss"])
    return (time.perf_counter() - t0) / steps * 1e3


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tools/comm_bench.py")
    parser.add_argument("--buckets", default="0.05,0.25",
                        help="comma list of bucket MiB targets")
    parser.add_argument("--steps", type=int, default=4)
    parser.add_argument("--seed", type=int, default=5)
    parser.add_argument("--topk-frac", type=float, default=0.125)
    parser.add_argument("--moe", action=argparse.BooleanOptionalAction,
                        default=True,
                        help="append the MoE all-to-all sweep (flat vs "
                             "hierarchical vs int8 DCN leg)")
    args = parser.parse_args(argv)

    from edl_tpu.parallel import mesh as mesh_lib
    from edl_tpu.train import comm
    from edl_tpu.train.step import make_train_step

    loss_fn, state, batch, n_dev = build_world(args.seed)
    topo = mesh_lib.SliceTopology(2, n_dev // 2)
    worlds = {
        "flat": (mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": -1})),
                 None),
        "hybrid": (mesh_lib.make_hybrid_mesh(
            mesh_lib.MeshSpec({"dp": -1}), topo), topo),
    }
    rows = []
    # the jit reference per layout (bucket size is meaningless there)
    for layout, (mesh, _) in worlds.items():
        placed = mesh_lib.shard_batch(mesh, batch)
        ms = time_step(make_train_step(loss_fn, donate=False), state,
                       placed, args.steps, mesh)
        rows.append((layout, "jit", "-", round(ms, 2), "-", "-", "-"))
    for bucket_mb in [float(b) for b in args.buckets.split(",") if b]:
        for layout, (mesh, topo_) in worlds.items():
            placed = mesh_lib.shard_batch(mesh, batch)
            for mode in ("off", "topk", "int8"):
                cfgc = comm.CommConfig(bucket_mb=bucket_mb,
                                       compress=mode,
                                       topk_frac=args.topk_frac,
                                       min_compress_elems=64)
                step = comm.make_comm_train_step(
                    loss_fn, mesh=mesh, topology=topo_, donate=False,
                    config=cfgc)
                ms = time_step(step, state, placed, args.steps, mesh)
                gate = comm.loss_parity_gate(
                    loss_fn, state, batch, mesh=mesh, config=cfgc,
                    topology=topo_, steps=2, envelope=1e-1)
                parity = ("bitwise" if gate["bitwise_dense"]
                          else "loss" if gate["dense_loss_delta"] <= 1e-4
                          else "DIVERGED")
                if mode != "off":
                    parity += ("+env" if gate.get("loss_envelope_ok")
                               else "+OVER")
                rows.append((layout,
                             "dense" if mode == "off" else mode,
                             bucket_mb, round(ms, 2),
                             step.dcn_bytes_per_step(),
                             step.dcn_overlap_pct(), parity))

    print(f"# comm_bench seed={args.seed} world={n_dev} "
          f"topology=2x{n_dev // 2} topk_frac={args.topk_frac}\n")
    print("| layout | wire | bucket MiB | step ms | dcn B/step/chip "
          "| overlap % | parity |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        print("| " + " | ".join(str(c) for c in r) + " |")
    print("\nstep-ms columns are CPU-harness schedule costs (no DCN "
          "here); bytes/overlap are exact wire accounting. parity: "
          "bitwise = identical to the jit step, loss = equal loss at "
          "float tolerance (re-associated hierarchical sum), +env = "
          "compressed run inside the transient loss envelope.")

    if not args.moe:
        return 0

    # -- MoE all-to-all sweep: flat vs hierarchical vs int8 DCN leg ----------
    lf, jit_loss, mstate, mbatch, _ = build_moe_world(args.seed)
    mesh = mesh_lib.make_hybrid_mesh(mesh_lib.MeshSpec({"ep": -1}),
                                     topo)
    placed = mesh_lib.shard_batch(mesh, mbatch, batch_axes=("ep",))
    gate = comm.moe_parity_gate(
        lf, mstate, mbatch, mesh=mesh, topology=topo,
        comm_config=comm.CommConfig(bucket_mb=0.25),
        moe_config=comm.MoEDispatchConfig(mode="hier",
                                          compress="int8"),
        steps=2, envelope=0.1)
    moe_rows = []
    jit_ms = time_step(make_train_step(jit_loss, donate=False), mstate,
                       placed, args.steps, mesh)
    moe_rows.append(("jit-dense", round(jit_ms, 2), "-", "-", "-"))
    for mode, compress in (("flat", "off"), ("hier", "off"),
                           ("hier", "int8")):
        step = comm.make_moe_comm_step(
            lf, mesh=mesh, topology=topo, donate=False,
            config=comm.CommConfig(bucket_mb=0.25),
            moe_config=comm.MoEDispatchConfig(mode=mode,
                                              compress=compress))
        ms = time_step(step, mstate, placed, args.steps, mesh)
        parity = ("baseline" if mode == "flat"
                  else "bitwise" if gate["bitwise_hier"] else "DIVERGED")
        if compress != "off":
            parity = ("+env" if gate.get("loss_envelope_ok")
                      else "+OVER")
        moe_rows.append((f"{mode}/{compress}", round(ms, 2),
                         step.moe_dcn_bytes_per_step(),
                         step.moe_dispatch_overlap_pct(), parity))

    print(f"\n# moe all-to-all sweep (E={2 * n_dev}, top_k=2, "
          f"topology 2x{n_dev // 2})\n")
    print("| dispatch | step ms | moe dcn B/step/chip | overlap % "
          "| parity |")
    print("|---|---|---|---|---|")
    for r in moe_rows:
        print("| " + " | ".join(str(c) for c in r) + " |")
    print("\nmoe parity: bitwise = hier/off identical to the flat "
          "single collective through real steps; +env = int8 DCN leg "
          "inside the loss envelope vs flat. jit-dense routes per "
          "GLOBAL batch (different capacity semantics) — timing "
          "reference only.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
