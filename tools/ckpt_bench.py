"""One-shot checkpoint-plane tuner: save stall across state sizes x
sync/async x save interval.

Sizing companion to the async snapshot-then-write plane
(edl_tpu/train/checkpoint.py `save_async`): for each state size it runs
a simulated step loop (fixed per-step compute) that checkpoints every N
steps, and reports what the STEP LOOP paid per save — the full
serialize+write under sync, the snapshot copy under async — plus the
background write time and how many queued snapshots the drop-to-latest
rule superseded. Picking `--ckpt-steps` / EDL_TPU_CKPT_STEPS for a job
becomes one command: walk the interval down until the stall column (or
the superseded column — the writer's sign that it can't keep up) says
stop.

  python tools/ckpt_bench.py --sizes-mb 4 16 64 --intervals 1 5 20
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/ckpt_bench.py` puts tools/
    sys.path.insert(0, REPO)  # on sys.path, not the repo root


def build_state(size_mb: float):
    """A train-state-shaped pytree of the requested footprint: a few
    dozen layer-ish leaves (serialization cost scales with leaf count
    too, not just bytes) placed on device."""
    import jax
    import numpy as np

    n_leaves = 32
    floats = int(size_mb * 2**20 / 4)
    per_leaf = max(1, floats // n_leaves)
    side = max(1, int(per_leaf ** 0.5))
    rng = np.random.default_rng(0)
    tree = {"params": {f"layer_{i}": {
        "kernel": rng.normal(size=(side, side)).astype(np.float32),
        "bias": rng.normal(size=(side,)).astype(np.float32)}
        for i in range(n_leaves)}}
    return jax.device_put(tree)


def run_case(state, *, sync: bool, interval: int, steps: int,
             step_s: float) -> dict:
    from edl_tpu.train.checkpoint import CheckpointManager
    from edl_tpu.train.state import TrainStatus

    d = tempfile.mkdtemp(prefix="edl-ckpt-bench-")
    mgr = CheckpointManager(d, max_to_keep=2, process_index=0)
    stall_ms = []
    t_run = time.perf_counter()
    try:
        for step in range(1, steps + 1):
            time.sleep(step_s)  # the "train step" (releases the GIL,
            # like device compute — the writer thread overlaps it)
            if step % interval == 0:
                t0 = time.perf_counter()
                if sync:
                    mgr.save(state, TrainStatus(step=step))
                else:
                    mgr.save_async(state, TrainStatus(step=step))
                stall_ms.append((time.perf_counter() - t0) * 1e3)
        mgr.close()
        run_s = time.perf_counter() - t_run
        stats = mgr.stats()
    finally:
        shutil.rmtree(d, ignore_errors=True)
    stall_ms.sort()
    return {"stall_ms": stall_ms[len(stall_ms) // 2],
            "stall_ms_max": stall_ms[-1],
            "write_s": stats["write_s_last"],
            "superseded": stats["superseded"],
            "run_s": run_s}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tools/ckpt_bench.py")
    parser.add_argument("--sizes-mb", type=float, nargs="+",
                        default=[4, 16, 64])
    parser.add_argument("--intervals", type=int, nargs="+",
                        default=[1, 5, 20],
                        help="checkpoint every N steps")
    parser.add_argument("--steps", type=int, default=40,
                        help="simulated steps per case")
    parser.add_argument("--step-ms", type=float, default=20.0,
                        help="simulated per-step compute")
    args = parser.parse_args(argv)

    print(f"steps/case: {args.steps}  step: {args.step_ms:.0f}ms  "
          f"(stall = what the step loop pays per save; superseded = "
          f"drop-to-latest drops, the writer's backpressure signal)")
    print(f"{'state':>8} {'every':>6} {'mode':>6} {'stall ms':>9} "
          f"{'max ms':>8} {'write s':>8} {'dropped':>8} {'run s':>6}")
    for size in args.sizes_mb:
        state = build_state(size)
        for interval in args.intervals:
            for sync in (True, False):
                r = run_case(state, sync=sync, interval=interval,
                             steps=args.steps, step_s=args.step_ms / 1e3)
                print(f"{size:>6.0f}MB {interval:>6} "
                      f"{'sync' if sync else 'async':>6} "
                      f"{r['stall_ms']:>9.1f} {r['stall_ms_max']:>8.1f} "
                      f"{r['write_s']:>8.3f} {r['superseded']:>8} "
                      f"{r['run_s']:>6.2f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
