"""Control-plane load bench for the replicated coordination store.

Answers the three capacity questions ROADMAP item 1 frames, on the
repo's 1-core bench host (numbers are PER CORE — the store fans out
with cores, so fleet projections multiply):

- **registration pressure**: how many simulated pods/second a
  3-replica group absorbs through the full majority-ack write path
  (TCP + replication + commit gate), vs the single-store baseline —
  i.e. what one shard group costs vs what it buys;
- **watch fan-out**: how many concurrent watch streams one follower
  sustains while delivering a mutation burst to ALL of them (in-proc
  streams measure the store's fan-out ceiling; a TCP cohort rides on
  top to price the socket path);
- **failover**: leader killed under load — write-unavailability window
  and the zero-lost-events check (revision audit, same contract the
  chaos dryrun enforces).

    python tools/store_bench.py [--pods 2000] [--streams 500] [--json out.json]

Prints a human summary and (with --json) the artifact consumed by
`bench.py bench_store_ha`'s trend row. Pure control plane: identical
on every platform, no jax anywhere.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/store_bench.py` puts tools/
    sys.path.insert(0, REPO)  # on sys.path, not the repo root


def bench_registrations(pods: int) -> dict:
    """Pod registrations/s: single store vs 3-replica majority-ack."""
    from edl_tpu.coord.client import StoreClient
    from edl_tpu.coord.replication import ReplicaGroup
    from edl_tpu.coord.server import StoreServer

    def _drive(client, n) -> float:
        t0 = time.perf_counter()
        for i in range(n):
            client.put(f"/job/pods/pod-{i}", '{"rank": %d}' % i)
        return n / (time.perf_counter() - t0)

    with StoreServer(port=0, host="127.0.0.1") as srv:
        single = StoreClient(f"127.0.0.1:{srv.port}")
        single_rate = _drive(single, pods)
        single.close()
    with ReplicaGroup(3, election_ttl=1.0) as group:
        group.wait_leader(timeout=20.0)
        client = group.client(timeout=5.0)
        replicated_rate = _drive(client, pods)
        client.close()
    return {
        "store_pods_registered": pods,
        "store_single_writes_per_sec_core": round(single_rate, 1),
        "store_majority_writes_per_sec_core": round(replicated_rate, 1),
        "store_replication_write_cost_x": round(
            single_rate / max(replicated_rate, 1e-9), 2),
    }


def bench_watch_fanout(streams: int, tcp_streams: int) -> dict:
    """Follower watch fan-out: `streams` in-proc watchers plus a
    `tcp_streams` TCP cohort on ONE follower, one mutation burst,
    everyone must see every event."""
    from edl_tpu.coord.client import StoreClient
    from edl_tpu.coord.replication import ReplicaGroup

    burst = 50
    with ReplicaGroup(3, election_ttl=1.0) as group:
        leader = group.wait_leader(timeout=20.0)
        follower = next(s for s in group.servers if s is not leader)
        client = group.client(timeout=5.0)
        client.put("/fan/warm", "0")
        deadline = time.monotonic() + 10.0
        while follower.node.store.current_revision < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.01)

        watches = [follower.node.store.watch("/fan/")
                   for _ in range(streams)]
        tcp_clients = [StoreClient(follower.endpoint, timeout=5.0)
                       for _ in range(tcp_streams)]
        tcp_watches = [c.watch("/fan/", heartbeat=5.0)
                       for c in tcp_clients]

        t0 = time.perf_counter()
        for i in range(burst):
            client.put(f"/fan/k{i}", str(i))
        # drain: every stream sees the whole burst (+1 warm event for
        # in-proc watches created after it)
        need = burst

        def _drain(watch) -> int:
            got = 0
            stop_at = time.monotonic() + 20.0
            while got < need and time.monotonic() < stop_at:
                batch = watch.get(timeout=0.5)
                if batch is None:
                    continue
                got += sum(1 for ev in batch.events
                           if ev.key != "/fan/warm")
            return got

        delivered = sum(_drain(w) for w in watches)
        fanout_s = time.perf_counter() - t0
        tcp_delivered = sum(_drain(w) for w in tcp_watches)
        tcp_s = time.perf_counter() - t0

        for w in watches:
            w.cancel()
        for w in tcp_watches:
            w.cancel()
        for c in tcp_clients:
            c.close()
        client.close()
    total = streams * burst
    tcp_total = tcp_streams * burst
    return {
        "store_watch_fanout_streams": streams + tcp_streams,
        "store_watch_fanout_delivered_pct": round(
            100.0 * (delivered + tcp_delivered) / max(total + tcp_total, 1),
            2),
        "store_watch_fanout_events_per_sec_core": round(
            delivered / fanout_s, 1),
        "store_watch_fanout_tcp_events_per_sec_core": round(
            tcp_delivered / max(tcp_s, 1e-9), 1),
    }


def bench_failover(writers_hz: float = 100.0) -> dict:
    """Kill the leader under write load: unavailability window =
    last-ack-before-kill -> first-ack-after, with the zero-lost audit."""
    from edl_tpu.coord.replication import ReplicaGroup

    with ReplicaGroup(3, election_ttl=0.6) as group:
        group.wait_leader(timeout=20.0)
        client = group.client(timeout=3.0)
        watcher = group.client(timeout=3.0)
        watch = watcher.watch("/job/", start_revision=0)

        acked: dict[str, int] = {}
        stop = threading.Event()
        gap = {"last_before": 0.0, "first_after": None}
        killed_at = [None]

        def writer() -> None:
            i = 0
            while not stop.is_set() and i < 2000:
                try:
                    rev = client.put(f"/job/rank/{i % 32}", f"p-{i}")
                    now = time.perf_counter()
                    acked[f"p-{i}"] = rev
                    if killed_at[0] is None:
                        gap["last_before"] = now
                    elif gap["first_after"] is None:
                        gap["first_after"] = now
                except Exception:
                    pass
                i += 1
                time.sleep(1.0 / writers_hz)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            time.sleep(0.5)
            killed_at[0] = time.perf_counter()
            group.kill_leader()
            group.wait_leader(timeout=20.0)
            deadline = time.monotonic() + 10.0
            while gap["first_after"] is None \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            time.sleep(0.5)
        finally:
            stop.set()
            t.join(timeout=15.0)

        seen: set[int] = set()
        deadline = time.monotonic() + 10.0
        max_acked = max(acked.values(), default=0)
        while time.monotonic() < deadline:
            batch = watch.get(timeout=0.5)
            if batch is None:
                if seen and max(seen) >= max_acked:
                    break
                continue
            seen.update(ev.revision for ev in batch.events)
        lost = sum(1 for rev in acked.values() if rev not in seen)
        watch.cancel()
        watcher.close()
        client.close()
    downtime_ms = 0.0
    if gap["first_after"] is not None:
        downtime_ms = (gap["first_after"] - gap["last_before"]) * 1e3
    return {
        "store_failover_downtime_ms": round(downtime_ms, 1),
        "store_failover_acked_writes": len(acked),
        "store_events_lost": lost,
    }


def bench_fleet(pods: int, streams: int, *, pods_per_host: int = 40,
                prefixes: int = 128, tcp_streams: int = 200,
                reg_writers: int = 8, burst: int = 8,
                keepalive_window_s: float = 6.0,
                keepalive_hosts: int = 25,
                keepalive_per_pod: int = 400) -> dict:
    """The 100k-pod / 1M-watch control-plane tier (ISSUE 18 acceptance):

    - register ``pods`` simulated pods through coalesced HOST leases
      (``pods_per_host`` registrations per lease) against a 3-replica
      majority-ack group;
    - attach ``streams`` in-proc watch streams THROUGH the watch relay
      (one upstream stream per distinct prefix — ``prefixes`` of them),
      plus a ``tcp_streams`` TCP cohort against a RelayServer to price
      the socket path;
    - mutation burst -> LEADER KILL -> second burst: every stream must
      see every event of both bursts exactly once, revisions strictly
      increasing (the relay's upstream watches resume by revision across
      the failover — zero lost, zero duplicated);
    - measure keepalive writes/s per pod with coalesced host leases vs
      per-pod leases, live cohorts of each, in the same artifact.

    CPU-host honesty: the 1M streams are in-proc ``RelayWatch`` handles
    (``__slots__`` + shared batch refs make a million fit in RAM) and
    the drain is a polling pass, not 1M blocked threads; the TCP cohort
    is what prices real sockets. doc/design_coord.md carries the
    limits table.
    """
    import threading as th

    from edl_tpu.coord.client import (HostLeaseCoalescer, LeaseKeeper,
                                      StoreClient)
    from edl_tpu.coord.relay import RelayServer, WatchRelay
    from edl_tpu.coord.replication import ReplicaGroup
    from edl_tpu.utils.exceptions import EdlStoreError

    def _put_retry(client, key, value, deadline_s: float = 30.0):
        stop_at = time.monotonic() + deadline_s
        while True:
            try:
                return client.put(key, value)
            except EdlStoreError:
                if time.monotonic() >= stop_at:
                    raise
                time.sleep(0.1)

    out: dict = {"store_fleet_pods": pods,
                 "store_fleet_pods_per_host": pods_per_host,
                 "store_fleet_prefixes": prefixes}
    with ReplicaGroup(3, election_ttl=0.8) as group:
        group.wait_leader(timeout=20.0)
        spec = ",".join(s.endpoint for s in group.servers)

        # -- registration through coalesced host leases ------------------
        hosts = (pods + pods_per_host - 1) // pods_per_host
        host_ttl = 1800.0  # no keepalive traffic during the bench window
        t0 = time.perf_counter()

        def _register(wid: int) -> None:
            c = group.client(timeout=5.0)
            try:
                for h in range(wid, hosts, reg_writers):
                    lease = c.lease_grant(host_ttl)
                    n = min(pods_per_host, pods - h * pods_per_host)
                    for p in range(n):
                        _put_retry(c, f"/fleet/pods/h{h:05d}/{p:02d}",
                                   '{"host":%d,"slot":%d}' % (h, p))
            finally:
                c.close()

        writers = [th.Thread(target=_register, args=(w,), daemon=True)
                   for w in range(reg_writers)]
        for t in writers:
            t.start()
        for t in writers:
            t.join()
        reg_s = time.perf_counter() - t0
        out["store_fleet_hosts"] = hosts
        out["store_fleet_reg_writes_per_sec"] = round(pods / reg_s, 1)

        # -- the watch fleet: in-proc relay cohort + TCP relay cohort ----
        relay = WatchRelay(spec, buffer=8192)
        rs = RelayServer(spec, port=0, host="127.0.0.1").start()

        def fan(k: int) -> str:
            return f"/fleet/fan/{k:04d}/"

        t0 = time.perf_counter()
        watches = [relay.attach(fan(i % prefixes)) for i in range(streams)]
        out["store_fleet_attach_s"] = round(time.perf_counter() - t0, 1)

        tcp_clients = [StoreClient(f"127.0.0.1:{rs.port}", timeout=5.0)
                       for _ in range(tcp_streams)]
        tcp_watches = [c.watch(fan(i % prefixes), heartbeat=5.0,
                               via_relay=False)
                       for i, c in enumerate(tcp_clients)]

        wc = group.client(timeout=5.0)

        def _burst(base: int) -> None:
            for i in range(prefixes * burst):
                _put_retry(wc, fan(i % prefixes) + f"e{base + i:06d}",
                           str(base + i))

        def _wait_fanned(want: int, timeout: float) -> int:
            stop_at = time.monotonic() + timeout
            fanned = relay.stats()["relay_events_fanned_out"]
            while fanned < want and time.monotonic() < stop_at:
                time.sleep(0.05)
                fanned = relay.stats()["relay_events_fanned_out"]
            return fanned

        per_stream = burst  # events each stream's prefix gets per burst
        t0 = time.perf_counter()
        _burst(0)
        _wait_fanned(streams * per_stream, 120.0)
        fan_a_s = time.perf_counter() - t0
        out["store_fanout_events_per_sec"] = round(
            streams * per_stream / fan_a_s, 1)

        # -- leader kill mid-run: relay upstreams must resume ------------
        t_kill = time.perf_counter()
        group.kill_leader()
        group.wait_leader(timeout=30.0)
        probe_rev = _put_retry(wc, "/fleet/fan-probe", "alive")
        out["store_fleet_failover_downtime_ms"] = round(
            (time.perf_counter() - t_kill) * 1e3, 1)
        del probe_rev
        _burst(prefixes * burst)
        fanned = _wait_fanned(2 * streams * per_stream, 180.0)
        out["store_fleet_events_fanned"] = fanned

        # -- exactly-once audit, per stream ------------------------------
        expected = 2 * per_stream
        delivered = lost = dups = compacted_streams = 0
        for w in watches:
            last = 0
            n = 0
            comp = False
            while True:
                b = w.get(timeout=0)
                if b is None:
                    break
                if b.compacted:
                    comp = True
                for ev in b.events:
                    if ev.revision <= last:
                        dups += 1
                    last = ev.revision
                    n += 1
            delivered += n
            if comp:
                compacted_streams += 1
            elif n < expected:
                lost += expected - n

        tcp_delivered = tcp_dups = 0
        for w in tcp_watches:
            got = 0
            last = 0
            stop_at = time.monotonic() + 60.0
            while got < expected and time.monotonic() < stop_at:
                b = w.get(timeout=0.5)
                if b is None:
                    continue
                for ev in b.events:
                    if ev.key == "/fleet/fan-probe":
                        continue
                    if ev.revision <= last:
                        tcp_dups += 1
                    last = ev.revision
                    got += 1
            tcp_delivered += got

        relay_stats = relay.stats()
        out["store_watch_streams"] = streams + tcp_streams
        out["store_fleet_events_expected"] = streams * expected
        out["store_fleet_events_delivered"] = delivered
        out["store_fleet_events_lost"] = lost
        out["store_fleet_duplicates"] = dups + tcp_dups
        out["store_fleet_compacted_streams"] = compacted_streams
        out["store_fleet_tcp_streams"] = tcp_streams
        out["store_fleet_tcp_delivered_pct"] = round(
            100.0 * tcp_delivered / max(tcp_streams * expected, 1), 2)
        out["store_fleet_upstream_streams"] = \
            relay_stats["relay_upstream_streams"]
        out["store_fleet_upstream_resumes"] = relay_stats["relay_resumes"]

        for w in tcp_watches:
            w.cancel()
        for c in tcp_clients:
            c.close()
        rs.stop()
        relay.close()  # cancels every in-proc RelayWatch in one sweep

        # -- keepalive writes/s: coalesced host leases vs per-pod --------
        ka_client = group.client(timeout=5.0)
        coalescers = [HostLeaseCoalescer(ka_client, f"bench-host-{h}",
                                         ttl=3.0)
                      for h in range(keepalive_hosts)]
        for h, co in enumerate(coalescers):
            for p in range(pods_per_host):
                co.attach(f"/fleet/ka/h{h:03d}/{p:02d}")
        time.sleep(keepalive_window_s)
        coalesced_writes = sum(co.stats()["keepalives_sent"]
                               for co in coalescers)
        for co in coalescers:
            co.close(revoke=True)
        co_pods = keepalive_hosts * pods_per_host
        coalesced_per_pod = coalesced_writes / keepalive_window_s / co_pods

        class _CountingLeases:
            """Store facade counting keepalive writes (LeaseKeeper only
            touches lease_keepalive/lease_revoke)."""

            def __init__(self, inner):
                self.inner = inner
                self.count = 0
                self._lock = th.Lock()

            def lease_keepalive(self, lease: int) -> bool:
                with self._lock:
                    self.count += 1
                return self.inner.lease_keepalive(lease)

            def lease_revoke(self, lease: int) -> None:
                self.inner.lease_revoke(lease)

        counting = _CountingLeases(ka_client)
        keepers = []
        for _ in range(keepalive_per_pod):
            lease = ka_client.lease_grant(3.0)
            keepers.append(LeaseKeeper(counting, lease,
                                       interval=0.5).start())
        time.sleep(keepalive_window_s)
        per_pod_writes = counting.count
        for k in keepers:
            k.stop(revoke=True)
        per_pod_rate = (per_pod_writes / keepalive_window_s
                        / keepalive_per_pod)

        out["store_fleet_keepalive_writes_per_sec_per_pod"] = round(
            coalesced_per_pod, 4)
        out["store_fleet_keepalive_writes_per_sec_per_pod_uncoalesced"] \
            = round(per_pod_rate, 4)
        out["store_fleet_keepalive_reduction_x"] = round(
            per_pod_rate / max(coalesced_per_pod, 1e-9), 1)

        wc.close()
        ka_client.close()
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="replicated-store control-plane load bench")
    parser.add_argument("--pods", type=int, default=2000,
                        help="simulated pod registrations")
    parser.add_argument("--streams", type=int, default=500,
                        help="in-proc watch streams on one follower")
    parser.add_argument("--tcp-streams", type=int, default=50,
                        help="TCP watch streams on one follower")
    parser.add_argument("--fleet", action="store_true",
                        help="run the relay-tier fleet bench instead: "
                             "coalesced-lease registrations, watch "
                             "streams through the relay, leader kill "
                             "with per-stream exactly-once audit, "
                             "keepalive coalescing ratio")
    parser.add_argument("--fleet-pods", type=int, default=100_000,
                        help="fleet mode: simulated pod registrations")
    parser.add_argument("--fleet-streams", type=int, default=1_000_000,
                        help="fleet mode: in-proc relay watch streams")
    parser.add_argument("--fleet-prefixes", type=int, default=128,
                        help="fleet mode: distinct watched prefixes")
    parser.add_argument("--fleet-tcp-streams", type=int, default=200,
                        help="fleet mode: TCP streams via RelayServer")
    parser.add_argument("--pods-per-host", type=int, default=40,
                        help="fleet mode: registrations per host lease")
    parser.add_argument("--json", default=None,
                        help="write the artifact JSON here")
    args = parser.parse_args(argv)

    out: dict = {"host_cores": os.cpu_count()}
    if args.fleet:
        out.update(bench_fleet(args.fleet_pods, args.fleet_streams,
                               pods_per_host=args.pods_per_host,
                               prefixes=args.fleet_prefixes,
                               tcp_streams=args.fleet_tcp_streams))
    else:
        out.update(bench_registrations(args.pods))
        out.update(bench_watch_fanout(args.streams, args.tcp_streams))
        out.update(bench_failover())

    print(json.dumps(out, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
    if args.fleet:
        bad = []
        if out["store_fleet_events_lost"] != 0:
            bad.append(f"{out['store_fleet_events_lost']} events lost "
                       "across the leader kill")
        if out["store_fleet_duplicates"] != 0:
            bad.append(f"{out['store_fleet_duplicates']} duplicate "
                       "deliveries")
        if out["store_fleet_keepalive_reduction_x"] < 10.0:
            bad.append("keepalive coalescing under the 10x floor "
                       f"({out['store_fleet_keepalive_reduction_x']}x)")
        for b in bad:
            print(f"FAIL: {b}", file=sys.stderr)
        return 1 if bad else 0
    if out["store_events_lost"] != 0:
        print("FAIL: events lost across failover", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
