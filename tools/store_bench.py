"""Control-plane load bench for the replicated coordination store.

Answers the three capacity questions ROADMAP item 1 frames, on the
repo's 1-core bench host (numbers are PER CORE — the store fans out
with cores, so fleet projections multiply):

- **registration pressure**: how many simulated pods/second a
  3-replica group absorbs through the full majority-ack write path
  (TCP + replication + commit gate), vs the single-store baseline —
  i.e. what one shard group costs vs what it buys;
- **watch fan-out**: how many concurrent watch streams one follower
  sustains while delivering a mutation burst to ALL of them (in-proc
  streams measure the store's fan-out ceiling; a TCP cohort rides on
  top to price the socket path);
- **failover**: leader killed under load — write-unavailability window
  and the zero-lost-events check (revision audit, same contract the
  chaos dryrun enforces).

    python tools/store_bench.py [--pods 2000] [--streams 500] [--json out.json]

Prints a human summary and (with --json) the artifact consumed by
`bench.py bench_store_ha`'s trend row. Pure control plane: identical
on every platform, no jax anywhere.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/store_bench.py` puts tools/
    sys.path.insert(0, REPO)  # on sys.path, not the repo root


def bench_registrations(pods: int) -> dict:
    """Pod registrations/s: single store vs 3-replica majority-ack."""
    from edl_tpu.coord.client import StoreClient
    from edl_tpu.coord.replication import ReplicaGroup
    from edl_tpu.coord.server import StoreServer

    def _drive(client, n) -> float:
        t0 = time.perf_counter()
        for i in range(n):
            client.put(f"/job/pods/pod-{i}", '{"rank": %d}' % i)
        return n / (time.perf_counter() - t0)

    with StoreServer(port=0, host="127.0.0.1") as srv:
        single = StoreClient(f"127.0.0.1:{srv.port}")
        single_rate = _drive(single, pods)
        single.close()
    with ReplicaGroup(3, election_ttl=1.0) as group:
        group.wait_leader(timeout=20.0)
        client = group.client(timeout=5.0)
        replicated_rate = _drive(client, pods)
        client.close()
    return {
        "store_pods_registered": pods,
        "store_single_writes_per_sec_core": round(single_rate, 1),
        "store_majority_writes_per_sec_core": round(replicated_rate, 1),
        "store_replication_write_cost_x": round(
            single_rate / max(replicated_rate, 1e-9), 2),
    }


def bench_watch_fanout(streams: int, tcp_streams: int) -> dict:
    """Follower watch fan-out: `streams` in-proc watchers plus a
    `tcp_streams` TCP cohort on ONE follower, one mutation burst,
    everyone must see every event."""
    from edl_tpu.coord.client import StoreClient
    from edl_tpu.coord.replication import ReplicaGroup

    burst = 50
    with ReplicaGroup(3, election_ttl=1.0) as group:
        leader = group.wait_leader(timeout=20.0)
        follower = next(s for s in group.servers if s is not leader)
        client = group.client(timeout=5.0)
        client.put("/fan/warm", "0")
        deadline = time.monotonic() + 10.0
        while follower.node.store.current_revision < 1 \
                and time.monotonic() < deadline:
            time.sleep(0.01)

        watches = [follower.node.store.watch("/fan/")
                   for _ in range(streams)]
        tcp_clients = [StoreClient(follower.endpoint, timeout=5.0)
                       for _ in range(tcp_streams)]
        tcp_watches = [c.watch("/fan/", heartbeat=5.0)
                       for c in tcp_clients]

        t0 = time.perf_counter()
        for i in range(burst):
            client.put(f"/fan/k{i}", str(i))
        # drain: every stream sees the whole burst (+1 warm event for
        # in-proc watches created after it)
        need = burst

        def _drain(watch) -> int:
            got = 0
            stop_at = time.monotonic() + 20.0
            while got < need and time.monotonic() < stop_at:
                batch = watch.get(timeout=0.5)
                if batch is None:
                    continue
                got += sum(1 for ev in batch.events
                           if ev.key != "/fan/warm")
            return got

        delivered = sum(_drain(w) for w in watches)
        fanout_s = time.perf_counter() - t0
        tcp_delivered = sum(_drain(w) for w in tcp_watches)
        tcp_s = time.perf_counter() - t0

        for w in watches:
            w.cancel()
        for w in tcp_watches:
            w.cancel()
        for c in tcp_clients:
            c.close()
        client.close()
    total = streams * burst
    tcp_total = tcp_streams * burst
    return {
        "store_watch_fanout_streams": streams + tcp_streams,
        "store_watch_fanout_delivered_pct": round(
            100.0 * (delivered + tcp_delivered) / max(total + tcp_total, 1),
            2),
        "store_watch_fanout_events_per_sec_core": round(
            delivered / fanout_s, 1),
        "store_watch_fanout_tcp_events_per_sec_core": round(
            tcp_delivered / max(tcp_s, 1e-9), 1),
    }


def bench_failover(writers_hz: float = 100.0) -> dict:
    """Kill the leader under write load: unavailability window =
    last-ack-before-kill -> first-ack-after, with the zero-lost audit."""
    from edl_tpu.coord.replication import ReplicaGroup

    with ReplicaGroup(3, election_ttl=0.6) as group:
        group.wait_leader(timeout=20.0)
        client = group.client(timeout=3.0)
        watcher = group.client(timeout=3.0)
        watch = watcher.watch("/job/", start_revision=0)

        acked: dict[str, int] = {}
        stop = threading.Event()
        gap = {"last_before": 0.0, "first_after": None}
        killed_at = [None]

        def writer() -> None:
            i = 0
            while not stop.is_set() and i < 2000:
                try:
                    rev = client.put(f"/job/rank/{i % 32}", f"p-{i}")
                    now = time.perf_counter()
                    acked[f"p-{i}"] = rev
                    if killed_at[0] is None:
                        gap["last_before"] = now
                    elif gap["first_after"] is None:
                        gap["first_after"] = now
                except Exception:
                    pass
                i += 1
                time.sleep(1.0 / writers_hz)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            time.sleep(0.5)
            killed_at[0] = time.perf_counter()
            group.kill_leader()
            group.wait_leader(timeout=20.0)
            deadline = time.monotonic() + 10.0
            while gap["first_after"] is None \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            time.sleep(0.5)
        finally:
            stop.set()
            t.join(timeout=15.0)

        seen: set[int] = set()
        deadline = time.monotonic() + 10.0
        max_acked = max(acked.values(), default=0)
        while time.monotonic() < deadline:
            batch = watch.get(timeout=0.5)
            if batch is None:
                if seen and max(seen) >= max_acked:
                    break
                continue
            seen.update(ev.revision for ev in batch.events)
        lost = sum(1 for rev in acked.values() if rev not in seen)
        watch.cancel()
        watcher.close()
        client.close()
    downtime_ms = 0.0
    if gap["first_after"] is not None:
        downtime_ms = (gap["first_after"] - gap["last_before"]) * 1e3
    return {
        "store_failover_downtime_ms": round(downtime_ms, 1),
        "store_failover_acked_writes": len(acked),
        "store_events_lost": lost,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="replicated-store control-plane load bench")
    parser.add_argument("--pods", type=int, default=2000,
                        help="simulated pod registrations")
    parser.add_argument("--streams", type=int, default=500,
                        help="in-proc watch streams on one follower")
    parser.add_argument("--tcp-streams", type=int, default=50,
                        help="TCP watch streams on one follower")
    parser.add_argument("--json", default=None,
                        help="write the artifact JSON here")
    args = parser.parse_args(argv)

    out: dict = {"host_cores": os.cpu_count()}
    out.update(bench_registrations(args.pods))
    out.update(bench_watch_fanout(args.streams, args.tcp_streams))
    out.update(bench_failover())

    print(json.dumps(out, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
    if out["store_events_lost"] != 0:
        print("FAIL: events lost across failover", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
