"""Distill QUALITY at flagship scale on the real chip.

The reference's headline is not only throughput: its ResNet50_vd student
reaches acc1 79.0 distilled vs 77.1 trained alone on the same data
(/root/reference/README.md:70-72). This tool measures OUR analogue of
that claim with the real serving stack:

  teacher   = ResNet50_vd trained on the FULL synthetic-ImageNet shards
              (224 px, low template signal so subset students sit below
              the ceiling);
  alone     = ResNet50_vd student trained on a SUBSET of the shards with
              hard labels only;
  distilled = the SAME student/subset/steps/LR, but the loss is
              temperature-KD against the teacher's logits served over
              the real TCP stack (teacher_server CLI + DistillReader
              inside examples/imagenet_train --teachers).

distill_acc1_delta = distilled_acc1 - alone_acc1. Matched budget: both
students run identical epochs/LR/batch on identical data; the ONLY
difference is the loss target. bench.py surfaces the recorded delta in
BENCH extras (reads the artifact this writes).

Usage (TPU host):  python tools/distill_quality_tpu.py \
                       --out DISTILL_QUALITY_r5.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
TRAINER = "edl_tpu.examples.imagenet_train"


def run(cmd, env=None, timeout=2400, log_path=None):
    log = open(log_path, "wb") if log_path else None
    try:
        proc = subprocess.run(cmd, env=env, timeout=timeout,
                              stdout=log or subprocess.PIPE,
                              stderr=subprocess.STDOUT, cwd=REPO)
    finally:
        if log:
            log.close()
    if proc.returncode != 0:
        tail = ""
        if log_path and os.path.exists(log_path):
            with open(log_path, "rb") as f:
                tail = f.read()[-4000:].decode(errors="replace")
        raise SystemExit(f"command failed ({proc.returncode}): "
                         f"{' '.join(cmd)}\n{tail}")
    return proc


def train(a, data_dir, work, tag, epochs, *, ckpt=None, teachers="",
          topk=0, seed=0):
    blog = os.path.join(work, f"blog-{tag}")
    shutil.rmtree(blog, ignore_errors=True)
    cmd = [sys.executable, "-m", TRAINER, "--data-dir", data_dir,
           "--model", a.model, "--num-classes", str(a.classes),
           "--image-size", str(a.image_size), "--epochs", str(epochs),
           "--batch-size", str(a.batch_size), "--warmup-epochs", "1",
           "--lr-strategy", "cosine", "--lr", str(a.lr), "--no-augment",
           "--label-smoothing", "0", "--bf16", "--seed", str(seed),
           "--benchmark-log", blog]
    if ckpt:
        cmd += ["--ckpt-dir", ckpt]
    if teachers:
        cmd += ["--teachers", teachers,
                "--distill-temperature", str(a.temperature),
                "--distill-hard-weight", str(a.hard_weight)]
        if topk:
            cmd += ["--distill-topk", str(topk)]
    run(cmd, timeout=a.phase_timeout,
        log_path=os.path.join(work, f"{tag}.log"))
    with open(os.path.join(blog, "log_0.json")) as f:
        return json.load(f)["final"]


def measure_topk_mass(a, ckpt: str, data_dir: str, ks: list[int],
                      temperature: float) -> list[dict]:
    """Retained softmax mass of the TRAINED teacher at `temperature` for
    each K — the fraction of the tempered distribution the top-k wire
    ships. Measured on the val shard with the restored checkpoint (the
    data the quality numbers are scored on), in-process: this is a
    forward pass, not a training phase."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from edl_tpu import models as zoo
    from edl_tpu.train.checkpoint import CheckpointManager
    from edl_tpu.train.classification import create_state

    model = zoo.get_model(a.model)(num_classes=a.classes)
    state = create_state(model, jax.random.PRNGKey(0),
                         (1, a.image_size, a.image_size, 3),
                         optax.identity())
    restored = CheckpointManager(ckpt).restore_raw()
    if restored is None:
        raise SystemExit(f"no teacher checkpoint under {ckpt}")
    raw = restored[0]
    state = state.replace(params=raw["params"],
                          batch_stats=raw.get("batch_stats")
                          or state.batch_stats)
    variables = {"params": state.params}
    if state.batch_stats is not None:
        variables["batch_stats"] = state.batch_stats
    forward = jax.jit(lambda x: state.apply_fn(variables, x, train=False))

    val = np.load(os.path.join(data_dir, "val.npz"))
    images = val["image"].astype(np.float32)
    bs = min(128, len(images))
    sums = {k: [] for k in ks}
    for lo in range(0, len(images) - bs + 1, bs):
        logits = np.asarray(forward(jnp.asarray(images[lo:lo + bs])),
                            dtype=np.float64)
        z = logits / temperature
        z -= z.max(axis=-1, keepdims=True)
        prob = np.exp(z)
        prob /= prob.sum(axis=-1, keepdims=True)
        cum = np.cumsum(np.sort(prob, axis=-1)[:, ::-1], axis=-1)
        for k in ks:
            sums[k].append(cum[:, min(k, prob.shape[-1]) - 1].mean())
    return [{"topk": k,
             "mass": round(float(np.mean(sums[k])), 4),
             "wire_bytes_per_row": k * 6}  # int32 idx + fp16 val
            for k in ks]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tools/distill_quality_tpu.py")
    p.add_argument("--out", default="DISTILL_QUALITY_r5.json")
    p.add_argument("--workdir", default="/tmp/edl_distill_quality")
    p.add_argument("--model", default="ResNet50_vd")
    p.add_argument("--classes", type=int, default=8)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--shards", type=int, default=8)
    p.add_argument("--rows-per-file", type=int, default=256)
    p.add_argument("--student-shards", type=int, default=2,
                   help="the students' subset (the teacher's knowledge "
                        "of the remaining shards is what distillation "
                        "transfers — the reference's teacher was "
                        "likewise trained far beyond its students)")
    p.add_argument("--signal", type=float, default=0.45,
                   help="template amplitude: low enough that the "
                        "subset-trained hard-label student sits well "
                        "below the teacher (224px template tasks "
                        "saturate at the 0.7 default; measured on v5e: "
                        "0.45 + lr 0.02 learns steadily, 0.5 + lr 0.05 "
                        "is unstable, <=0.35 is stuck at chance)")
    p.add_argument("--teacher-epochs", type=int, default=12)
    p.add_argument("--student-epochs", type=int, default=12)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--lr", type=float, default=0.02)
    p.add_argument("--temperature", type=float, default=2.0)
    p.add_argument("--hard-weight", type=float, default=0.0)
    p.add_argument("--serve-topk", type=int, default=0,
                   help=">0: ALSO run the compressed-wire distilled "
                        "student and record its delta")
    p.add_argument("--mass-topk", default="",
                   help="comma list of K values: measure the trained "
                        "teacher's retained softmax mass at the distill "
                        "temperature for each K (the top-k wire's "
                        "quality-safety number) on the val shard")
    p.add_argument("--phase-timeout", type=int, default=2400)
    p.add_argument("--reuse-teacher", action="store_true",
                   help="skip teacher training when its checkpoint and "
                        "blog already exist in the workdir (iteration "
                        "aid; the recorded teacher_acc1 comes from the "
                        "reused run)")
    a = p.parse_args(argv)

    work = a.workdir
    os.makedirs(work, exist_ok=True)
    t0 = time.time()

    # -- data: full shards + a subset dir sharing the SAME val shard ----
    full = os.path.join(work, "data_full")
    marker = os.path.join(full, ".recipe")
    want = (f"signal={a.signal} classes={a.classes} shards={a.shards} "
            f"rows={a.rows_per_file} size={a.image_size}")
    if not os.path.exists(marker) or open(marker).read().strip() != want:
        shutil.rmtree(full, ignore_errors=True)
        run([sys.executable, "-m", TRAINER, "--data-dir", full,
             "--make-synthetic", str(a.shards),
             "--rows-per-file", str(a.rows_per_file),
             "--synthetic-signal", str(a.signal),
             "--model", a.model, "--num-classes", str(a.classes),
             "--image-size", str(a.image_size), "--epochs", "0",
             "--batch-size", str(a.batch_size)],
            log_path=os.path.join(work, "datagen.log"))
        with open(marker, "w") as f:
            f.write(want)
    sub = os.path.join(work, "data_subset")
    shutil.rmtree(sub, ignore_errors=True)
    os.makedirs(sub)
    shards = sorted(f for f in os.listdir(full) if f.startswith("train-"))
    for f in shards[: a.student_shards] + ["val.npz"]:
        os.link(os.path.join(full, f), os.path.join(sub, f))

    # -- teacher: full data, checkpointed -------------------------------
    ckpt = os.path.join(work, "teacher_ckpt")
    teacher_blog = os.path.join(work, "blog-teacher", "log_0.json")
    if a.reuse_teacher and os.path.isdir(ckpt) \
            and os.path.exists(teacher_blog):
        with open(teacher_blog) as f:
            teacher = json.load(f)["final"]
    else:
        shutil.rmtree(ckpt, ignore_errors=True)
        teacher = train(a, full, work, "teacher", a.teacher_epochs,
                        ckpt=ckpt)

    # -- student baseline: subset, hard labels only ---------------------
    alone = train(a, sub, work, "alone", a.student_epochs, seed=1)

    # -- distilled student: same subset/budget, served teacher logits ---
    from edl_tpu.utils import net
    port = net.free_port()
    tlog = os.path.join(work, "teacher_server.log")
    tsrv = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.distill.teacher_server",
         "--model", a.model, "--num-classes", str(a.classes),
         "--params", ckpt, "--host", "127.0.0.1", "--port", str(port),
         "--input-shape", f"{a.image_size},{a.image_size},3",
         "--max-batch", "64"]
        + (["--serve-topk", str(a.serve_topk)] if a.serve_topk else []),
        stdout=open(tlog, "wb"), stderr=subprocess.STDOUT, cwd=REPO)
    try:
        # the teacher restores params + binds before listening; FAIL
        # here (with its log) rather than letting the student's deadman
        # report a confusing connect-refused 60s later
        from edl_tpu.utils.net import is_endpoint_alive
        deadline = time.time() + 180
        while time.time() < deadline and not is_endpoint_alive(
                f"127.0.0.1:{port}"):
            if tsrv.poll() is not None:
                break
            time.sleep(0.5)
        if not is_endpoint_alive(f"127.0.0.1:{port}"):
            with open(tlog, "rb") as f:
                tail = f.read()[-3000:].decode(errors="replace")
            raise SystemExit(f"teacher server never came up:\n{tail}")
        distilled = train(a, sub, work, "distilled", a.student_epochs,
                          teachers=f"127.0.0.1:{port}",
                          topk=a.serve_topk, seed=1)
    finally:
        tsrv.kill()

    mass_points = None
    if a.mass_topk:
        ks = [int(k) for k in a.mass_topk.split(",") if k]
        mass_points = measure_topk_mass(a, ckpt, full, ks, a.temperature)

    delta = distilled["acc1"] - alone["acc1"]
    report = {
        "clause": "same student/subset/steps/LR; only the loss target "
                  "differs (hard labels vs served teacher logits) — the "
                  "reference's acc1 77.1->79.0 analogue "
                  "(/root/reference/README.md:70-72)",
        "teacher_acc1": teacher["acc1"],
        "alone_acc1": alone["acc1"],
        "distilled_acc1": distilled["acc1"],
        "distill_acc1_delta": round(delta, 5),
        "pass": delta > 0.0,
        "config": {"model": a.model, "image_size": a.image_size,
                   "classes": a.classes, "signal": a.signal,
                   "teacher_samples": a.shards * a.rows_per_file,
                   "student_samples": a.student_shards * a.rows_per_file,
                   "teacher_epochs": a.teacher_epochs,
                   "student_epochs": a.student_epochs,
                   "batch_size": a.batch_size, "lr": a.lr,
                   "temperature": a.temperature,
                   "hard_weight": a.hard_weight,
                   "serve_topk": a.serve_topk,
                   "wire": "TCP teacher_server + DistillReader inside "
                           "examples/imagenet_train --teachers"},
        "wall_s": round(time.time() - t0, 1),
    }
    if mass_points is not None:
        report["topk_mass"] = {
            "note": "fraction of the trained teacher's temperature-"
                    f"{a.temperature:g} softmax retained by the top K of "
                    f"{a.classes} classes (val shard; the top-k wire "
                    "ships exactly this mass). Guidance: pick K for "
                    ">=99% retained mass at the distill temperature.",
            "temperature": a.temperature,
            "classes": a.classes,
            "points": mass_points,
        }
    with open(a.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({k: report[k] for k in
                      ("teacher_acc1", "alone_acc1", "distilled_acc1",
                       "distill_acc1_delta", "pass")}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
