"""One-shot input-plane tuner: worker sweep + source x augment table.

Local sizing companion to the host input plane (edl_tpu/data/):
generates a synthetic JPEG dataset and

1. runs the decode + random-resized-crop + flip plane at each
   `--workers` count (the mp shared-memory loader sweep — pick
   `--loader-workers` / `EDL_TPU_LOADER_WORKERS` for a host), then
2. prints a `source ∈ {jpeg, npz, packed} × augment ∈ {host, device}`
   markdown table of HOST-side throughput.  Per-core framing
   (`img/s/core`, the bench extra `loader_imgs_per_sec_per_core`):
   multi-worker speedup is host-size-dependent, per-core rate is not —
   and on a 1-core host it is the only honest number.  The `device`
   rows ship raw bytes + the parent-drawn per-step seed
   (`DataLoader(emit_batch_seed=True)`); crop/flip/normalize run jitted
   on the accelerator (`ops/augment.py`), costing the host nothing —
   so a device row measures the whole host cost of that feed.  jpeg ×
   device is not a thing: decode is inherently host work — pack first
   (`python -m edl_tpu.data.packed_records pack`), which is exactly
   what the packed rows measure.

  python tools/loader_bench.py --n-imgs 256 --size 128 --batches 4
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/loader_bench.py` puts tools/
    sys.path.insert(0, REPO)  # on sys.path, not the repo root


def measure(loader, batches: int, batch_size: int) -> float:
    # Two warm-up batches: the second next() is what forks the mp
    # workers and builds the shm ring (the first only yields the
    # in-parent probe), so worker startup stays outside the timed
    # window; close() drains in-flight slots, leaving the pool warm.
    it = iter(loader.epoch(0))
    next(it)
    next(it, None)
    it.close()
    n = 0
    t0 = time.perf_counter()

    def forever():
        epoch = 1
        while True:
            yield from loader.epoch(epoch)
            epoch += 1

    for batch in forever():
        n += len(batch["label"])
        if n >= batches * batch_size:
            break
    dt = time.perf_counter() - t0
    loader.close()
    return n / dt


def source_augment_table(d: str, list_file: str, args) -> None:
    """The `source x augment` host-throughput table (markdown)."""
    from edl_tpu.data.image import JpegFileListSource, train_image_transform
    from edl_tpu.data.packed_records import (PackedSource, pack_jpeg_list)
    from edl_tpu.data.pipeline import (DataLoader, FileSource, random_crop,
                                       random_flip_lr)

    size = args.size
    # npz shards: crop-sized uint8 images (the host npz plane pads +
    # crops back to size, the device plane does the same on chip)
    rng = np.random.default_rng(0)
    npz_files = []
    per_shard = max(1, args.n_imgs // 2)
    for i in range(2):
        path = os.path.join(d, f"bench-{i}.npz")
        np.savez(path,
                 image=rng.integers(0, 256, size=(per_shard, size, size, 3),
                                    dtype=np.uint8),
                 label=rng.integers(0, 100, per_shard).astype(np.int32))
        npz_files.append(path)
    pack_path = os.path.join(d, "train.pack")
    pack_jpeg_list(list_file, d, pack_path, size=size,
                   batch_size=args.batch_size)

    host_t = (random_flip_lr, random_crop)
    jpeg_src = JpegFileListSource(list_file, root=d)
    combos = [
        ("jpeg", "host", lambda: DataLoader(
            jpeg_src, args.batch_size,
            sample_transforms=(train_image_transform(size),))),
        ("npz", "host", lambda: DataLoader(
            FileSource(npz_files), args.batch_size, transforms=host_t)),
        ("npz", "device", lambda: DataLoader(
            FileSource(npz_files), args.batch_size, emit_batch_seed=True)),
        ("packed", "host", lambda: DataLoader(
            PackedSource(pack_path), args.batch_size, transforms=host_t)),
        ("packed", "device", lambda: DataLoader(
            PackedSource(pack_path), args.batch_size,
            emit_batch_seed=True)),
    ]
    cores = os.cpu_count() or 1
    print(f"\nhost img/s by source x augment (crop {size}px, batch "
          f"{args.batch_size}, {cores} core(s); device rows = raw-byte "
          "gather + emitted seed, augmentation rides the accelerator)\n")
    print("| source | augment | host img/s | img/s/core | vs jpeg+host |")
    print("|--------|---------|-----------:|-----------:|-------------:|")
    base = None
    for src_name, aug, make in combos:
        rate = measure(make(), args.batches, args.batch_size)
        base = base if base is not None else rate
        # single-threaded production: per-core rate IS the rate
        print(f"| {src_name} | {aug} | {rate:.1f} | {rate:.1f} "
              f"| {rate / base:.2f}x |")
    print("| jpeg | device | — | — | pack first (packed rows) |")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tools/loader_bench.py")
    parser.add_argument("--n-imgs", type=int, default=256)
    parser.add_argument("--size", type=int, default=128,
                        help="crop size (224 = the real train plane)")
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--batches", type=int, default=4,
                        help="timed batches per worker count")
    parser.add_argument("--workers", type=int, nargs="+",
                        default=[0, 1, 2, 4])
    parser.add_argument("--decode-threads", type=int, default=0,
                        help="thread pool width for the workers=0 row")
    parser.add_argument("--no-table", action="store_true",
                        help="skip the source x augment table")
    parser.add_argument("--no-sweep", action="store_true",
                        help="skip the worker sweep")
    args = parser.parse_args(argv)

    from edl_tpu.data.image import (JpegFileListSource,
                                    make_synthetic_jpeg_dataset,
                                    train_image_transform)
    from edl_tpu.data.pipeline import DataLoader

    d = tempfile.mkdtemp(prefix="edl-loader-bench-")
    try:
        list_file = make_synthetic_jpeg_dataset(
            d, args.n_imgs, classes=100,
            hw=(args.size * 3 // 2, args.size * 2), seed=0)
        src = JpegFileListSource(list_file, root=d)
        print(f"host cores: {os.cpu_count()}  images: {args.n_imgs}  "
              f"crop: {args.size}px  batch: {args.batch_size}")
        if not args.no_sweep:
            print(f"{'workers':>8} {'img/s':>10} {'vs workers=0':>13}")
            base = None
            for w in args.workers:
                loader = DataLoader(
                    src, args.batch_size,
                    sample_transforms=(train_image_transform(args.size),),
                    decode_threads=args.decode_threads if w == 0 else 0,
                    num_workers=w)
                rate = measure(loader, args.batches, args.batch_size)
                base = base if base is not None else rate
                print(f"{w:>8} {rate:>10.1f} {rate / base:>12.2f}x")
        if not args.no_table:
            source_augment_table(d, list_file, args)
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
