"""One-shot input-plane tuner: img/s at loader workers in {0, 1, 2, 4}.

Local sizing companion to the mp shared-memory loader
(edl_tpu/data/mp_loader.py): generates a synthetic JPEG dataset, runs
the decode + random-resized-crop + flip plane at each worker count and
prints a small table, so picking `--loader-workers` /
`EDL_TPU_LOADER_WORKERS` for a host is one command instead of a sweep
by hand.  workers=0 is the inline path; pass --decode-threads to also
see the thread-pool variant at width 0.

  python tools/loader_bench.py --n-imgs 256 --size 128 --batches 4
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/loader_bench.py` puts tools/
    sys.path.insert(0, REPO)  # on sys.path, not the repo root


def measure(loader, batches: int, batch_size: int) -> float:
    # Two warm-up batches: the second next() is what forks the mp
    # workers and builds the shm ring (the first only yields the
    # in-parent probe), so worker startup stays outside the timed
    # window; close() drains in-flight slots, leaving the pool warm.
    it = iter(loader.epoch(0))
    next(it)
    next(it, None)
    it.close()
    n = 0
    t0 = time.perf_counter()

    def forever():
        epoch = 1
        while True:
            yield from loader.epoch(epoch)
            epoch += 1

    for batch in forever():
        n += len(batch["label"])
        if n >= batches * batch_size:
            break
    dt = time.perf_counter() - t0
    loader.close()
    return n / dt


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tools/loader_bench.py")
    parser.add_argument("--n-imgs", type=int, default=256)
    parser.add_argument("--size", type=int, default=128,
                        help="crop size (224 = the real train plane)")
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--batches", type=int, default=4,
                        help="timed batches per worker count")
    parser.add_argument("--workers", type=int, nargs="+",
                        default=[0, 1, 2, 4])
    parser.add_argument("--decode-threads", type=int, default=0,
                        help="thread pool width for the workers=0 row")
    args = parser.parse_args(argv)

    from edl_tpu.data.image import (JpegFileListSource,
                                    make_synthetic_jpeg_dataset,
                                    train_image_transform)
    from edl_tpu.data.pipeline import DataLoader

    d = tempfile.mkdtemp(prefix="edl-loader-bench-")
    try:
        list_file = make_synthetic_jpeg_dataset(
            d, args.n_imgs, classes=100,
            hw=(args.size * 3 // 2, args.size * 2), seed=0)
        src = JpegFileListSource(list_file, root=d)
        print(f"host cores: {os.cpu_count()}  images: {args.n_imgs}  "
              f"crop: {args.size}px  batch: {args.batch_size}")
        print(f"{'workers':>8} {'img/s':>10} {'vs workers=0':>13}")
        base = None
        for w in args.workers:
            loader = DataLoader(
                src, args.batch_size,
                sample_transforms=(train_image_transform(args.size),),
                decode_threads=args.decode_threads if w == 0 else 0,
                num_workers=w)
            rate = measure(loader, args.batches, args.batch_size)
            base = base if base is not None else rate
            print(f"{w:>8} {rate:>10.1f} {rate / base:>12.2f}x")
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
