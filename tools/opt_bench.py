"""Fused-optimizer tuner: optimizer x impl x size sweep.

Local sizing companion to the fused optimizer path
(edl_tpu/train/fused_opt.py, doc/design_step.md): one seeded parameter
world per size, stepped through every {sgdm, adam} x {xla (the optax
chain), fused-fp32, fused-int8} combination, printed as a markdown
table of

  update ms/step | resident opt-state bytes | bytes vs xla | parity

Seeded-exact: params, grads and the bucket plan are functions of
--seed, so every non-timing column is stable across runs on the same
machine. On the CPU harness the fused columns time the jitted XLA
fallback expression (the Pallas kernel is a TPU/interpret path), so
ms columns calibrate schedule cost, not a VMEM win; the bytes and
parity columns are exact either way. Parity = fused-fp32 params
bitwise vs the optax chain after --steps steps (sgdm; adam to float
tolerance), the same gate CI pins via
`python -m edl_tpu.train.fused_opt smoke`.

  python tools/opt_bench.py --sizes 0.5,2 --steps 10
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/opt_bench.py` puts tools/
    sys.path.insert(0, REPO)  # on sys.path, not the repo root


def build_world(seed: int, size_m: float):
    """A ragged ~size_m-million-param fp32 tree + matching grads."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    n = int(size_m * 1e6)
    # a few big kernels + odd-sized tails so bucketing/padding engage
    shapes = []
    per = max(n // 4, 1)
    cols = 1024
    while n > 0:
        rows = max(min(per, n) // cols, 1)
        shapes.append((rows, cols))
        n -= rows * cols
    shapes += [(129,), (33,)]

    def leaf(shape):
        return jnp.asarray(rng.normal(0, 0.02, size=shape)
                           .astype(np.float32))

    params = {f"w{i}": leaf(s) for i, s in enumerate(shapes)}
    grads = {k: leaf(v.shape) for k, v in params.items()}
    return params, grads


def make_tx(optimizer: str, impl: str, lr: float):
    import optax

    from edl_tpu.train import fused_opt as fo

    if impl == "xla":
        if optimizer == "sgdm":
            return optax.chain(optax.add_decayed_weights(1e-4),
                               optax.sgd(lr, momentum=0.9))
        return optax.adamw(lr, weight_decay=1e-4)
    mode = {"fused-fp32": "fp32", "fused-int8": "int8"}[impl]
    return fo.make_fused_tx(optimizer, lr, mode, weight_decay=1e-4)


def run_combo(optimizer: str, impl: str, params, grads, steps: int,
              lr: float):
    import jax
    import jax.numpy as jnp

    from edl_tpu.train import fused_opt as fo
    from edl_tpu.train.state import TrainState

    tx = make_tx(optimizer, impl, lr)
    # own copy: the donated step consumes its state buffers, and the
    # caller reuses `params` across combos
    state = TrainState.create(apply_fn=None,
                              params=jax.tree.map(jnp.copy, params),
                              tx=tx)
    step = jax.jit(lambda s, g: s.apply_gradients(grads=g),
                   donate_argnums=(0,))
    state = step(state, grads)  # compile + warm
    jax.block_until_ready(jax.tree.leaves(state))
    t0 = time.perf_counter()
    for _ in range(steps):
        state = step(state, grads)
    jax.block_until_ready(jax.tree.leaves(state))
    ms = (time.perf_counter() - t0) / steps * 1e3
    return ms, fo.opt_state_bytes(state.opt_state), state.params


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tools/opt_bench.py")
    parser.add_argument("--sizes", default="0.5,2",
                        help="comma list of model sizes, millions of "
                             "params")
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--lr", type=float, default=0.1)
    args = parser.parse_args(argv)

    import jax
    import jax.numpy as jnp

    sizes = [float(s) for s in args.sizes.split(",") if s]
    impls = ("xla", "fused-fp32", "fused-int8")
    print(f"devices={len(jax.devices())} "
          f"backend={jax.default_backend()} seed={args.seed} "
          f"steps={args.steps}")
    print("| size | optimizer | impl | update ms | opt bytes "
          "| bytes vs xla | parity |")
    print("|---|---|---|---|---|---|---|")
    ok = True
    for size in sizes:
        params, grads = build_world(args.seed, size)
        for optimizer in ("sgdm", "adam"):
            base_bytes = None
            ref_params = None
            for impl in impls:
                ms, nbytes, out = run_combo(optimizer, impl, params,
                                            grads, args.steps, args.lr)
                if impl == "xla":
                    base_bytes, ref_params = nbytes, out
                    cut, parity = "1.00x", "ref"
                else:
                    cut = f"{base_bytes / nbytes:.2f}x"
                    err = max(float(jnp.max(jnp.abs(a - b)))
                              for a, b in zip(jax.tree.leaves(ref_params),
                                              jax.tree.leaves(out)))
                    if impl == "fused-fp32":
                        # sgdm is bitwise; adam float-tolerance
                        tol = 0.0 if optimizer == "sgdm" else 1e-4
                        good = err <= tol
                    else:
                        good = np.isfinite(err)  # quantized: smoke
                        # gate owns the loss envelope, not a param pin
                    ok = ok and good
                    parity = (f"err={err:.1e}"
                              + ("" if good else " FAIL"))
                print(f"| {size}M | {optimizer} | {impl} "
                      f"| {ms:.2f} | {nbytes} | {cut} | {parity} |")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
