"""Policy x curve-shape sweep for the autoscaler decision plane.

Runs every (policy, scaling-curve) pair on the deterministic
`SimCluster` and prints ONE markdown table: how many ticks until the
allocation converged, how far from the oracle it landed, how many
resizes it spent, the stop-resume downtime it paid (at the measured
`elastic_downtime_s` price), and whether it stayed put afterwards.
Tuning `--gain-threshold` / `--cooldown` for a deployment is one
command: widen the threshold until post-convergence resizes hit 0,
then shrink cooldown until the downtime column says stop.

  python tools/scaler_bench.py --downtime-s 1.2 --ticks 200
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/scaler_bench.py` puts tools/
    sys.path.insert(0, REPO)  # on sys.path, not the repo root


def curve_menu():
    from edl_tpu.scaler.simulator import concave, flat, knee, linear
    return (("concave a=0.3", concave(100.0, 0.3), 1),
            ("concave a=0.6", concave(100.0, 0.6), 2),
            ("flat", flat(100.0), 4),
            ("knee k=4", knee(100.0, 4), 7),
            ("linear", linear(100.0), 1))


def run_throughput(curve, start, args):
    from edl_tpu.scaler.policy import ThroughputPolicy
    from edl_tpu.scaler.simulator import SimCluster, SimJob, run_policy
    sim = SimCluster([SimJob("j", curve, 1, args.max_nodes, nodes=start,
                             noise=args.noise)],
                     tick_s=args.tick_s, downtime_s=args.downtime_s,
                     seed=args.seed)
    policy = ThroughputPolicy(gain_threshold=args.gain_threshold,
                              cooldown_s=args.cooldown,
                              horizon_s=args.horizon)
    out = run_policy(sim, policy, ticks=args.ticks, settle_ticks=50)
    return out["jobs"]["j"]


def run_fairshare(curve, start, args):
    """The swept curve shares a budget with one fixed linear job — the
    competitive setting FairShare exists for."""
    from edl_tpu.scaler.policy import FairSharePolicy
    from edl_tpu.scaler.simulator import (SimCluster, SimJob, linear,
                                          run_policy)
    jobs = [SimJob("j", curve, 1, args.max_nodes, nodes=start,
                   noise=args.noise),
            SimJob("rival", linear(50.0), 1, args.max_nodes, nodes=1,
                   noise=args.noise)]
    sim = SimCluster(jobs, tick_s=args.tick_s,
                     downtime_s=args.downtime_s, seed=args.seed)
    policy = FairSharePolicy(args.budget,
                             gain_threshold=args.gain_threshold,
                             cooldown_s=args.cooldown,
                             horizon_s=args.horizon)
    out = run_policy(sim, policy, ticks=args.ticks, settle_ticks=50)
    job = dict(out["jobs"]["j"])
    job["oracle_nodes"] = sim.oracle_fair_share(args.budget)["j"]
    job["gap_nodes"] = abs(job["final_nodes"] - job["oracle_nodes"])
    return job


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tools/scaler_bench.py")
    parser.add_argument("--ticks", type=int, default=200)
    parser.add_argument("--tick-s", type=float, default=5.0)
    parser.add_argument("--downtime-s", type=float, default=1.2,
                        help="per-resize stop-resume price (bench.py "
                             "elastic_downtime_s)")
    parser.add_argument("--cooldown", type=float, default=15.0)
    parser.add_argument("--horizon", type=float, default=60.0)
    parser.add_argument("--gain-threshold", type=float, default=0.05)
    parser.add_argument("--noise", type=float, default=0.01)
    parser.add_argument("--max-nodes", type=int, default=8)
    parser.add_argument("--budget", type=int, default=10,
                        help="fairshare: shared node budget")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--ladder", default=None, metavar="BENCH_JSON",
                        help="price resizes from a bench.py artifact's "
                             "measured downtime extras instead of "
                             "--downtime-s (shared with serve_bench "
                             "and fleet_bench: one artifact, one "
                             "price list)")
    args = parser.parse_args(argv)

    if args.ladder:
        from edl_tpu.scaler.fleet import DowntimeLadder
        ladder = DowntimeLadder.from_artifact(args.ladder)
        if ladder is None:
            print(f"unreadable ladder artifact: {args.ladder}",
                  file=sys.stderr)
            return 2
        # SimCluster charges ONE price per resize; a scheduled resize
        # is a reform (the grow direction — shrinks ride the cheaper
        # adopt path that this single-knob sim cannot split out)
        args.downtime_s = ladder.reform_s
        print(f"ladder={ladder.name}: downtime_s={args.downtime_s}")

    print(f"ticks={args.ticks} tick={args.tick_s:.0f}s "
          f"downtime={args.downtime_s}s cooldown={args.cooldown:.0f}s "
          f"eps={args.gain_threshold} noise={args.noise} "
          f"(converge = tick of the LAST resize; post = resizes in the "
          f"trailing 50-tick window, the oscillation alarm)")
    print("| policy | curve | start | final | oracle | gap | converge "
          "(ticks) | resizes | downtime s | post |")
    print("|---|---|---|---|---|---|---|---|---|---|")
    for policy_name, runner in (("throughput", run_throughput),
                                ("fairshare", run_fairshare)):
        for curve_name, curve, start in curve_menu():
            r = runner(curve, start, args)
            print(f"| {policy_name} | {curve_name} | {start} "
                  f"| {r['final_nodes']} | {r['oracle_nodes']} "
                  f"| {r['gap_nodes']} | {r['decisions_to_converge']} "
                  f"| {r['resizes']} | {r['downtime_paid_s']} "
                  f"| {r['post_convergence_resizes']} |")
    return 0


if __name__ == "__main__":
    sys.exit(main())
