"""North-star resize clause at MODEL scale on the real chip.

Runs the BASELINE.md clause — ResNet50_vd at 224 px surviving >= 2
elastic resize events with < 1% acc1 loss vs an unresized run — with the
flagship model on real TPU, mirroring
tests/test_imagenet_multipod.py::test_two_resizes_under_one_percent_acc_loss
(which proves the same invariant at ResNetTiny/16px scale on a CPU
world). Each resize is a stop-resume generation under the REAL elastic
launcher (store server + collective.launch + checkpoint restore +
--schedule-epochs pinning every phase to one cosine horizon) — the
reference's resize mechanism IS stop-resume (doc/edl_collective_design_
doc.md:10-16: on membership change all trainers are killed and re-formed
from the checkpoint), so generation boundaries are exactly what a
world-size change exercises; with one chip the re-formed world keeps
size 1, and the world-size-varying half of the clause is proven by the
CPU test above.

Writes NORTHSTAR_r{round}.json:
    {"straight_acc1": ..., "resized_acc1": ..., "delta": ...,
     "phases": [...], "config": {...}}

Usage (on the TPU host):  python tools/northstar_tpu.py --out NORTHSTAR_r4.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/northstar_tpu.py` puts tools/
    sys.path.insert(0, REPO)  # on sys.path, not the repo root
TRAINER = "edl_tpu.examples.imagenet_train"


def run(cmd, env=None, timeout=1800, log_path=None):
    log = open(log_path, "wb") if log_path else None
    try:
        proc = subprocess.run(cmd, env=env, timeout=timeout,
                              stdout=log or subprocess.PIPE,
                              stderr=subprocess.STDOUT, cwd=REPO)
    finally:
        if log:
            log.close()
    if proc.returncode != 0:
        tail = ""
        if log_path and os.path.exists(log_path):
            with open(log_path, "rb") as f:
                tail = f.read()[-4000:].decode(errors="replace")
        raise SystemExit(f"command failed ({proc.returncode}): "
                         f"{' '.join(cmd)}\n{tail}")
    return proc


def trainer_args(a, work, epochs, schedule_epochs, blog, ckpt=None):
    args = [sys.executable, "-m", TRAINER,
            "--data-dir", os.path.join(work, "data"),
            "--model", "ResNet50_vd", "--num-classes", str(a.classes),
            "--image-size", "224", "--epochs", str(epochs),
            "--batch-size", str(a.batch_size), "--warmup-epochs", "1",
            "--lr-strategy", "cosine", "--lr", str(a.lr), "--no-augment",
            "--label-smoothing", "0", "--bf16",
            "--benchmark-log", blog]
    if schedule_epochs:
        args += ["--schedule-epochs", str(schedule_epochs)]
    if ckpt:
        args += ["--ckpt-dir", ckpt]
    return args


def launcher_run(a, work, tag, epochs, schedule_epochs, ckpt, port):
    """One elastic GENERATION: the real launcher forms the world, spawns
    the trainer, and the trainer resumes the shared checkpoint."""
    blog = os.path.join(work, f"blog-{tag}")
    env = dict(os.environ)
    env["EDL_TPU_JOB_ID"] = f"northstar-{tag}"
    cmd = [sys.executable, "-m", "edl_tpu.collective.launch",
           "--store", f"127.0.0.1:{port}", "--nodes-range", "1:1",
           "--log-dir", os.path.join(work, f"log-{tag}"), "--"]
    cmd += trainer_args(a, work, epochs, schedule_epochs, blog, ckpt)
    run(cmd, env=env, timeout=a.phase_timeout,
        log_path=os.path.join(work, f"{tag}.launch.log"))
    with open(os.path.join(blog, "log_0.json")) as f:
        return json.load(f)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tools/northstar_tpu.py")
    p.add_argument("--out", default="NORTHSTAR_r4.json")
    p.add_argument("--workdir", default="/tmp/edl_northstar")
    p.add_argument("--classes", type=int, default=8)
    p.add_argument("--shards", type=int, default=6)
    p.add_argument("--rows-per-file", type=int, default=256)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--label-noise", type=float, default=0.06,
                   help="flipped-label fraction in the synthetic data: "
                        "pins the val acc1 ceiling at ~1-x (template "
                        "tasks at 224px are separable at any SNR, so "
                        "without it both runs saturate at 1.0 and the "
                        "<1%% comparison is vacuous)")
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--phase-timeout", type=int, default=1800)
    a = p.parse_args(argv)
    if a.epochs < 3:
        # mid1 < mid2 < epochs must hold or a phase trains zero epochs
        # and the run dies late with an opaque missing-'final' error
        raise SystemExit("--epochs must be >= 3 (two resize points need "
                         "three non-empty phases)")

    work = a.workdir
    os.makedirs(work, exist_ok=True)
    # a prior invocation's checkpoint would make p1 resume past its stop
    # epoch and train nothing; phase evidence must come from THIS run
    import shutil
    for stale in ("ckpt", "blog-straight", "blog-p1", "blog-p2",
                  "blog-p3"):
        shutil.rmtree(os.path.join(work, stale), ignore_errors=True)

    # data once (deterministic; last shard is val.npz). Regenerate if a
    # prior invocation used ANY different data parameter (marker file
    # records the full recipe — reusing stale data would make the
    # report's config block misdescribe what was trained on).
    marker = os.path.join(work, "data", ".data_recipe")
    want = (f"noise={a.label_noise:.4f} classes={a.classes} "
            f"shards={a.shards} rows={a.rows_per_file}")
    have = (open(marker).read().strip()
            if os.path.exists(marker) else None)
    if not os.path.exists(os.path.join(work, "data", "val.npz")) \
            or have != want:
        shutil.rmtree(os.path.join(work, "data"), ignore_errors=True)
        run([sys.executable, "-m", TRAINER,
             "--data-dir", os.path.join(work, "data"),
             "--make-synthetic", str(a.shards),
             "--rows-per-file", str(a.rows_per_file),
             "--synthetic-label-noise", str(a.label_noise),
             "--model", "ResNet50_vd", "--num-classes", str(a.classes),
             "--image-size", "224", "--epochs", "0",
             "--batch-size", str(a.batch_size)],
            log_path=os.path.join(work, "datagen.log"))
        with open(marker, "w") as f:
            f.write(want)

    # straight run: no launcher, no resumes, same horizon
    t0 = time.time()
    blog_s = os.path.join(work, "blog-straight")
    run(trainer_args(a, work, a.epochs, 0, blog_s),
        timeout=a.phase_timeout,
        log_path=os.path.join(work, "straight.log"))
    with open(os.path.join(blog_s, "log_0.json")) as f:
        straight = json.load(f)

    # elastic run: store + 3 launcher generations (2 resize events),
    # all phases riding ONE cosine horizon via --schedule-epochs
    from edl_tpu.utils import net
    port = net.free_port()
    store = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.coord.server", "--port", str(port)],
        stdout=open(os.path.join(work, "store.log"), "wb"),
        stderr=subprocess.STDOUT, cwd=REPO)
    try:
        from edl_tpu.coord.client import StoreClient
        deadline = time.time() + 20
        while True:  # poll readiness (a bare sleep races slow startups)
            try:
                # ping() returns False (no raise) on a dead endpoint
                if StoreClient(f"127.0.0.1:{port}").ping():
                    break
            except Exception:
                pass
            if time.time() > deadline:
                raise SystemExit("store server did not come up")
            time.sleep(0.25)
        ckpt = os.path.join(work, "ckpt")
        mid1 = max(1, a.epochs // 2)
        mid2 = max(mid1 + 1, a.epochs - 1)
        phases = []
        for tag, epochs in (("p1", mid1), ("p2", mid2), ("p3", a.epochs)):
            blog = launcher_run(a, work, tag, epochs, a.epochs, ckpt, port)
            phases.append({"tag": tag, "stop_epoch": epochs,
                           "epochs_trained":
                               [e["epoch"] for e in blog["epochs"]],
                           "final": blog["final"]})
        resized = phases[-1]["final"]
    finally:
        store.kill()

    # every phase must have RESUMED (trained only its own epochs) — a
    # silent restore failure would make the comparison vacuous
    for ph, lo in zip(phases, [0, mid1, mid2]):
        if not ph["epochs_trained"] or ph["epochs_trained"][0] != lo:
            raise SystemExit(f"phase {ph['tag']} did not resume: trained "
                             f"{ph['epochs_trained']}, expected start {lo}")

    acc_s = straight["final"]["acc1"]
    acc_r = resized["acc1"]
    # A straight run pinned at 1.0 makes the <1% comparison vacuous (a
    # restore bug that re-memorizes still matches); require the straight
    # run to land BELOW the ceiling so the delta is discriminating.
    saturated = acc_s >= 1.0
    ceiling = 1.0 - a.label_noise
    # "learned the task" scales the original 0.8 bar by the configured
    # ceiling (at --label-noise 0.25 a perfect run tops out at 0.75, so
    # a fixed 0.8 would fail perfect runs; at noise 0 this stays 0.8)
    learned = acc_s > 0.8 * ceiling
    report = {
        "clause": "ResNet50_vd 224px, >=2 resize events, <1% acc1 loss",
        "straight_acc1": acc_s,
        "resized_acc1": acc_r,
        "delta": round(abs(acc_s - acc_r), 5),
        "saturated": saturated,
        "pass": (abs(acc_s - acc_r) < 0.01 and learned
                 and not saturated),
        "phases": phases,
        "straight": straight["final"],
        "config": {"model": "ResNet50_vd", "image_size": 224,
                   "classes": a.classes, "batch_size": a.batch_size,
                   "epochs": a.epochs, "lr": a.lr,
                   "label_noise": a.label_noise,
                   "val_acc_ceiling": round(ceiling, 4),
                   "samples": a.shards * a.rows_per_file,
                   "resize_mechanism":
                       "stop-resume generations under collective.launch "
                       "(world stays 1 on a single chip; world-varying "
                       "half proven by test_imagenet_multipod.py on a "
                       "CPU world)"},
        "wall_s": round(time.time() - t0, 1),
    }
    with open(a.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({k: report[k] for k in
                      ("straight_acc1", "resized_acc1", "delta", "pass")}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
