"""Policy x arrival-trace sweep for the serving-elasticity plane.

Runs every (ServingPolicy variant, arrival trace) pair on the
deterministic `SimServingPool` and prints ONE markdown table: SLO
attainment %, how many ticks after the load change the SLO was last
violated (the reaction), resizes spent, the final pool vs the steady
oracle, and whether the pool stayed put afterwards. Tuning the SLO
knobs for a deployment is one command: tighten `breach_ticks` until
the reaction column says stop, then widen `idle_ticks` until
post-convergence resizes hit 0.

  python tools/serve_bench.py --slo-p95-ms 250 --ticks 200
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/serve_bench.py` puts tools/
    sys.path.insert(0, REPO)  # on sys.path, not the repo root


def trace_menu(args):
    from edl_tpu.scaler.simulator import burst, steady, step
    return ((steady(args.lam * 2), None),
            (step(args.lam, 4.0, at=40), 40),
            (step(args.lam, 8.0, at=40), 40),
            (burst(args.lam, 4.0, at=40, length=25), 40))


def policy_menu(args):
    from edl_tpu.scaler.serving import ServingConfig, ServingPolicy

    def make(name, **kw):
        base = dict(slo_p95_ms=args.slo_p95_ms, breach_ticks=2,
                    idle_ticks=5, cooldown_s=args.cooldown,
                    max_teachers=args.max_teachers)
        base.update(kw)
        return name, lambda: ServingPolicy(ServingConfig(**base))

    return (make("default"),
            make("aggressive", breach_ticks=1, cooldown_s=5.0,
                 grow_max_factor=4.0),
            make("conservative", breach_ticks=4, idle_ticks=10,
                 cooldown_s=30.0))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tools/serve_bench.py")
    parser.add_argument("--ticks", type=int, default=200)
    parser.add_argument("--tick-s", type=float, default=1.0)
    parser.add_argument("--lam", type=float, default=100.0,
                        help="base arrival rate rows/sec")
    parser.add_argument("--teacher-rate", type=float, default=250.0,
                        help="one teacher's service rate rows/sec")
    parser.add_argument("--slo-p95-ms", type=float, default=250.0)
    parser.add_argument("--cooldown", type=float, default=15.0)
    parser.add_argument("--noise", type=float, default=0.01)
    parser.add_argument("--max-teachers", type=int, default=16)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--spawn-delay-ticks", type=int, default=2,
                        help="ticks before a grown teacher is ready")
    parser.add_argument("--ladder", default=None, metavar="BENCH_JSON",
                        help="derive the spawn delay from a bench.py "
                             "artifact's measured stop-resume downtime "
                             "(a teacher spawn is a cold start; shared "
                             "with scaler_bench and fleet_bench)")
    args = parser.parse_args(argv)

    from edl_tpu.scaler.simulator import SimServingPool, run_serving_policy

    if args.ladder:
        import math

        from edl_tpu.scaler.fleet import DowntimeLadder
        ladder = DowntimeLadder.from_artifact(args.ladder)
        if ladder is None:
            print(f"unreadable ladder artifact: {args.ladder}",
                  file=sys.stderr)
            return 2
        args.spawn_delay_ticks = max(
            1, math.ceil(ladder.stop_resume_s / args.tick_s))
        print(f"ladder={ladder.name}: spawn_delay_ticks="
              f"{args.spawn_delay_ticks}")

    print(f"ticks={args.ticks} tick={args.tick_s:g}s "
          f"slo={args.slo_p95_ms:g}ms teacher_rate={args.teacher_rate:g} "
          f"rows/s noise={args.noise} (react = ticks from the load "
          f"change to the LAST SLO violation; post = resizes in the "
          f"trailing 50-tick window, the oscillation alarm)")
    print("| policy | trace | attain % | react (ticks) | resizes "
          "| final | oracle | post |")
    print("|---|---|---|---|---|---|---|---|")
    for policy_name, make_policy in policy_menu(args):
        for trace, change_at in trace_menu(args):
            pool = SimServingPool(
                "svc", trace, teacher_rate=args.teacher_rate,
                slo_p95_ms=args.slo_p95_ms, teachers=1,
                max_teachers=args.max_teachers, tick_s=args.tick_s,
                spawn_delay_ticks=args.spawn_delay_ticks,
                noise=args.noise, seed=args.seed)
            out = run_serving_policy(pool, make_policy(),
                                     ticks=args.ticks, settle_ticks=50)
            react = (max(0, out["last_violation_tick"] - change_at)
                     if change_at is not None
                     else out["last_violation_tick"])
            oracle = pool.oracle_teachers(trace(args.ticks))
            print(f"| {policy_name} | {out['trace']} "
                  f"| {100 * out['slo_attainment']:.1f} | {react} "
                  f"| {out['resizes']} | {out['final_teachers']} "
                  f"| {oracle} | {out['post_convergence_resizes']} |")
    return 0


if __name__ == "__main__":
    sys.exit(main())
