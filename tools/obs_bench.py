"""Observability-plane on/off sweep: what does watching cost?

Runs a synthetic instrumented step loop (the per-step work a fully
instrumented TrainLoop/Batcher performs: counter + gauge + histogram
update, an optional timeline span) under every combination of
{metrics off/on} x {trace off/on} and prints a markdown table of
per-step cost, plus scrape/render cost as the registry population
grows. Pure stdlib + the obs plane — runs on a scheduler node.

  python tools/obs_bench.py --steps 20000
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from edl_tpu.obs import metrics, trace  # noqa: E402
from edl_tpu.utils import timeline as tl  # noqa: E402


def step_loop(steps: int, *, with_metrics: bool, with_span: bool) -> float:
    """Per-step seconds of the instrumentation alone (the simulated
    step body is one float multiply — the delta between variants is
    the observability cost)."""
    reg = metrics.Registry()
    c = reg.counter("sweep_rows")
    g = reg.gauge("sweep_depth")
    h = reg.histogram("sweep_step_ms", metrics.LOG_BUCKETS_MS)
    t = tl.timeline("sweep")
    x = 1.0
    t0 = time.perf_counter()
    for i in range(steps):
        x *= 1.0000001
        if with_metrics:
            c.inc(64)
            g.set(i & 7)
            h.observe(7.3)
        if with_span:
            with t.span("step"):
                pass
    dt = time.perf_counter() - t0
    assert x > 0
    return dt / steps


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tools/obs_bench.py")
    parser.add_argument("--steps", type=int, default=20000)
    args = parser.parse_args(argv)

    tmp = tempfile.mkdtemp(prefix="edl-obs-bench-")
    rows = []
    try:
        for metrics_on in (False, True):
            for trace_on in (False, True):
                if trace_on:
                    os.environ["EDL_TPU_TRACE"] = tmp
                else:
                    os.environ.pop("EDL_TPU_TRACE", None)
                trace.reconfigure()
                per_step = step_loop(args.steps,
                                     with_metrics=metrics_on,
                                     with_span=trace_on)
                rows.append((metrics_on, trace_on, per_step))
    finally:
        os.environ.pop("EDL_TPU_TRACE", None)
        trace.reconfigure()
        shutil.rmtree(tmp, ignore_errors=True)

    base = rows[0][2]
    print(f"observability on/off sweep ({args.steps} steps; baseline = "
          "uninstrumented loop body)\n")
    print("| metrics | trace | per-step us | delta us |")
    print("|---------|-------|------------:|---------:|")
    for metrics_on, trace_on, per_step in rows:
        print(f"| {'on' if metrics_on else 'off':7s} "
              f"| {'on' if trace_on else 'off':5s} "
              f"| {per_step * 1e6:11.3f} "
              f"| {(per_step - base) * 1e6:8.3f} |")

    print("\nscrape render cost vs registry population:\n")
    print("| sources | render ms |")
    print("|--------:|----------:|")
    for n_sources in (1, 8, 32, 128):
        reg = metrics.Registry()
        reg.histogram("pop_lat_ms", metrics.LOG_BUCKETS_MS).observe(3.0)
        for i in range(n_sources):
            reg.register_stats(f"src{i}", lambda: {
                "served_rows": 123456, "queue_depth": 2, "util": 0.73,
                "latency_hist_ms": {"5.0": 10, "inf": 1}})
        reg.render()  # warm
        t0 = time.perf_counter()
        for _ in range(10):
            reg.render()
        print(f"| {n_sources:7d} | {(time.perf_counter() - t0) * 100:9.3f} |")
    return 0


if __name__ == "__main__":
    sys.exit(main())
