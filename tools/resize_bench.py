"""Resize restore-path sweep: state size x {disk, p2p} x grow/shrink.

Companion to the state-migration plane (collective/migration.py): for
each state size it saves a dp-sharded state from a SOURCE mesh, then
times re-assembling it onto a LARGER (grow) and SMALLER (shrink) target
mesh through each transport:

- ``disk``      — the stop-resume recipe: chunk files + index on disk,
                  `restore_sharded`'s mmap region reads;
- ``disk-rep``  — the legacy replicated recipe: one flax msgpack blob,
                  full deserialize (what small-model jobs pay);
- ``p2p``       — a live donor serving the SAME chunks from memory over
                  the binary tensor wire, assembled by the SAME
                  resharding planner (`restore_from_peers`).

The reported seconds are the restore TERM of the resize downtime (the
part `TrainLoop.try_restore` owns); surviving pods under p2p skip even
this by adopting in place — see `elastic_downtime_p2p_s` in bench.py.
Bytes are what the transport actually moved. Run on any host:

  python tools/resize_bench.py --sizes-mb 8 64 256

With ``EDL_TPU_TRACE`` set (obs plane), each p2p row also gets a
phase-breakdown column derived from the restore's spans — how much of
the restore term was chunk transfer (``migrate.fetch``) vs planner/
assembly, and how many chunks crossed the wire.

``--worlds`` adds the MULTI-PROCESS world axis: real subprocess worlds
(launcher pods under a JobServer) driven through scripted grow/shrink
resizes, one row per (direction, transport):

- ``stop-resume``     — a restarted process's full price: respawn +
                        re-import + re-jit + peer/disk restore (the
                        grown pod of the reform demo);
- ``p2p-adopt``       — a survivor whose device set is unchanged
                        adopts in place (``elastic_demo --resize-p2p``);
- ``in-place-reform`` — a survivor whose device world CHANGED walks
                        the reform state machine (quiesce-seal ->
                        mesh-reform -> peer-restore -> re-jit) without
                        leaving its process (``--resize-reform``);
                        warm = shape already compiled, cold = first
                        sight of the shape (exactly one compile).

Each demo self-audits and this tool refuses to print rows from a
failed run. Sequential by design — the bench host has one core.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# virtual CPU devices for the mesh sweep — before any jax import
os.environ.setdefault("EDL_TPU_TEST_DEVICES", "8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_NUM_CPU_DEVICES",
                      os.environ["EDL_TPU_TEST_DEVICES"])
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count="
        + os.environ["EDL_TPU_TEST_DEVICES"]).strip()

from edl_tpu.obs import trace  # noqa: E402 — stdlib-only, jax-free


def _mesh(n: int):
    import jax
    import numpy as np
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:n]), ("dp",))


def build_state(size_mb: float, mesh):
    """A layer-ish pytree of the requested footprint, dp-sharded over
    the mesh (first axis divisible by every mesh size in the sweep)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_leaves = 16
    rows = 64
    floats = int(size_mb * 2**20 / 4)
    cols = max(1, floats // (n_leaves * rows))
    rng = np.random.default_rng(0)
    sharding = NamedSharding(mesh, P("dp"))
    return {f"layer_{i}": jax.device_put(
        rng.normal(size=(rows, cols)).astype(np.float32), sharding)
        for i in range(n_leaves)}


def target_like(state, mesh):
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(mesh, P("dp"))
    return {k: jax.device_put(np.zeros(v.shape, np.float32), sharding)
            for k, v in state.items()}


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def _phase_breakdown() -> str | None:
    """Per-phase split of the last p2p restore, read from the obs span
    ring (None when tracing is off): wire share of the restore term +
    chunk count — the column ROADMAP item 2's multi-host budget reads."""
    if not trace.enabled():
        return None
    restores = trace.finished("resize.restore_peers")
    if not restores:
        return None
    total = restores[-1].get("dur", 0.0)
    fetches = [s for s in trace.finished("migrate.fetch")
               if s["tid"] == restores[-1]["tid"]]
    wire = sum(s.get("dur", 0.0) for s in fetches)
    if total <= 0:
        return None
    # fetches run on the restore THREAD POOL, so their summed seconds
    # legitimately exceed the wall-clock span when reads overlap —
    # report the sum with a Σ so the column reads as thread-seconds
    return (f"wire Σ{100 * wire / total:.0f}% of wall "
            f"({len(fetches)} chunks)")


def sweep_size(size_mb: float, src_n: int, directions, trials: int):
    import jax
    import numpy as np
    from flax import serialization

    from edl_tpu.coord.store import InMemStore
    from edl_tpu.collective import migration as mig
    from edl_tpu.train import sharded_checkpoint as sc

    rows = []
    src_mesh = _mesh(src_n)
    state = build_state(size_mb, src_mesh)
    nbytes = sum(np.asarray(v).nbytes for v in state.values())

    d = tempfile.mkdtemp(prefix="edl-resize-bench-")
    try:
        sc.save_sharded(d, state)
        host = jax.device_get(state)
        blob = serialization.to_bytes(host)

        # a live donor serving the same snapshot from memory
        snap = sc.snapshot_shards(state)
        server = mig.MigrationServer(host="127.0.0.1")
        server.publish({"version": 0, "status": {}, "process_index": 0,
                        "leaves": snap["leaves"],
                        "chunks": dict(snap["chunks"])})
        store = InMemStore()
        store.put(mig.donor_key("bench", "donor0"), json.dumps(
            {"pod_id": "donor0", "addr": "127.0.0.1",
             "port": server.port, "version": 0}))
        try:
            for direction, tgt_n in directions:
                tgt_mesh = _mesh(tgt_n)
                target = target_like(state, tgt_mesh)

                disk_s = []
                for _ in range(trials):
                    t0 = time.perf_counter()
                    out = sc.restore_sharded(d, target)
                    jax.block_until_ready(out)
                    disk_s.append(time.perf_counter() - t0)

                p2p_s, wire_bytes, phases = [], 0, "-"
                for _ in range(trials):
                    trace.clear_ring()
                    t0 = time.perf_counter()
                    out, _, stats = mig.restore_from_peers(
                        store, "bench", target)
                    jax.block_until_ready(out)
                    p2p_s.append(time.perf_counter() - t0)
                    wire_bytes = stats["bytes_from_peers"]
                    phases = _phase_breakdown() or phases

                rows.append((size_mb, "disk", direction,
                             f"{src_n}->{tgt_n}", _median(disk_s), nbytes,
                             "-"))
                rows.append((size_mb, "p2p", direction,
                             f"{src_n}->{tgt_n}", _median(p2p_s),
                             wire_bytes, phases))

            # legacy replicated baseline: full msgpack deserialize (no
            # mesh direction — the blob is the whole state)
            rep_s = []
            for _ in range(trials):
                t0 = time.perf_counter()
                serialization.from_bytes(host, blob)
                rep_s.append(time.perf_counter() - t0)
            rows.append((size_mb, "disk-rep", "-", "-", _median(rep_s),
                         len(blob), "-"))
        finally:
            server.stop()
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return rows


def _run_demo(flag: str) -> dict | None:
    """Run one elastic_demo mode in a subprocess; parsed summary or
    None on failure (the demos self-audit and exit nonzero)."""
    import re
    import subprocess
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)           # each demo sets its own world
    env.pop("JAX_NUM_CPU_DEVICES", None)
    env["JAX_PLATFORMS"] = "cpu"
    tag = {"--resize-p2p": "p2p_summary",
           "--resize-reform": "reform_summary"}[flag]
    proc = subprocess.run(
        [sys.executable, "-m", "edl_tpu.examples.elastic_demo", flag],
        env=env, capture_output=True, text=True, timeout=600, cwd=REPO)
    m = re.search(tag + r"=(\{.*\})", proc.stdout)
    if not m or proc.returncode != 0:
        print(f"{flag} demo failed (rc={proc.returncode}) — rows "
              "omitted", file=sys.stderr)
        print(proc.stdout[-1500:], file=sys.stderr)
        return None
    return json.loads(m.group(1))


def sweep_worlds() -> None:
    """The multi-process world axis: grow/shrink across real subprocess
    worlds, downtime per transport (see module docstring)."""
    print("\nmulti-process world axis: measured resize downtime per "
          "transport\n(real launcher-pod subprocess worlds; each demo "
          "self-audits)\n")
    print("| direction | transport | survivor restarts | downtime s "
          "| notes |")
    print("|-----------|-----------|-------------------|-----------:"
          "|-------|")
    p2p = _run_demo("--resize-p2p")
    reform = _run_demo("--resize-reform")
    if p2p is not None:
        gaps = p2p.get("adoption_gaps_s") or []
        for direction, gap in zip(("shrink", "grow"), gaps):
            print(f"| {direction} | p2p-adopt | 0 | {gap:9.4f} "
                  "| device set unchanged |")
    if reform is not None:
        gaps = reform.get("reform_gaps_s") or []
        warm = reform.get("elastic_downtime_multihost_s")
        for gap in gaps:
            label = "warm (cached shape)" if gap == warm \
                else "cold (one compile)"
            print(f"| shrink/grow | in-place-reform | 0 | {gap:9.4f} "
                  f"| {label}; restore "
                  f"{(reform.get('last_reform') or {}).get('restore')} "
                  "|")
        respawn = reform.get("respawn_downtime_s")
        if respawn is not None:
            print(f"| grow | stop-resume | 1 | {respawn:9.4f} "
                  "| respawn + re-import + re-jit + peer restore |")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tools/resize_bench.py")
    parser.add_argument("--sizes-mb", type=float, nargs="+",
                        default=[8, 64, 256])
    parser.add_argument("--src-devices", type=int, default=4)
    parser.add_argument("--grow-devices", type=int, default=8)
    parser.add_argument("--shrink-devices", type=int, default=2)
    parser.add_argument("--trials", type=int, default=3)
    parser.add_argument("--worlds", action="store_true",
                        help="also run the multi-process world axis "
                             "(subprocess worlds; ~3-4 min)")
    parser.add_argument("--worlds-only", action="store_true",
                        help="skip the single-host sweep")
    args = parser.parse_args(argv)

    if args.worlds_only:
        sweep_worlds()
        return 0

    import jax
    n_dev = len(jax.devices())
    for need in (args.src_devices, args.grow_devices,
                 args.shrink_devices):
        if need > n_dev:
            print(f"need {need} devices, have {n_dev} "
                  f"(set EDL_TPU_TEST_DEVICES)", file=sys.stderr)
            return 2
    directions = [("grow", args.grow_devices),
                  ("shrink", args.shrink_devices)]

    print(f"restore term of the resize downtime (median of "
          f"{args.trials}); src mesh = {args.src_devices} devices\n")
    print("| state | path | direction | mesh | restore s | MB moved "
          "| phases (spans) |")
    print("|------:|------|-----------|------|----------:|---------:"
          "|----------------|")
    for size in args.sizes_mb:
        for row in sweep_size(size, args.src_devices, directions,
                              args.trials):
            size_mb, path, direction, mesh, secs, nbytes, phases = row
            print(f"| {size_mb:.0f}MB | {path} | {direction} | {mesh} "
                  f"| {secs:9.4f} | {nbytes / 2**20:8.1f} | {phases} |")
    if args.worlds:
        sweep_worlds()
    return 0


if __name__ == "__main__":
    sys.exit(main())
