"""Open-loop load sweep for the teacher serving tier (r23).

Probes REAL `TeacherServer`s (sleepy predict_fn standing in for chip
time, so the numbers are scheduling numbers, not model numbers) with
the open-loop generator (`edl_tpu.distill.loadgen`) across a batching
mode x offered-rate grid, and prints ONE markdown table per section:

  * latency sweep — window vs continuous batching at each offered
    rate: sustained rps, p50/p95, shed%. The continuous rows should
    dominate the window rows on latency at every rate below
    saturation at equal sustained throughput (the ``--serve-load``
    CI dryrun pins the 1.5x floor; this tool shows the whole curve);
  * overload section (``--overload``) — 2x the measured capacity on a
    high/normal/low mix with the shed rule armed, reporting per-class
    shed% / p95 / SLO attainment (graceful degradation is per class,
    never global).

  python tools/serve_load_bench.py --duration 5
  python tools/serve_load_bench.py --overload --shed-ms 150
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/serve_load_bench.py` puts
    sys.path.insert(0, REPO)  # tools/ on sys.path, not the repo root


def sleepy(per_row_s: float, base_s: float):
    import numpy as np

    def predict(feeds):
        rows = next(iter(feeds.values())).shape[0]
        time.sleep(base_s + per_row_s * rows)
        return {"logits": np.zeros((rows, 4), np.float32)}
    return predict


def fmt(x, nd=1) -> str:
    return "-" if x is None else f"{x:.{nd}f}"


def latency_sweep(args) -> None:
    from edl_tpu.distill.admission import AdmissionConfig
    from edl_tpu.distill.loadgen import run_open_loop
    from edl_tpu.distill.teacher_server import TeacherServer

    rates = [float(r) for r in args.rps.split(",") if r]
    print(f"\n## window vs continuous ({args.rows}-row requests, "
          f"{args.duration:.0f}s per cell)\n")
    print("| mode | offered rps | sustained rps | p50 ms | p95 ms "
          "| shed % |")
    print("|---|---|---|---|---|---|")
    for mode in ("window", "continuous"):
        server = TeacherServer(
            sleepy(args.per_row_ms / 1e3, args.base_ms / 1e3),
            port=0, host="127.0.0.1", max_batch=args.max_batch,
            max_wait=args.window_ms / 1e3,
            admission=AdmissionConfig(batching=mode,
                                      shed_ms=args.shed_ms)).start()
        try:
            for rps in rates:
                s = run_open_loop(
                    [f"127.0.0.1:{server.port}"],
                    duration_s=args.duration, rps=rps, rows=args.rows,
                    seed=args.seed).summary()
                print(f"| {mode} | {fmt(s['rps_offered'])} "
                      f"| {fmt(s['rps_sustained'])} "
                      f"| {fmt(s['p50_ms'])} | {fmt(s['p95_ms'])} "
                      f"| {fmt(100.0 * s['shed'] / max(s['offered'], 1))}"
                      f" |")
        finally:
            server.stop()


def overload(args) -> None:
    from edl_tpu.distill.admission import AdmissionConfig
    from edl_tpu.distill.loadgen import run_open_loop
    from edl_tpu.distill.teacher_server import TeacherServer

    adm = AdmissionConfig(batching="continuous",
                          shed_ms=args.shed_ms or 150.0)
    servers = [TeacherServer(sleepy(0.004, 0.004), port=0,
                             host="127.0.0.1", max_batch=8,
                             admission=adm).start()
               for _ in range(args.teachers)]
    eps = [f"127.0.0.1:{s.port}" for s in servers]
    # one teacher ~222 rows/s on the 36 ms fake chip; offer 2x the pool
    rps = args.teachers * 222.0 / args.rows * 2.0
    try:
        s = run_open_loop(
            eps, duration_s=3 * args.duration, rps=rps, rows=args.rows,
            mix={"high": 0.1, "normal": 0.15, "low": 0.75},
            seed=args.seed).summary(slo_ms=args.slo_ms)
    finally:
        for server in servers:
            server.stop()
    print(f"\n## 2x overload, {args.teachers} teachers, shed_ms="
          f"{adm.shed_ms:.0f}, SLO {args.slo_ms:.0f} ms "
          f"(offered {s['rps_offered']} rps, sustained "
          f"{s['rps_sustained']} rps)\n")
    print("| class | offered | ok | shed % | p50 ms | p95 ms "
          "| attainment |")
    print("|---|---|---|---|---|---|---|")
    for cls in ("high", "normal", "low"):
        c = s["by_class"].get(cls)
        if c is None:
            continue
        print(f"| {cls} | {c['offered']} | {c['ok']} "
              f"| {fmt(c['shed_pct'])} | {fmt(c['p50_ms'])} "
              f"| {fmt(c['p95_ms'])} | {fmt(c['attainment'], 3)} |")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tools/serve_load_bench.py")
    parser.add_argument("--duration", type=float, default=5.0,
                        help="seconds per sweep cell")
    parser.add_argument("--rps", default="25,50,100,200",
                        help="comma-joined offered request rates")
    parser.add_argument("--rows", type=int, default=4,
                        help="rows per predict request")
    parser.add_argument("--per-row-ms", type=float, default=0.3)
    parser.add_argument("--base-ms", type=float, default=1.0)
    parser.add_argument("--window-ms", type=float, default=20.0,
                        help="window-mode coalesce wait")
    parser.add_argument("--max-batch", type=int, default=64)
    parser.add_argument("--shed-ms", type=float, default=0.0,
                        help="normal-class delay budget (0 = no "
                             "overload shedding in the sweep)")
    parser.add_argument("--slo-ms", type=float, default=500.0)
    parser.add_argument("--teachers", type=int, default=2,
                        help="--overload: pool size")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--overload", action="store_true",
                        help="also run the 2x-overload per-class "
                             "degradation section (8-row requests)")
    parser.add_argument("--no-sweep", action="store_true",
                        help="skip the latency sweep section")
    args = parser.parse_args(argv)
    if not args.no_sweep:
        latency_sweep(args)
    if args.overload:
        args.rows = 8
        overload(args)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
