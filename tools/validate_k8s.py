"""Hermetic structural validation of deploy/k8s manifests.

The reference ships raw yaml (k8s/edl_controller.yaml etc.) with no
validation gate; a typo'd selector or a dangling Service reference only
surfaces at deploy time. kubeconform/kubectl need network or a cluster —
neither exists in CI here — so this checks the invariants that actually
bite, offline:

- every document parses and carries apiVersion/kind/metadata.name;
- workload selectors (Deployment/StatefulSet/Job) match their pod
  template labels — the classic silent-empty-ReplicaSet mistake;
- container names are unique per pod; every container has an image;
- StatefulSet.serviceName and any in-bundle DNS references
  (`<name>.<svc>.<ns>` / `<svc>:<port>`) resolve to a Service defined in
  the bundle, and the port exists on it;
- resource quantities and port numbers parse;
- namespaced objects agree with the bundle's Namespace.

Run directly (`python tools/validate_k8s.py [dir]`) or via
tests/test_k8s_manifests.py (CI).
"""

from __future__ import annotations

import re
import sys

import yaml

WORKLOAD_KINDS = {"Deployment", "StatefulSet", "Job", "DaemonSet"}
QTY_RE = re.compile(r"^\d+(\.\d+)?(m|k|Ki|Mi|Gi|Ti|M|G|T)?$")


def _fail(errors: list, doc_id: str, msg: str) -> None:
    errors.append(f"{doc_id}: {msg}")


def _pod_spec(doc: dict):
    kind = doc.get("kind")
    spec = doc.get("spec", {})
    if kind in WORKLOAD_KINDS:
        return spec.get("template", {}).get("spec", {})
    if kind == "JobSet":
        return None  # handled per replicatedJob
    if kind == "Pod":
        return spec
    return None


def _check_containers(errors, doc_id, pod_spec):
    containers = (pod_spec.get("initContainers", [])
                  + pod_spec.get("containers", []))
    if not pod_spec.get("containers"):
        _fail(errors, doc_id, "no containers in pod spec")
        return
    names = [c.get("name") for c in containers]
    if len(set(names)) != len(names):
        _fail(errors, doc_id, f"duplicate container names {names}")
    for c in containers:
        if not c.get("name"):
            _fail(errors, doc_id, "container without name")
        if not c.get("image"):
            _fail(errors, doc_id,
                  f"container {c.get('name')!r} without image")
        for kind2 in ("requests", "limits"):
            for key, val in (c.get("resources", {})
                             .get(kind2, {}) or {}).items():
                if not QTY_RE.match(str(val)):
                    _fail(errors, doc_id,
                          f"unparseable resource {key}={val!r}")
        for port in c.get("ports", []) or []:
            cp = port.get("containerPort")
            if not isinstance(cp, int) or not 0 < cp < 65536:
                _fail(errors, doc_id, f"bad containerPort {cp!r}")


def _check_selector(errors, doc_id, doc):
    labels = (doc.get("spec", {}).get("template", {})
              .get("metadata", {}).get("labels", {}))
    want = doc.get("spec", {}).get("selector", {}).get("matchLabels", {})
    if not want:
        # Jobs get a controller-generated selector; the others silently
        # manage zero pods without one.
        if doc.get("kind") != "Job":
            _fail(errors, doc_id, "workload without selector.matchLabels")
        return
    for k, v in want.items():
        if labels.get(k) != v:
            _fail(errors, doc_id,
                  f"selector {k}={v} not in template labels {labels}")


def _service_ports(doc) -> set:
    out = set()
    for port in doc.get("spec", {}).get("ports", []) or []:
        if "port" in port:
            out.add(int(port["port"]))
    return out


def _collect_dns_refs(obj, refs):
    """Find '<host>:<port>' strings in args/env that look like in-bundle
    service DNS (contain a dot-name matching our service conventions)."""
    if isinstance(obj, dict):
        for v in obj.values():
            _collect_dns_refs(v, refs)
    elif isinstance(obj, list):
        for v in obj:
            _collect_dns_refs(v, refs)
    elif isinstance(obj, str):
        for m in re.finditer(r"([a-z0-9-]+(?:\.[a-z0-9-]+)+):(\d+)", obj):
            refs.append((m.group(1), int(m.group(2))))


def validate_dir(directory: str) -> list[str]:
    import glob
    import os

    errors: list[str] = []
    docs = []
    for path in sorted(glob.glob(os.path.join(directory, "*.yaml"))):
        try:
            with open(path) as f:
                for i, doc in enumerate(yaml.safe_load_all(f)):
                    if doc is None:
                        continue
                    docs.append((f"{os.path.basename(path)}[{i}]", doc))
        except yaml.YAMLError as exc:
            errors.append(f"{os.path.basename(path)}: yaml parse: {exc}")
    if not docs:
        errors.append(f"no yaml documents under {directory}")
        return errors

    services = {}
    namespaces = set()
    for doc_id, doc in docs:
        for key in ("apiVersion", "kind"):
            if not doc.get(key):
                _fail(errors, doc_id, f"missing {key}")
        name = doc.get("metadata", {}).get("name")
        if not name:
            _fail(errors, doc_id, "missing metadata.name")
        if doc.get("kind") == "Namespace":
            namespaces.add(name)
        if doc.get("kind") == "Service":
            ns = doc.get("metadata", {}).get("namespace", "default")
            services[(name, ns)] = _service_ports(doc)

    for doc_id, doc in docs:
        kind = doc.get("kind")
        ns = doc.get("metadata", {}).get("namespace")
        if ns and namespaces and ns not in namespaces and ns != "default":
            _fail(errors, doc_id,
                  f"namespace {ns!r} not defined in bundle")
        pod_spec = _pod_spec(doc)
        if pod_spec is not None:
            _check_containers(errors, doc_id, pod_spec)
        if kind in WORKLOAD_KINDS:
            _check_selector(errors, doc_id, doc)
        if kind == "JobSet":
            for rj in doc.get("spec", {}).get("replicatedJobs", []) or []:
                rj_id = f"{doc_id}/replicatedJob[{rj.get('name')}]"
                tmpl = (rj.get("template", {}).get("spec", {})
                        .get("template", {}).get("spec", {}))
                _check_containers(errors, rj_id, tmpl)
        if kind == "StatefulSet":
            svc = doc.get("spec", {}).get("serviceName")
            if svc and not any(n == svc for (n, _) in services):
                _fail(errors, doc_id,
                      f"serviceName {svc!r} has no Service in bundle")

        refs: list[tuple[str, int]] = []
        _collect_dns_refs(doc.get("spec"), refs)
        for host, port in refs:
            parts = host.split(".")
            # conventions: pod-0.<svc>.<ns> or <svc>.<ns>
            candidates = {parts[0]}
            if "-" in parts[0]:
                candidates.add(parts[0].rsplit("-", 1)[0])
            if len(parts) > 1:
                candidates.add(parts[1])
            in_bundle = [k for k in services if k[0] in candidates]
            if not in_bundle:
                continue  # external host: not ours to validate
            if not any(port in services[k] for k in in_bundle):
                _fail(errors, doc_id,
                      f"reference {host}:{port} — no matching Service "
                      f"port in bundle")
    return errors


def main(argv=None) -> int:
    directory = (argv or sys.argv[1:] or ["deploy/k8s"])[0]
    errors = validate_dir(directory)
    if errors:
        print("\n".join(errors))
        return 1
    print(f"ok: {directory} manifests structurally valid")
    return 0


if __name__ == "__main__":
    sys.exit(main())
