"""Fleet-scale policy tournament: policy x trace x ladder grids.

Runs the seeded `FleetSim` tournament (hundreds of concurrent trainer
jobs + serving pools per trace) and prints ONE markdown table per
ladder: fleet goodput (sealed trainer rows + SLO-ok served rows per
second), Jain fairness over entitlement-normalized occupancy, SLO
attainment, the downtime bill by action kind, and the spot columns
(forced evictions, notices ridden, progress lost). Two extra seeded
experiments follow the grid:

* spot riding — the same trace shape at 0% and 80% revocable
  capacity under `PreemptiveFairSharePolicy`; the ratio is the price
  of living on spot when every notice is ridden as a scheduled shrink;
* the ladder flip — the ``noisy`` trace, where raw-observation
  re-packing (`GreedyRebalancePolicy`) beats fair-share under the
  measured reform ladder and loses under legacy stop-resume pricing.

`--check` turns the run into a gate (nonzero exit unless the
tournament's headline claims hold); `--json` writes the full artifact
(`FLEET_r20.json`). Deterministic end to end: same seeds => identical
tables and sha256 fingerprint.

  python tools/fleet_bench.py --check --json FLEET_r20.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/fleet_bench.py` puts tools/
    sys.path.insert(0, REPO)  # on sys.path, not the repo root


def print_tables(rows) -> None:
    ladders = []
    for r in rows:
        if r["ladder"] not in ladders:
            ladders.append(r["ladder"])
    for ladder in ladders:
        print(f"\n### ladder: {ladder}")
        print("| trace | policy | goodput rows/s | SLO attain "
              "| Jain | downtime s | adopt/reform/stop | evict "
              "| rode | lost rows |")
        print("|---|---|---|---|---|---|---|---|---|---|")
        for r in rows:
            if r["ladder"] != ladder:
                continue
            k = r["resizes_by_kind"]
            print(f"| {r['trace']} | {r['policy']} "
                  f"| {r['goodput_rows_per_s']} "
                  f"| {r['slo_attainment']:.4f} "
                  f"| {r['jain_fairness']:.3f} "
                  f"| {r['downtime_paid_s']} "
                  f"| {k['adopt']}/{k['reform']}/{k['stop-resume']} "
                  f"| {r['forced_evictions']} "
                  f"| {r['notices_ridden']}/{r['notices_issued']} "
                  f"| {r['lost_rows']} |")


def spot_experiment(args) -> dict:
    """The same trace shape all-reserved vs 80% revocable, ridden by
    the preemptive policy."""
    from edl_tpu.scaler.fleet import FleetSim, FleetTrace, run_fleet
    from edl_tpu.scaler.fleet_policy import PreemptiveFairSharePolicy
    out = {}
    for key, frac in (("reserved", 0.0), ("spot80", 0.8)):
        trace = FleetTrace.generate(
            "spot-ride", 21, n_jobs=args.jobs, n_pools=args.pools,
            ticks=args.ticks, spot_fraction=frac)
        out[key] = run_fleet(
            FleetSim(trace),
            PreemptiveFairSharePolicy(1, cooldown_s=15.0,
                                      horizon_s=60.0))
    out["goodput_ratio"] = round(
        out["spot80"]["goodput_rows_per_s"]
        / out["reserved"]["goodput_rows_per_s"], 4)
    return out


def check(rows, spot) -> list[str]:
    """The headline claims the artifact must support."""
    failures = []
    cell = {(r["trace"], r["ladder"], r["policy"]): r for r in rows}
    traces = sorted({r["trace"] for r in rows})
    # 1. preemptive beats fair-share on SLO attainment at
    # equal-or-better goodput, per trace, under the measured ladder
    wins = 0
    for t in traces:
        base = cell.get((t, "measured", "fair-share"))
        pre = cell.get((t, "measured", "preemptive-fair-share"))
        if base is None or pre is None:
            continue
        if pre["slo_attainment"] >= base["slo_attainment"] \
                and pre["goodput_rows_per_s"] >= base["goodput_rows_per_s"] \
                and (pre["slo_attainment"] > base["slo_attainment"]
                     or pre["goodput_rows_per_s"]
                     > base["goodput_rows_per_s"]):
            wins += 1
    if wins < 3:
        failures.append(f"preemptive-beats-fair on {wins} traces (<3)")
    # 2. 80% revocable capacity sustains >=90% of all-reserved goodput
    # with zero forced evictions (every notice ridden)
    if spot["goodput_ratio"] < 0.9:
        failures.append(f"spot80 goodput ratio {spot['goodput_ratio']}"
                        " < 0.9")
    if spot["spot80"]["forced_evictions"] \
            > spot["reserved"]["forced_evictions"]:
        failures.append("spot80 paid forced evictions "
                        f"({spot['spot80']['forced_evictions']})")
    # 3. the ladder changes the winner: greedy re-packing beats
    # fair-share on the noisy trace under measured, loses under legacy
    for ladder, want_greedy in (("measured", True), ("legacy", False)):
        fair = cell.get(("noisy", ladder, "fair-share"))
        greedy = cell.get(("noisy", ladder, "greedy-rebalance"))
        if fair is None or greedy is None:
            continue
        greedy_wins = (greedy["goodput_rows_per_s"]
                       > fair["goodput_rows_per_s"])
        if greedy_wins != want_greedy:
            failures.append(
                f"noisy/{ladder}: greedy "
                f"{'should' if want_greedy else 'should not'} win "
                f"({greedy['goodput_rows_per_s']} vs "
                f"{fair['goodput_rows_per_s']})")
    return failures


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tools/fleet_bench.py")
    parser.add_argument("--jobs", type=int, default=180)
    parser.add_argument("--pools", type=int, default=24)
    parser.add_argument("--ticks", type=int, default=240)
    parser.add_argument("--decide-every", type=int, default=2)
    parser.add_argument("--ladder", metavar="BENCH_JSON", default=None,
                        help="bench artifact whose measured extras "
                             "(elastic_downtime_p2p_s / _multihost_s / "
                             "elastic_downtime_s) price the resize "
                             "ladder instead of the built-in defaults")
    parser.add_argument("--json", metavar="PATH", default=None,
                        help="write the full tournament artifact here")
    parser.add_argument("--check", action="store_true",
                        help="exit nonzero unless the headline claims "
                             "hold (acceptance gate)")
    args = parser.parse_args(argv)

    from edl_tpu.scaler.fleet import (LEGACY, DowntimeLadder,
                                      tournament, trace_menu)
    ladders = None
    if args.ladder:
        measured = DowntimeLadder.from_artifact(args.ladder)
        if measured is None:
            print(f"unreadable ladder artifact: {args.ladder}",
                  file=sys.stderr)
            return 2
        # keep the canonical grid names so --check applies unchanged
        measured = DowntimeLadder("measured", measured.adopt_s,
                                  measured.reform_s,
                                  measured.stop_resume_s)
        ladders = [measured, LEGACY]

    traces = trace_menu(n_jobs=args.jobs, n_pools=args.pools,
                        ticks=args.ticks)
    n_workloads = args.jobs + args.pools
    print(f"fleet tournament: {len(traces)} traces x "
          f"{n_workloads} concurrent workloads x {args.ticks} ticks "
          f"(goodput = sealed trainer rows + SLO-ok served rows; a "
          f"row served during a breach is throughput, not goodput)")
    result = tournament(traces=traces, ladders=ladders,
                        decide_every=args.decide_every)
    print_tables(result["rows"])

    print("\n### spot riding (preemptive policy)")
    spot = spot_experiment(args)
    for key in ("reserved", "spot80"):
        r = spot[key]
        print(f"{key}: goodput={r['goodput_rows_per_s']} "
              f"evict={r['forced_evictions']} "
              f"rode={r['notices_ridden']}/{r['notices_issued']} "
              f"lost={r['lost_rows']}")
    print(f"spot80/reserved goodput ratio: {spot['goodput_ratio']}")
    print(f"\nfingerprint: {result['fingerprint']}")

    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump({"rows": result["rows"],
                       "fingerprint": result["fingerprint"],
                       "spot": spot,
                       "config": {"jobs": args.jobs,
                                  "pools": args.pools,
                                  "ticks": args.ticks,
                                  "decide_every": args.decide_every}},
                      f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.json}")

    if args.check:
        failures = check(result["rows"], spot)
        for f in failures:
            print(f"CHECK FAIL: {f}", file=sys.stderr)
        if failures:
            return 1
        print("all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
