#!/usr/bin/env python3
"""Markdown audit report for edl-lint: findings by checker + the full
suppression inventory with reasons.

    python tools/lint_report.py [--root .] [--out report.md]

Runs the same checkers as ``python -m edl_tpu.analysis lint`` and
renders (a) a findings-by-checker table (all zeros on a healthy HEAD —
the CI gate enforces that), (b) every suppression in force with its
file, line, and mandatory reason (the audit surface: a suppression
without a defensible reason should die in review), and (c) the
lockgraph report summary when a ``/tmp/edl_lockgraph.json`` (or
``--lockgraph PATH``) artifact exists from a plugin run.  Paste the
output into a PR description; future audits diff it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from edl_tpu.analysis.checks import CHECKS          # noqa: E402
from edl_tpu.analysis.core import run_lint          # noqa: E402


def render(root: str, lockgraph_path: str | None) -> str:
    result = run_lint(root)
    lines = ["# edl-lint audit", ""]

    by_check = {name: 0 for name in CHECKS}
    by_check["unused-suppression"] = 0
    by_check["parse"] = 0
    by_check["suppression"] = 0
    for f in result.findings:
        by_check[f.check] = by_check.get(f.check, 0) + 1
    sup_by_check: dict[str, int] = {}
    for _f, s in result.suppressed:
        sup_by_check[s.check] = sup_by_check.get(s.check, 0) + 1

    lines += ["## Findings by checker", "",
              "| Checker | Open findings | Suppressed |",
              "|---|---:|---:|"]
    for name in sorted(by_check):
        if by_check[name] == 0 and name in ("parse", "suppression",
                                            "unused-suppression"):
            continue
        lines.append(f"| `{name}` | {by_check[name]} | "
                     f"{sup_by_check.get(name, 0)} |")
    lines += ["",
              f"**Verdict: {'CLEAN' if result.ok else 'FAILING'}** — "
              f"{len(result.findings)} open finding(s), "
              f"{len(result.suppressed)} suppressed.", ""]

    if result.findings:
        lines += ["## Open findings", ""]
        for f in result.findings:
            lines.append(f"- `{f.path}:{f.line}` **{f.check}** — "
                         f"{f.message}")
        lines.append("")

    lines += ["## Suppression inventory", ""]
    if not result.suppressions:
        lines += ["(none — every contract holds without exception)", ""]
    else:
        lines += ["| Site | Check | Reason |", "|---|---|---|"]
        for s in sorted(result.suppressions,
                        key=lambda s: (s.path, s.line)):
            lines.append(f"| `{s.path}:{s.line}` | `{s.check}` | "
                         f"{s.reason} |")
        lines.append("")

    if lockgraph_path and os.path.exists(lockgraph_path):
        with open(lockgraph_path) as fh:
            rep = json.load(fh)
        lines += ["## Lockgraph (last plugin run)", "",
                  f"- lock sites tracked: {rep['locks_tracked']}",
                  f"- order edges: {rep['edges']}",
                  f"- cycles: {len(rep['cycles'])}",
                  f"- hazards: {len(rep['hazards'])}",
                  f"- self-edge warnings: "
                  f"{len(rep.get('self_edge_warnings', []))}", ""]
        for cyc in rep["cycles"]:
            lines.append(f"  - CYCLE: {' -> '.join(cyc + [cyc[0]])}")
        for hz in rep["hazards"]:
            lines.append(f"  - HAZARD [{hz['kind']}] {hz['queue']} at "
                         f"{hz['at']}")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--root", default=os.getcwd())
    parser.add_argument("--out", default=None,
                        help="write here instead of stdout")
    parser.add_argument("--lockgraph", default="/tmp/edl_lockgraph.json",
                        help="lockgraph JSON artifact to summarize")
    args = parser.parse_args(argv)
    text = render(args.root, args.lockgraph)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(text)
        print(f"wrote {args.out}")
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
