#!/usr/bin/env python
"""Chaos sweep: seeds x fault mixes -> a markdown robustness table.

Each cell runs one ``python -m edl_tpu.chaos soak`` as a subprocess at
a fixed (seed, mix) and reports what the invariant audit said: faults
injected / survived, breaches (must be 0), worst observed recovery
window, acked/delivered mark counts. The sweep is how a change to any
elastic mechanism shows its robustness envelope — a regression appears
as a nonzero breach column at some seed long before it costs a fleet.

Sequential by design: the bench host has ONE core — never run cells
concurrently (nor concurrent with tier-1).

    python tools/chaos_bench.py --seeds 1,2,3 --ticks 16
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys

# Named fault mixes: --mix restricts the schedule to a class subset so
# a failure localizes (a breach under "store" implicates the
# replication plane, not the checkpoint rig).
MIXES = {
    "all": None,
    "store": ["wire", "store-partition", "leader-kill"],
    "process": ["process-kill", "process-pause", "resize"],
    "ckpt": ["ckpt-corrupt", "process-kill"],
}


def run_cell(seed: int, mix: str, ticks: int, settle_s: float) -> dict:
    cmd = [sys.executable, "-m", "edl_tpu.chaos", "soak",
           "--seed", str(seed), "--ticks", str(ticks),
           "--settle-s", str(settle_s)]
    if MIXES[mix]:
        cmd += ["--mix", ",".join(MIXES[mix])]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=600)
    summary: dict = {}
    for line in proc.stdout.splitlines():
        if line.startswith("chaos_summary="):
            summary = json.loads(line.split("=", 1)[1])
    stats = summary.get("stats", {})
    return {"seed": seed, "mix": mix, "rc": proc.returncode,
            "injected": stats.get("faults_injected", 0),
            "survived": stats.get("faults_survived", 0),
            "breaches": len(summary.get("breaches", [])),
            "classes": len(stats.get("fault_classes", [])),
            "max_downtime_s": stats.get("max_downtime_s", 0.0),
            "acked": stats.get("marks_acked", 0),
            "sealed": stats.get("versions_sealed", 0)}


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--seeds", default="1,2,3")
    parser.add_argument("--mixes", default="all",
                        help=f"comma-joined subset of {sorted(MIXES)}")
    parser.add_argument("--ticks", type=int, default=16)
    parser.add_argument("--settle-s", type=float, default=10.0)
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args()

    seeds = [int(s) for s in args.seeds.split(",") if s]
    mixes = [m for m in args.mixes.split(",") if m]
    for m in mixes:
        if m not in MIXES:
            raise SystemExit(f"unknown mix {m!r} (have {sorted(MIXES)})")

    rows = []
    for mix in mixes:
        for seed in seeds:
            print(f"# soak seed={seed} mix={mix} ...", file=sys.stderr,
                  flush=True)
            rows.append(run_cell(seed, mix, args.ticks, args.settle_s))

    if args.json:
        print(json.dumps(rows, indent=1))
        return 0
    print("| seed | mix | faults | survived | breaches | classes "
          "| max downtime s | marks acked | ckpts sealed |")
    print("|---|---|---|---|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['seed']} | {r['mix']} | {r['injected']} "
              f"| {r['survived']} | {r['breaches']} | {r['classes']} "
              f"| {r['max_downtime_s']} | {r['acked']} "
              f"| {r['sealed']} |")
    worst = max((r["breaches"] for r in rows), default=0)
    print(f"\nworst breach count across {len(rows)} cells: {worst}")
    return 1 if worst else 0


if __name__ == "__main__":
    sys.exit(main())
