"""Headline benchmark suite: ResNet train, distill e2e, transformer MFU.

Mirrors the reference's published numbers (README.md:70-72):
  - 1828 img/s ResNet50_vd pure training on 8x V100 (228.5/accelerator)
    -> `resnet50_vd_train_imgs_per_sec` (the headline metric + vs_baseline),
    fed through the real input pipeline (DataLoader + prefetch_to_device),
  - 656 img/s co-located distill on the same 8 GPUs (82/accelerator)
    -> `extras.distill_student_imgs_per_sec`: student train step + teacher
    inference sharing this chip, logits over the real TCP tensor wire
    through DistillReader (exactly-once pipeline, request coalescing),
  - plus a net-new transformer LM number (no reference counterpart — its
    models are CNNs): `extras.transformer_tokens_per_sec` and
    `extras.transformer_mfu` against the chip's peak bf16 FLOPs.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extras"}.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

# Peak dense bf16 FLOPs/s per chip by device kind (public spec sheets;
# conservative default if the kind is unknown).
PEAK_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5": 459e12,        # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
}


def _sync(x) -> float:
    # value fetch = hard sync (block_until_ready alone does not force
    # execution through remote-device tunnels)
    return float(x)


def normalize_uint8(x):
    """uint8 pixels -> [-1, 1] float32 ON DEVICE (shared by the train
    steps and the teacher forward: distill students must see exactly the
    normalization the teacher was fed)."""
    return x.astype(jnp.float32) * (2.0 / 255.0) - 1.0


def bench_resnet(on_tpu: bool) -> dict:
    """ResNet50_vd training: chip steady-state + pipeline-fed numbers.

    Headline = device-resident steady-state (a handful of pre-staged
    batches rotated on device), which is what the reference's DALI-fed
    GPUs measure — their input plane never starves the accelerator. The
    extras number feeds the SAME step through DataLoader +
    prefetch_to_device with uint8 wire/transport and on-device
    normalization (the DALI recipe: never ship float32 pixels). Under
    this harness the host<->chip link is a network tunnel ~2 orders
    slower than a TPU VM's PCIe/DMA path, so the pipeline figure is a
    lower bound that collapses to the headline on real hosts.
    """
    from edl_tpu.data.pipeline import (ArraySource, DataLoader,
                                       prefetch_to_device, random_flip_lr)
    from edl_tpu.models.resnet import ResNet50_vd, ResNetTiny
    from edl_tpu.parallel import mesh as mesh_lib
    from edl_tpu.train import classification as cls
    from edl_tpu.train.step import make_train_step

    n_dev = len(jax.devices())
    if on_tpu:
        model = ResNet50_vd(num_classes=1000, dtype=jnp.bfloat16)
        per_dev_batch, hw, classes, steps = 128, 224, 1000, 24
        # >= 4 global batches whatever the chip count (uint8: ~150KB/img)
        source_n, pipe_steps = 4 * per_dev_batch * n_dev, 6
    else:
        model = ResNetTiny(num_classes=10, dtype=jnp.float32)
        per_dev_batch, hw, classes, steps = 8, 32, 10, 4
        source_n, pipe_steps = 32 * n_dev, 2

    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": n_dev}))
    batch_size = per_dev_batch * n_dev
    rng = np.random.default_rng(0)
    # uint8 pixels, normalized ON DEVICE inside the jitted step
    source = ArraySource({
        "image": rng.integers(0, 256, size=(source_n, hw, hw, 3),
                              dtype=np.uint8),
        "label": rng.integers(0, classes, size=(source_n,)).astype(np.int32),
    })
    loader = DataLoader(source, batch_size, transforms=(random_flip_lr,))
    sharding = mesh_lib.data_sharding(mesh)

    state = cls.create_state(model, jax.random.PRNGKey(0), (1, hw, hw, 3),
                             optax.sgd(0.1, momentum=0.9, nesterov=True))

    def loss_fn(state, params, batch):
        img = normalize_uint8(batch["image"])
        variables = {"params": params, "batch_stats": state.batch_stats}
        logits, mutated = state.apply_fn(variables, img, train=True,
                                         mutable=["batch_stats"])
        targets = cls.smoothed_labels(batch["label"], classes, 0.1)
        loss = cls.soft_cross_entropy(logits, targets)
        return loss, {"batch_stats": mutated["batch_stats"]}

    step = make_train_step(loss_fn, donate=True)  # donates state, not batch

    # -- headline: device-resident rotation (chip steady-state) ------------
    def all_batches(start_epoch):
        epoch = start_epoch
        while True:
            yield from loader.epoch(epoch)
            epoch += 1

    staged = []
    it0 = all_batches(0)  # chained epochs: one epoch may hold < 4 batches
    for _ in range(4):
        b = next(it0)
        staged.append({k: jax.device_put(v, sharding) for k, v in b.items()})
    for i in range(3):  # warmup / compile
        state, metrics = step(state, staged[i % len(staged)])
    _sync(metrics["loss"])
    t0 = time.perf_counter()
    for i in range(steps):
        state, metrics = step(state, staged[i % len(staged)])
    _sync(metrics["loss"])
    dt = time.perf_counter() - t0
    imgs_per_sec = steps * batch_size / dt

    # -- extras: full input pipeline (host -> device each step), fed
    # through the MP shared-memory loader (the DALI multi-worker feed
    # role — worker processes collate into shm slots, the parent
    # device_puts zero-copy views) ----------------------------------------
    mp_workers = 4 if on_tpu else 2
    mp_loader = DataLoader(source, batch_size, transforms=(random_flip_lr,),
                           num_workers=mp_workers)

    def batches():
        epoch = 1
        while True:
            yield from mp_loader.epoch(epoch)
            epoch += 1

    it = prefetch_to_device(batches(), sharding, size=4)
    state, metrics = step(state, next(it))  # pipeline warmup
    _sync(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(pipe_steps):
        state, metrics = step(state, next(it))
    _sync(metrics["loss"])
    pipe_dt = time.perf_counter() - t0
    it.close()
    mp_loader.close()
    pipe_imgs_per_sec = pipe_steps * batch_size / pipe_dt

    # -- extras: the SAME step fed from PACKED records with DEVICE
    # augmentation — the host only gathers raw uint8 rows off the mmap
    # and ships the per-step seed; the flip (the host transform above)
    # runs jitted right after placement, overlapping the step. This is
    # the zero-host-transform feed path end to end. --------------------
    import tempfile

    from edl_tpu.data.packed_records import PackedSource, pack_source
    from edl_tpu.ops.augment import make_device_augment
    pack_dir = tempfile.mkdtemp(prefix="edl-bench-pack-")
    try:
        pack_path = os.path.join(pack_dir, "bench.pack")
        pack_source(source, pack_path, batch_size=batch_size)
        packed_loader = DataLoader(PackedSource(pack_path), batch_size,
                                   emit_batch_seed=True)
        augment = make_device_augment(flip=True, crop=False,
                                      normalize=None)  # step normalizes

        def packed_batches():
            epoch = 1
            while True:
                yield from packed_loader.epoch(epoch)
                epoch += 1

        it = prefetch_to_device(packed_batches(), sharding, size=4,
                                augment=augment)
        state, metrics = step(state, next(it))  # warmup (augment compile)
        _sync(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(pipe_steps):
            state, metrics = step(state, next(it))
        _sync(metrics["loss"])
        packed_dt = time.perf_counter() - t0
        it.close()
        packed_loader.close()
    finally:
        import shutil
        shutil.rmtree(pack_dir, ignore_errors=True)
    packed_pipe_imgs_per_sec = pipe_steps * batch_size / packed_dt

    per_accel = imgs_per_sec / n_dev
    return {"imgs_per_sec": round(imgs_per_sec, 1),
            "batch_size": batch_size,
            "pipeline_imgs_per_sec": round(pipe_imgs_per_sec, 1),
            "pipeline_loader_workers": mp_workers,
            "pipeline_packed_imgs_per_sec":
                round(packed_pipe_imgs_per_sec, 1),
            "vs_baseline": round(per_accel / (1828.0 / 8.0), 3)}


def bench_input_plane(on_tpu: bool) -> dict:
    """Host-side loader-ONLY throughput of the JPEG decode/augment plane
    (no device transfer): JpegFileListSource -> thread-pooled decode +
    random-resized-crop + flip -> collated uint8 batches.

    This is the number the resnet headline's input story rests on: the
    reference's input plane is a multi-core cv2/DALI pipeline
    (reader_cv2.py xmap threads=4+, dali.py GPU decode); whether OURS
    can feed the chip is a host-CPU question, so alongside img/s we
    report the pool width and the per-core rate — on an N-core TPU VM
    the plane scales to ~N * per_core (cv2 releases the GIL), and
    `cores_to_feed_headline` is the host size at which the loader
    saturates the measured chip rate."""
    import os
    import tempfile

    from edl_tpu.data.image import (JpegFileListSource,
                                    make_synthetic_jpeg_dataset,
                                    train_image_transform)
    from edl_tpu.data.pipeline import DataLoader

    cores = os.cpu_count() or 1
    threads = max(1, cores)
    if on_tpu:
        n_imgs, size, hw, batches = 1024, 224, (360, 480), 8
    else:
        n_imgs, size, hw, batches = 128, 64, (90, 120), 4
    import shutil

    d = tempfile.mkdtemp(prefix="edl-bench-jpeg-")
    try:
        list_file = make_synthetic_jpeg_dataset(d, n_imgs, classes=1000,
                                                hw=hw, seed=0)
        src = JpegFileListSource(list_file, root=d)
        batch_size = 128 if on_tpu else 32

        def timed_run(loader) -> float:
            # Two warm-up batches: the first is the page-cache/pool warm
            # (mp: the in-parent probe that sizes the shm ring), the
            # SECOND is what actually forks the mp workers and builds
            # the ring — with one, worker startup (and a second
            # in-parent probe) would land inside the timed window.
            it = iter(loader.epoch(0))
            next(it)
            next(it, None)
            it.close()  # mp: drain in-flight slots; pool stays warm
            n = 0
            t0 = time.perf_counter()

            def batches_forever():
                epoch = 1
                while True:
                    yield from loader.epoch(epoch)
                    epoch += 1

            for batch in batches_forever():
                n += len(batch["label"])
                if n >= batches * batch_size:
                    break
            dt = time.perf_counter() - t0
            loader.close()
            return n / dt

        imgs_per_sec = timed_run(DataLoader(
            src, batch_size,
            sample_transforms=(train_image_transform(size),),
            decode_threads=threads))

        # MP shared-memory worker pool over the SAME plane: worker
        # PROCESSES sidestep the GIL that caps the thread pool once
        # Python-side transform/collation code dominates. On an N-core
        # host this scales ~linearly to min(workers, N); on a 1-core
        # host it measures the IPC overhead instead (scaling < 1).
        mp_workers = 4
        mp_imgs_per_sec = timed_run(DataLoader(
            src, batch_size,
            sample_transforms=(train_image_transform(size),),
            num_workers=mp_workers))

        # PACKED pre-decoded records (data/packed_records.py): the
        # decode + resize ran ONCE at pack time, train-time host work is
        # a single np.take gather per batch + the per-step seed for the
        # on-device augmentation (emit_batch_seed — crop/flip/normalize
        # run jitted on the accelerator, costing the host nothing).
        # This is the zero-host-transform feed the cores_to_feed number
        # is recomputed against; the price is disk
        # (loader_pack_ratio_bytes: pre-decoded uint8 vs jpeg).
        from edl_tpu.data.packed_records import PackedSource, pack_jpeg_list
        jpeg_bytes = sum(
            os.path.getsize(os.path.join(d, f)) for f in os.listdir(d))
        pack_path = os.path.join(d, "train.pack")
        pack_jpeg_list(list_file, d, pack_path, size=size,
                       batch_size=batch_size)
        packed_bytes = os.path.getsize(pack_path)
        packed_imgs_per_sec = timed_run(DataLoader(
            PackedSource(pack_path), batch_size, emit_batch_seed=True))
    finally:
        shutil.rmtree(d, ignore_errors=True)
    per_core = imgs_per_sec / max(1, min(threads, cores))
    return {"imgs_per_sec": round(imgs_per_sec, 1),
            "threads": threads,
            "host_cores": cores,
            "imgs_per_sec_per_core": round(per_core, 1),
            "mp_imgs_per_sec": round(mp_imgs_per_sec, 1),
            "mp_workers": mp_workers,
            "mp_scaling": round(mp_imgs_per_sec / max(imgs_per_sec, 1e-9),
                                2),
            # packed gather is single-threaded host work: its per-core
            # rate IS its rate
            "packed_imgs_per_sec": round(packed_imgs_per_sec, 1),
            "pack_ratio_bytes": round(packed_bytes / max(jpeg_bytes, 1),
                                      2)}


def bench_flash_kernel(on_tpu: bool) -> dict:
    """Pallas flash kernel vs XLA dense attention at long context.

    Kernel-level number (the transformer bench exercises it end-to-end):
    forward speedup at S=4096, where the causal block skip and the
    never-materialized score tensor matter most."""
    from edl_tpu.ops.flash_attention import flash_attention
    from edl_tpu.parallel.ring_attention import dense_attention

    if on_tpu:
        B, S, H, D, steps = 4, 4096, 16, 64, 10
    else:
        B, S, H, D, steps = 1, 512, 2, 64, 2
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, S, H, D),
                                 jnp.bfloat16) for i in range(3))
    f_flash = jax.jit(lambda q, k, v: flash_attention(q, k, v,
                                                      block_q=1024))
    f_dense = jax.jit(lambda q, k, v: dense_attention(q, k, v))

    def timed(fn) -> float:
        fn(q, k, v).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(q, k, v)
        out.block_until_ready()
        return time.perf_counter() - t0

    t_flash, t_dense = timed(f_flash), timed(f_dense)
    return {"seq_len": S,
            "speedup_vs_dense": round(t_dense / t_flash, 2)}


def _measure_lm(cfg_kw: dict, B: int, S: int, steps: int,
                on_tpu: bool) -> dict:
    """One LM train-step measurement: tokens/s + MFU vs the bf16 peak."""
    from edl_tpu.models.transformer import (Transformer, TransformerConfig,
                                            lm_loss_fused)
    from edl_tpu.parallel import mesh as mesh_lib, sharding as shd
    from edl_tpu.train.state import TrainState
    from edl_tpu.train.step import make_train_step

    n_dev = len(jax.devices())
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": n_dev}))
    cfg = TransformerConfig(mesh=mesh, **cfg_kw)
    model = Transformer(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0,
                              cfg.vocab_size)
    variables = shd.init_sharded(
        lambda: model.init(jax.random.PRNGKey(0), toks, train=False), mesh)
    state = TrainState.create(apply_fn=model.apply,
                              params=variables["params"],
                              tx=optax.adamw(1e-3))
    # fused (streamed-vocab) CE + state donation: the measured LM recipe.
    # The r4 profile that set this config: attention BACKWARD was ~29%
    # of step time under the XLA scan (now a Pallas kernel pair), and
    # the dense CE materializes a (B*S, V) fp32 logits tensor the
    # streamed loss never builds. 182ms -> 147ms/step on v5e-1.
    step = make_train_step(lm_loss_fused, donate=True)
    batch = {"tokens": mesh_lib.shard_batch(mesh, toks)}

    for _ in range(2):
        state, metrics = step(state, batch)
    _sync(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch)
    _sync(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_sec = steps * B * S / dt

    # Analytic model FLOPs/step (PaLM-style accounting): 6*T*P_matmul for
    # the matmuls (fwd+bwd), + causal attention scores/values at
    # 12*L*B*S^2*d * 0.5.
    d, L, V, ff = cfg.d_model, cfg.n_layers, cfg.vocab_size, cfg.d_ff
    p_matmul = L * (4 * d * d + 2 * d * ff) + d * V  # lm_head; embed=gather
    flops_step = 6 * (B * S) * p_matmul + 0.5 * 12 * L * B * S * S * d
    peak = PEAK_BF16.get(jax.devices()[0].device_kind) if on_tpu else None
    mfu = (flops_step * steps / dt) / (peak * n_dev) if peak else None
    return {"tokens_per_sec": round(tokens_per_sec, 1),
            "mfu": round(mfu, 4) if mfu is not None else None}


def bench_transformer(on_tpu: bool) -> dict:
    """Causal LM train step at TWO scales.

    Base = the r4 comparison config (d_model 1024). Large = d_model 2048
    with remat — doc/perf_notes_r4.md diagnosed the remaining base-config
    gap as modest-M GEMM efficiency and predicted MFU climbs as the
    GEMMs widen; `mfu_large` is that prediction measured."""
    n_dev = len(jax.devices())
    if on_tpu:
        base = _measure_lm(dict(vocab_size=32768, d_model=1024,
                                n_heads=16, n_layers=8, d_ff=4096,
                                max_len=1024, dtype=jnp.bfloat16),
                           B=16 * n_dev, S=1024, steps=16, on_tpu=True)
        # no remat: the 0.47B state + activations at B=8 fit v5e HBM,
        # and remat's ~25% recompute would depress measured MFU
        # (measured r5: remat 0.512, no-remat 0.645, B=16 0.638)
        large = _measure_lm(dict(vocab_size=32768, d_model=2048,
                                 n_heads=16, n_layers=8, d_ff=8192,
                                 max_len=1024, dtype=jnp.bfloat16),
                            B=8 * n_dev, S=1024, steps=8, on_tpu=True)
    else:
        base = _measure_lm(dict(vocab_size=256, d_model=64, n_heads=4,
                                n_layers=2, d_ff=128, max_len=128,
                                dtype=jnp.float32),
                           B=2 * n_dev, S=64, steps=2, on_tpu=False)
        large = _measure_lm(dict(vocab_size=256, d_model=128, n_heads=4,
                                 n_layers=2, d_ff=256, max_len=128,
                                 dtype=jnp.float32, remat=True),
                            B=2 * n_dev, S=64, steps=2, on_tpu=False)
    return {"tokens_per_sec": base["tokens_per_sec"], "mfu": base["mfu"],
            "tokens_per_sec_large": large["tokens_per_sec"],
            "mfu_large": large["mfu"]}


def _median_run(fn, n: int = 3) -> tuple:
    """Run a (rate, aux) measurement n times; return the median-rate
    run's (rate, aux) + [min, max] spread. Wire-touching numbers through
    this harness's tunnel are volatile (r5 captured 57-92 img/s across
    rounds on one path); a single trial is not an artifact of record."""
    runs = [fn() for _ in range(n)]
    runs.sort(key=lambda r: r[0])
    rate, aux = runs[n // 2]
    return rate, aux, [round(runs[0][0], 1), round(runs[-1][0], 1)]


def bench_distill(on_tpu: bool) -> dict:
    """Distill numbers: co-located e2e + the two bounds that support the
    disaggregated headline on hardware this harness doesn't have.

    Every wire-touching number is a MEDIAN OF 3 runs with [min, max]
    spread — the serving path rides real TCP + the host<->chip tunnel,
    and the r5 driver capture proved single trials unstable.

    - e2e: student train + in-chip teacher over the real stack
      (DistillReader threads, TCP tensor wire, coalescing batcher) —
      the reference's co-located mode (README.md:71).
    - student CEILING: identical pipeline with a NOP teacher (the
      reference's _NOP_PREDICT_TEST trick, distill_worker.py:34-42) —
      what the student side sustains when teacher capacity is not the
      constraint, i.e. the disaggregated-mode upper bound per student.
    - teacher-only img/s: the TeacherServer driven by concurrent
      clients with no student training sharing the chip — per-chip
      teacher capacity, the other term of the >=1500 img/s v5e-8
      arithmetic (README.md:72; see BASELINE.md).
    Plus the batcher's coalescing histogram (batch_rows_mean) so the
    request-merging the design leans on is measured, not assumed."""
    from edl_tpu.data.pipeline import ArraySource, DataLoader
    from edl_tpu.distill.reader import DistillReader
    from edl_tpu.distill.teacher_server import TeacherServer
    from edl_tpu.models.resnet import ResNet50, ResNet50_vd, ResNetTiny
    from edl_tpu.parallel import mesh as mesh_lib
    from edl_tpu.train import classification as cls
    from edl_tpu.train.step import make_train_step

    n_dev = len(jax.devices())
    if on_tpu:
        student = ResNet50_vd(num_classes=1000, dtype=jnp.bfloat16)
        teacher = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
        # 12 timed steps x 3 runs: median-of-3 replaces the old single
        # 20-step trial — same wall budget, a spread in the artifact
        per_dev_batch, hw, classes, steps = 128, 224, 1000, 12
        source_n, teacher_bs = 256, 16
    else:
        student = ResNetTiny(num_classes=10, dtype=jnp.float32)
        teacher = ResNetTiny(num_classes=10, dtype=jnp.float32)
        per_dev_batch, hw, classes, steps = 8, 32, 10, 3
        # source must hold >= a few GLOBAL batches (8 per-dev x n_dev)
        source_n, teacher_bs = 64 * len(jax.devices()), 4

    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": n_dev}))
    batch_size = per_dev_batch * n_dev
    sharding = mesh_lib.data_sharding(mesh)

    # Teacher: jitted forward served over the TCP tensor wire, in-process
    # (same chip) with request coalescing across the reader's workers.
    tstate = cls.create_state(teacher, jax.random.PRNGKey(7),
                              (1, hw, hw, 3), optax.identity())

    serve_topk = 16 if classes > 16 else 4  # device-side top-k: at 1000
    # classes this shrinks the chip->host logit pull and the response
    # wire 62x (r5 lever; role model
    # /root/reference/python/paddle_edl/distill/distill_worker.py:203-226)

    @jax.jit
    def tforward(images):
        # uint8 over the wire; normalize on device (DALI recipe)
        images = normalize_uint8(images)
        variables = {"params": tstate.params}
        if tstate.batch_stats is not None:
            variables["batch_stats"] = tstate.batch_stats
        return tstate.apply_fn(variables, images, train=False)

    @jax.jit
    def tforward_topk(images):
        val, idx = jax.lax.top_k(
            tforward(images).astype(jnp.float32), serve_topk)
        return idx.astype(jnp.int32), val.astype(jnp.float16)

    def tpredict(feeds):
        # device arrays returned UNFETCHED (r6): jit dispatch is async,
        # so the batcher's complete stage pulls these to host while the
        # chip computes the NEXT coalesced batch — per-transfer latency
        # now hides under compute instead of needing the r5 packed
        # single-fetch trick.
        idx, val = tforward_topk(jnp.asarray(feeds["image"]))
        return {"logits.idx": idx, "logits.val": val}

    compressed_meta = {"logits": {"topk": serve_topk, "classes": classes,
                                  "values": "<f2"}}

    # Pre-compile every serving bucket OUTSIDE the serving path: a first
    # compile (tens of seconds on TPU) inside a predict RPC would blow the
    # client timeout and spiral into retries.
    for b in (teacher_bs, 2 * teacher_bs, 4 * teacher_bs):
        tpredict({"image": np.zeros((b, hw, hw, 3), np.uint8)})

    def fresh_student():
        return cls.create_state(student, jax.random.PRNGKey(0),
                                (1, hw, hw, 3),
                                optax.sgd(0.1, momentum=0.9, nesterov=True))

    def distill_loss(state, params, batch):
        # soft-label CE against the teacher's TOP-K logits (reference
        # recipe example/distill/resnet/train_with_fleet.py:254-259;
        # sparse targets from the compressed wire — the dense (B, C)
        # teacher tensor never exists on device)
        img = normalize_uint8(batch["image"])
        variables = {"params": params}
        if state.batch_stats is not None:
            variables["batch_stats"] = state.batch_stats
        logits, mutated = state.apply_fn(
            variables, img, train=True, mutable=["batch_stats"])
        loss = cls.sparse_distill_kl(logits, batch["logits.idx"],
                                     batch["logits.val"])
        return loss, {"batch_stats": mutated["batch_stats"]}

    step = make_train_step(distill_loss, donate=True)

    rng = np.random.default_rng(1)
    source = ArraySource({
        "image": rng.integers(0, 256, size=(source_n, hw, hw, 3),
                              dtype=np.uint8),
        "label": rng.integers(0, classes, size=(source_n,)).astype(np.int32),
    })
    loader = DataLoader(source, batch_size)

    wire_keys = ("image", "logits.idx", "logits.val")
    # r6 overlap knobs: requests kept in flight per teacher connection
    # (hides the serving round trip under student compute) and the
    # host->device double-buffer depth for the next distill batch
    pipe_depth = 8 if on_tpu else 4

    def student_run(predict_fn):
        """The full student pipeline against `predict_fn` as the
        teacher (fresh student state per run — the step donates it);
        returns (img/s, batcher stats)."""
        from edl_tpu.data.pipeline import prefetch_to_device
        state = fresh_student()
        server = TeacherServer(predict_fn, max_batch=4 * teacher_bs,
                               buckets=(teacher_bs, 2 * teacher_bs,
                                        4 * teacher_bs),
                               compressed_meta=compressed_meta).start()
        try:
            endpoint = f"127.0.0.1:{server.port}"

            def batches():
                epoch = 0
                while True:
                    yield from loader.epoch(epoch)
                    epoch += 1

            dreader = DistillReader(batches, feeds=("image",),
                                    predicts=("logits",),
                                    teachers=[endpoint],
                                    teacher_batch_size=teacher_bs,
                                    rpc_timeout=120.0,
                                    pipeline_depth=pipe_depth,
                                    compress_topk=serve_topk,
                                    sparse_predicts=True)
            it = dreader()
            wire_only = ({k: np.ascontiguousarray(v)
                          for k, v in b.items() if k in wire_keys}
                         for b in it)
            # double-buffered device_put: batch i+1 transfers while the
            # student trains on batch i
            staged = prefetch_to_device(wire_only, sharding, size=2)
            for _ in range(2):
                state, metrics = step(state, next(staged))
            _sync(metrics["loss"])

            t0 = time.perf_counter()
            for _ in range(steps):
                state, metrics = step(state, next(staged))
            _sync(metrics["loss"])
            dt = time.perf_counter() - t0
            stats = server.batcher.stats()
            staged.close()
            it.close()
            dreader.close()
        finally:
            server.stop()
        return steps * batch_size / dt, stats

    # -- teacher chip capacity: device-resident batches, no wire ----------
    # The serving numbers below ride the harness's host<->chip tunnel
    # (~2 orders slower than a TPU VM's DMA path); this is the chip-only
    # forward rate the BASELINE.md v5e-8 arithmetic uses.
    staged = jax.device_put(
        np.zeros((4 * teacher_bs, hw, hw, 3), np.uint8), sharding)
    _sync(jnp.sum(tforward(staged).astype(jnp.float32)))
    chip_steps = 3 * steps
    t0 = time.perf_counter()
    for _ in range(chip_steps):
        out = tforward(staged)
    _sync(jnp.sum(out.astype(jnp.float32)))
    # PER-CHIP: the staged batch is dp-sharded, so wall-clock rate is the
    # aggregate across n_dev chips
    teacher_chip = (chip_steps * 4 * teacher_bs
                    / (time.perf_counter() - t0) / n_dev)

    # -- e2e: real teacher sharing this chip (median of 3) ----------------
    imgs_per_sec, bstats, e2e_spread = _median_run(
        lambda: student_run(tpredict))

    # -- student-side ceiling: NOP teacher (reference _NOP_PREDICT_TEST) --
    def nop_predict(feeds):
        rows = len(feeds["image"])
        return {"logits.idx": np.zeros((rows, serve_topk), np.int32),
                "logits.val": np.zeros((rows, serve_topk), np.float16)}

    ceiling_imgs_per_sec, _, ceiling_spread = _median_run(
        lambda: student_run(nop_predict))

    # -- teacher-only capacity: concurrent clients, no student train ------
    import threading

    from edl_tpu.distill.teacher_server import TeacherClient

    from collections import deque

    def teacher_only_run():
        server = TeacherServer(tpredict, max_batch=4 * teacher_bs,
                               buckets=(teacher_bs, 2 * teacher_bs,
                                        4 * teacher_bs),
                               compressed_meta=compressed_meta).start()
        try:
            endpoint = f"127.0.0.1:{server.port}"
            n_clients, reqs_per_client = 4, max(4, 2 * steps)
            img = np.zeros((teacher_bs, hw, hw, 3), np.uint8)
            # warm the serving path end-to-end before timing
            c0 = TeacherClient(endpoint, timeout=120.0, expand=False)
            c0.predict({"image": img})
            c0.close()
            served, client_errs = [], []

            def client():
                # r6: pipelined — keep pipe_depth requests in flight per
                # connection so the wire decode/encode, coalesce, chip
                # compute, and host fetch stages all stay busy at once
                try:
                    c = TeacherClient(endpoint, timeout=120.0, expand=False,
                                      max_inflight=pipe_depth)
                    n = 0
                    handles = deque()
                    for _ in range(reqs_per_client):
                        if len(handles) >= pipe_depth:
                            n += len(
                                handles.popleft().result()["logits.idx"])
                        handles.append(c.predict_async({"image": img}))
                    while handles:
                        n += len(handles.popleft().result()["logits.idx"])
                    c.close()
                    served.append(n)
                except Exception as exc:  # noqa: BLE001 — re-raised below
                    client_errs.append(exc)

            threads = [threading.Thread(target=client)
                       for _ in range(n_clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            tdt = time.perf_counter() - t0
            if client_errs or len(served) != n_clients:
                # a silently-dead client would deflate the published number
                raise RuntimeError(
                    f"teacher bench client failure ({len(served)}/"
                    f"{n_clients} finished): {client_errs[:1]}")
            return sum(served) / tdt, server.batcher.stats()
        finally:
            server.stop()

    teacher_imgs_per_sec, serving_stats, teacher_spread = _median_run(
        teacher_only_run)

    per_accel = imgs_per_sec / n_dev
    return {"imgs_per_sec": round(imgs_per_sec, 1),
            "imgs_per_sec_spread": e2e_spread,
            "vs_colocated_baseline": round(per_accel / (656.0 / 8.0), 3),
            "student_ceiling_imgs_per_sec": round(ceiling_imgs_per_sec, 1),
            "student_ceiling_spread": ceiling_spread,
            "teacher_imgs_per_sec": round(teacher_imgs_per_sec, 1),
            "teacher_imgs_per_sec_spread": teacher_spread,
            "teacher_chip_imgs_per_sec": round(teacher_chip, 1),
            "coalesce_batch_rows_mean": bstats.get("batch_rows_mean", 0.0),
            "coalesce_batch_rows_hist": bstats.get("batch_rows_hist", {}),
            # r6 overlap observability: reader in-flight depth per
            # connection, the server's adaptive coalescing window and
            # intake high-water mark — both for the e2e run and the
            # teacher-only serving run
            "pipeline_depth": pipe_depth,
            "coalesce_window_ms": bstats.get("coalesce_window_ms", 0.0),
            "pending_hwm": bstats.get("pending_hwm", 0),
            "serving_batch_rows_mean":
                serving_stats.get("batch_rows_mean", 0.0),
            "serving_pending_hwm": serving_stats.get("pending_hwm", 0),
            # response-direction bytes per image: dense fp32 classes vs
            # the served top-k (int32 idx + fp16 val)
            "wire_logits_bytes_dense": classes * 4,
            "wire_logits_bytes": serve_topk * 6,
            "serve_topk": serve_topk}


def bench_hybrid_mesh(on_tpu: bool) -> dict:
    """Hybrid ICI×DCN mesh vs flat mesh step time on the SAME devices.

    The dp gradient allreduce is the one collective allowed to cross the
    slice boundary (parallel/mesh.make_hybrid_mesh); this times a
    dp-only ResNet train step on the flat mesh vs the 2-slice hybrid
    layout. On real multi-slice TPU the hybrid layout is the comms win
    (per-layer collectives never touch DCN); on a single-slice chip or
    the CPU test world both layouts ride the same links, so PARITY
    (ratio ~1.0) is the expected — and still load-bearing — result: it
    proves the hybrid permutation costs nothing when there is no DCN to
    avoid."""
    from edl_tpu.models.resnet import ResNetTiny
    from edl_tpu.parallel import mesh as mesh_lib
    from edl_tpu.train import classification as cls
    from edl_tpu.train.step import make_train_step

    n_dev = len(jax.devices())
    if n_dev < 2 or n_dev % 2:
        return {"flat_step_ms": None, "hybrid_step_ms": None,
                "hybrid_vs_flat_step_ratio": None, "n_slices": 1}
    per_dev_batch, hw, classes, steps = (32, 64, 100, 8) if on_tpu \
        else (8, 32, 10, 4)
    model = ResNetTiny(num_classes=classes,
                       dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    rng = np.random.default_rng(3)
    batch_np = {
        "image": rng.integers(0, 256, size=(per_dev_batch * n_dev, hw, hw,
                                            3), dtype=np.uint8),
        "label": rng.integers(0, classes,
                              size=(per_dev_batch * n_dev,)).astype(
                                  np.int32)}
    step = cls.make_classification_step(classes, smoothing=0.1,
                                        donate=False)

    def timed(mesh) -> float:
        state = cls.create_state(model, jax.random.PRNGKey(0),
                                 (1, hw, hw, 3),
                                 optax.sgd(0.1, momentum=0.9))
        batch = mesh_lib.shard_batch(mesh, batch_np)
        for _ in range(2):
            state, metrics = step(state, batch)
        _sync(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, batch)
        _sync(metrics["loss"])
        return (time.perf_counter() - t0) / steps * 1e3

    spec = mesh_lib.MeshSpec({"dp": -1})
    topo = mesh_lib.SliceTopology(2, n_dev // 2)
    flat_ms = timed(mesh_lib.make_mesh(spec))
    hybrid_mesh = mesh_lib.make_hybrid_mesh(spec, topo)
    hybrid_ms = timed(hybrid_mesh)
    # the DCN-aware gradient path on the same hybrid layout: bucketed
    # reductions (manual hierarchical decomposition) instead of XLA's
    # single fused reduction — the r21 default for multi-slice worlds,
    # so the headline ratio is REFRESHED against it (the plain-jit
    # hybrid number stays alongside)
    from edl_tpu.train.comm import CommConfig
    comm_step = cls.make_classification_step(
        classes, smoothing=0.1, donate=False,
        comm=CommConfig(bucket_mb=4.0), mesh=hybrid_mesh, topology=topo)

    def timed_comm() -> float:
        state = cls.create_state(model, jax.random.PRNGKey(0),
                                 (1, hw, hw, 3),
                                 optax.sgd(0.1, momentum=0.9))
        batch = mesh_lib.shard_batch(hybrid_mesh, batch_np)
        for _ in range(2):
            state, metrics = comm_step(state, batch)
        _sync(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = comm_step(state, batch)
        _sync(metrics["loss"])
        return (time.perf_counter() - t0) / steps * 1e3

    hybrid_comm_ms = timed_comm()
    return {"flat_step_ms": round(flat_ms, 2),
            "hybrid_step_ms": round(hybrid_ms, 2),
            "hybrid_comm_step_ms": round(hybrid_comm_ms, 2),
            "hybrid_vs_flat_step_ratio": round(flat_ms / hybrid_comm_ms,
                                               3),
            "hybrid_vs_flat_step_ratio_jit": round(flat_ms / hybrid_ms,
                                                   3),
            "n_slices": 2}


def bench_dcn_comm(on_tpu: bool) -> dict:
    """The DCN-aware gradient path behind its loss-parity gate.

    Reports the cross-slice wire accounting (bytes one chip contributes
    per step under dense / topk / int8) and the bucketed schedule's
    overlap headroom — but ONLY after the gate passes: bucketed-dense
    must be BITWISE with the jit path on the flat dryrun world, and the
    compressed path must hold the loss envelope (comm.loss_parity_gate).
    A failed gate nulls the byte metrics instead of reporting numbers a
    diverging trainer would invalidate.

    On the CPU harness every byte rides the same host links — the step
    times are schedule-cost parity checks (the manual path must not be
    slower than jit by more than the measurement noise), and
    `dcn_overlap_pct` is the SCHEDULE property (share of DCN bytes
    dispatchable before backward completes), not a measured overlap —
    real overlap needs a profiler on real DCN.
    """
    from flax.core import meta

    from edl_tpu.models.transformer import (Transformer,
                                            TransformerConfig, lm_loss_fn)
    from edl_tpu.parallel import mesh as mesh_lib
    from edl_tpu.train import comm
    from edl_tpu.train.state import TrainState
    from edl_tpu.train.step import make_train_step

    n_dev = len(jax.devices())
    if n_dev < 2 or n_dev % 2:
        return {"dcn_bytes_per_step": None, "dcn_overlap_pct": None,
                "dcn_bytes_reduction_topk_x": None,
                "comm_gate_ok": None}
    if on_tpu:
        dim, layers, vocab, seq, B, steps = 512, 4, 4096, 256, 8, 8
        bucket_mb = 4.0
    else:
        dim, layers, vocab, seq, B, steps = 64, 2, 128, 32, 4, 4
        bucket_mb = 0.05  # CPU-scale model: still exercises multi-bucket
    cfg = TransformerConfig(vocab_size=vocab, d_model=dim,
                            n_heads=4, n_layers=layers, d_ff=dim * 4,
                            max_len=seq,
                            dtype=jnp.bfloat16 if on_tpu
                            else jnp.float32, mesh=None)
    model = Transformer(cfg)
    rng = np.random.default_rng(5)
    toks = rng.integers(0, vocab, size=(B * n_dev, seq)).astype(np.int32)
    variables = meta.unbox(model.init(jax.random.PRNGKey(0),
                                      jnp.asarray(toks), train=False))
    import optax as _optax
    state = TrainState.create(apply_fn=model.apply,
                              params=variables["params"],
                              tx=_optax.sgd(0.1, momentum=0.9))
    batch = {"tokens": toks}
    topo = mesh_lib.SliceTopology(2, n_dev // 2)
    flat = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": -1}))
    hybrid = mesh_lib.make_hybrid_mesh(mesh_lib.MeshSpec({"dp": -1}),
                                       topo)
    # topk at 1/8 density: k*(4B val + 4B idx) vs m*4B dense = exactly
    # 4x fewer DCN bytes — the acceptance floor
    topk_cfg = comm.CommConfig(bucket_mb=bucket_mb, compress="topk",
                               topk_frac=0.125, min_compress_elems=64)
    # gate 1: bucketed-dense BITWISE with jit on the flat dryrun world
    gate = comm.loss_parity_gate(lm_loss_fn, state, batch, mesh=flat,
                                 config=comm.CommConfig(
                                     bucket_mb=bucket_mb), steps=3)
    # gate 2: hybrid hierarchical-dense loss parity vs the jit path on
    # the same hybrid mesh (a re-associated sum, not a semantic change)
    # + gate 3: the compressed wire's TRANSIENT loss envelope on the
    # deployment topology (2 slices — where the DCN leg exists): 0.1
    # nat per probe step on an unlearnable random-token batch (~2% of
    # the ~4.9 loss). The convergence-level guarantee is the CI
    # smoke's relative envelope (python -m edl_tpu.train.comm smoke).
    hgate = comm.loss_parity_gate(lm_loss_fn, state, batch, mesh=hybrid,
                                  config=topk_cfg, topology=topo,
                                  steps=3, envelope=1e-1)
    hybrid_loss_parity = bool(hgate["bitwise_dense"]
                              or hgate["dense_loss_delta"] <= 1e-4)

    def timed(step_fn, mesh) -> float:
        s = jax.tree.map(lambda a: jax.device_put(
            a, jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec())), state)
        placed = mesh_lib.shard_batch(mesh, batch)
        for _ in range(2):
            s, m = step_fn(s, placed)
        _sync(m["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            s, m = step_fn(s, placed)
        _sync(m["loss"])
        return (time.perf_counter() - t0) / steps * 1e3

    jit_ms = timed(make_train_step(lm_loss_fn, donate=False), flat)
    mk = lambda mode, mesh_, topo_: comm.make_comm_train_step(  # noqa: E731
        lm_loss_fn, mesh=mesh_, topology=topo_, donate=False,
        config=comm.CommConfig(bucket_mb=bucket_mb, compress=mode,
                               topk_frac=0.125, min_compress_elems=64))
    dense_step = mk("off", hybrid, topo)
    dense_ms = timed(dense_step, hybrid)
    topk_step = mk("topk", hybrid, topo)
    topk_ms = timed(topk_step, hybrid)
    int8_step = mk("int8", hybrid, topo)
    int8_ms = timed(int8_step, hybrid)

    gate_ok = bool(gate["ok"] and hybrid_loss_parity
                   and hgate.get("loss_envelope_ok"))
    dense_bytes = dense_step.dcn_bytes_per_step()
    topk_bytes = topk_step.dcn_bytes_per_step()
    int8_bytes = int8_step.dcn_bytes_per_step()
    out = {
        "comm_gate_ok": gate_ok,
        "comm_parity_bitwise_dense": bool(gate["bitwise_dense"]),
        "comm_loss_envelope_ok": bool(hgate.get("loss_envelope_ok")),
        "comm_hybrid_loss_parity": hybrid_loss_parity,
        "comm_jit_step_ms": round(jit_ms, 2),
        "comm_bucketed_step_ms": round(dense_ms, 2),
        "comm_topk_step_ms": round(topk_ms, 2),
        "comm_int8_step_ms": round(int8_ms, 2),
        "comm_buckets": dense_step.plan.n_buckets,
    }
    if gate_ok:
        out.update({
            "dcn_bytes_per_step": dense_bytes,
            "dcn_bytes_per_step_topk": topk_bytes,
            "dcn_bytes_per_step_int8": int8_bytes,
            "dcn_bytes_reduction_topk_x": round(
                dense_bytes / max(topk_bytes, 1), 2),
            "dcn_bytes_reduction_int8_x": round(
                dense_bytes / max(int8_bytes, 1), 2),
            "dcn_overlap_pct": topk_step.dcn_overlap_pct(),
        })
    else:
        out.update({"dcn_bytes_per_step": None,
                    "dcn_bytes_per_step_topk": None,
                    "dcn_bytes_per_step_int8": None,
                    "dcn_bytes_reduction_topk_x": None,
                    "dcn_bytes_reduction_int8_x": None,
                    "dcn_overlap_pct": None})
    return out


def bench_moe(on_tpu: bool) -> dict:
    """Expert-parallel dispatch behind its parity gate.

    MoE twin of bench_dcn_comm: a top-2 capacity-factor router over
    E = 2 x world expert FFNs, trained through the hierarchical
    all-to-all (ICI leg + cross-slice DCN leg, doc/design_comm.md).
    Throughput and byte numbers report ONLY after comm.moe_parity_gate
    passes: hier/off must be BITWISE with the flat single-collective
    dispatch through real optimizer steps, and the int8 DCN leg must
    hold the loss envelope. A failed gate nulls the wire metrics.

    The resize row times an ep world change UNDER LOAD: the trained
    expert tables are saved as ep-sharded checkpoint leaves, resharded
    onto the half world through the same planner the migration plane
    rides (train/sharded_checkpoint.py — the in-process analogue of
    bench_resize_reform's multi-pod ladder), grafted back into a live
    step, and the first post-resize step is clocked; the restored
    tables are asserted bitwise against the donors.

    CPU-harness caveats match bench_dcn_comm: step times are schedule
    costs (every byte rides host links), `moe_dispatch_overlap_pct`
    is the SCHEDULE property (legs dispatchable before the final
    combine), bytes columns are exact wire accounting either way.
    """
    import dataclasses
    import functools
    import shutil
    import sys
    import tempfile

    from flax.core import meta

    from edl_tpu.models.transformer import (Transformer,
                                            TransformerConfig,
                                            lm_loss_moe)
    from edl_tpu.parallel import mesh as mesh_lib
    from edl_tpu.train import comm
    from edl_tpu.train import sharded_checkpoint as sc
    from edl_tpu.train.state import TrainState
    from edl_tpu.train.step import make_train_step

    NULL_KEYS = ("moe_tokens_per_sec", "moe_dcn_bytes_per_step",
                 "moe_dcn_bytes_per_step_int8",
                 "moe_dcn_bytes_reduction_int8_x",
                 "moe_dispatch_overlap_pct",
                 "moe_ep_resize_s", "moe_ep_resize_bitwise")
    n_dev = len(jax.devices())
    if n_dev < 2 or n_dev % 2:
        return {"moe_gate_ok": None, **{k: None for k in NULL_KEYS}}
    if on_tpu:
        dim, layers, vocab, seq, B, steps = 256, 2, 4096, 128, 8, 8
        bucket_mb = 4.0
    else:
        # bucket at 0.25 MiB (not bench_dcn_comm's 0.05): the system
        # under test is the DISPATCH wire; sub-bucket-sized gradient
        # shards compile to different reduce schedules across the
        # flat/hier programs on CPU XLA and break the bitwise gate
        dim, layers, vocab, seq, B, steps = 64, 2, 128, 32, 4, 4
        bucket_mb = 0.25
    cfg = TransformerConfig(vocab_size=vocab, d_model=dim, n_heads=4,
                            n_layers=layers, d_ff=dim * 4, max_len=seq,
                            dtype=jnp.bfloat16 if on_tpu
                            else jnp.float32, mesh=None, moe=True,
                            n_experts=2 * n_dev, moe_top_k=2)
    model = Transformer(cfg)
    rng = np.random.default_rng(7)
    toks = rng.integers(0, vocab, size=(B * n_dev, seq)).astype(np.int32)
    variables = meta.unbox(model.init(jax.random.PRNGKey(7),
                                      jnp.asarray(toks), train=False))
    import optax as _optax
    state = TrainState.create(apply_fn=model.apply,
                              params=variables["params"],
                              tx=_optax.sgd(0.1, momentum=0.9))
    batch = {"tokens": toks}

    def loss_factory(wire):
        wired = Transformer(dataclasses.replace(cfg, moe_wire=wire))
        return functools.partial(lm_loss_moe,
                                 aux_weight=cfg.moe_aux_weight,
                                 apply_fn=wired.apply)

    topo = mesh_lib.SliceTopology(2, n_dev // 2)
    mesh = mesh_lib.make_hybrid_mesh(mesh_lib.MeshSpec({"ep": -1}),
                                     topo)
    comm_cfg = comm.CommConfig(bucket_mb=bucket_mb)
    # gate first: hier/off bitwise with flat + int8 leg inside the
    # envelope, through real steps on the deployment topology
    gate = comm.moe_parity_gate(
        loss_factory, state, batch, mesh=mesh, topology=topo,
        comm_config=comm_cfg,
        moe_config=comm.MoEDispatchConfig(mode="hier", compress="int8"),
        steps=3, envelope=0.1)
    gate_ok = bool(gate["ok"])

    def timed(step_fn, mesh_, batch_):
        s = jax.tree.map(lambda a: jax.device_put(
            a, jax.sharding.NamedSharding(
                mesh_, jax.sharding.PartitionSpec())), state)
        placed = mesh_lib.shard_batch(mesh_, batch_,
                                      batch_axes=("ep",))
        for _ in range(2):
            s, m = step_fn(s, placed)
        _sync(m["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            s, m = step_fn(s, placed)
        _sync(m["loss"])
        return (time.perf_counter() - t0) / steps * 1e3, s

    # jit-dense reference: routes per GLOBAL batch (different capacity
    # semantics than the per-chip manual path) — timing anchor only
    jit_loss = functools.partial(lm_loss_moe,
                                 aux_weight=cfg.moe_aux_weight)
    jit_ms, _ = timed(make_train_step(jit_loss, donate=False), mesh,
                      batch)
    mk = lambda mode, compress: comm.make_moe_comm_step(  # noqa: E731
        loss_factory, mesh=mesh, topology=topo, donate=False,
        config=comm_cfg,
        moe_config=comm.MoEDispatchConfig(mode=mode, compress=compress))
    flat_step = mk("flat", "off")
    flat_ms, _ = timed(flat_step, mesh, batch)
    hier_step = mk("hier", "off")
    hier_ms, _ = timed(hier_step, mesh, batch)
    int8_step = mk("hier", "int8")
    int8_ms, s_final = timed(int8_step, mesh, batch)

    out = {
        "moe_gate_ok": gate_ok,
        "moe_parity_bitwise_hier": bool(gate["bitwise_hier"]),
        "moe_loss_envelope_ok": bool(gate.get("loss_envelope_ok")),
        "moe_experts": cfg.n_experts,
        "moe_jit_step_ms": round(jit_ms, 2),
        "moe_flat_step_ms": round(flat_ms, 2),
        "moe_hier_step_ms": round(hier_ms, 2),
        "moe_int8_step_ms": round(int8_ms, 2),
    }
    if not gate_ok:
        out.update({k: None for k in NULL_KEYS})
        return out

    flat_bytes = flat_step.moe_dcn_bytes_per_step()
    int8_bytes = int8_step.moe_dcn_bytes_per_step()
    out.update({
        # deployment path (hier + int8 DCN leg) end-to-end token rate
        "moe_tokens_per_sec": round(B * n_dev * seq / (int8_ms / 1e3),
                                    1),
        "moe_dcn_bytes_per_step": flat_bytes,
        "moe_dcn_bytes_per_step_int8": int8_bytes,
        "moe_dcn_bytes_reduction_int8_x": round(
            flat_bytes / max(int8_bytes, 1), 2),
        "moe_dispatch_overlap_pct": int8_step.moe_dispatch_overlap_pct(),
        "moe_ep_resize_s": None,
        "moe_ep_resize_bitwise": None,
    })

    # -- ep resize under load: full world -> half world ----------------
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P
    half = n_dev // 2
    tgt_mesh = Mesh(np.array(jax.devices()[:half]), ("ep",))

    def _path_key(path) -> str:
        return "/".join(str(getattr(p, "key", p)) for p in path)

    flat_params, treedef = jax.tree_util.tree_flatten_with_path(
        s_final.params)
    tables = {_path_key(p): leaf for p, leaf in flat_params
              if "moe_mlp" in _path_key(p)
              and _path_key(p).rsplit("/", 1)[-1] in ("w_in", "w_out")}
    # checkpoint representation: expert tables are ep-sharded leaves
    src = {k: jax.device_put(v, NamedSharding(mesh, P("ep")))
           for k, v in tables.items()}
    half_step = comm.make_moe_comm_step(
        loss_factory, mesh=tgt_mesh, topology=None, donate=False,
        config=comm_cfg,
        moe_config=comm.MoEDispatchConfig(mode="hier", compress="int8"))
    tmp = tempfile.mkdtemp(prefix="bench_moe_resize_")
    try:
        t0 = time.perf_counter()
        sc.save_sharded(tmp, src)
        tgt = {k: jax.device_put(np.zeros(v.shape, v.dtype),
                                 NamedSharding(tgt_mesh, P("ep")))
               for k, v in tables.items()}
        restored = sc.restore_sharded(tmp, tgt)
        host = {k: np.asarray(v) for k, v in restored.items()}
        # graft the resharded tables into the surviving step's state
        grafted = jax.tree_util.tree_unflatten(
            treedef, [host.get(_path_key(p), leaf)
                      for p, leaf in flat_params])
        s2 = jax.tree.map(
            lambda a: jax.device_put(np.asarray(a),
                                     NamedSharding(tgt_mesh, P())),
            s_final.replace(params=grafted))
        placed = mesh_lib.shard_batch(tgt_mesh,
                                      {"tokens": toks[:B * half]},
                                      batch_axes=("ep",))
        s2, m = half_step(s2, placed)
        _sync(m["loss"])
        out["moe_ep_resize_s"] = round(time.perf_counter() - t0, 3)
        out["moe_ep_resize_bitwise"] = bool(all(
            np.array_equal(host[k], np.asarray(v))
            for k, v in tables.items()))
    except (OSError, ValueError, TypeError) as exc:
        print(f"moe resize bench failed: {exc}", file=sys.stderr)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def bench_distill_churn(on_tpu: bool) -> dict:
    """Distill throughput UNDER teacher churn (VERDICT r5 ask #6).

    Two live teachers; after a steady phase one is KILLED mid-run (its
    in-flight tasks requeue to the survivor — invariant D3), then
    RE-ADDED on the same endpoint (the manage thread reconnects on its
    next tick). Reports the steady rate, the post-kill dip, and how many
    seconds until a full measurement window is back within 80% of
    steady — the reference's elastic-distill headline is exactly this
    scenario (40-teacher pool under churn)."""
    from edl_tpu.data.pipeline import ArraySource, DataLoader
    from edl_tpu.distill.reader import DistillReader
    from edl_tpu.distill.teacher_server import TeacherServer
    from edl_tpu.models.resnet import ResNetTiny
    from edl_tpu.parallel import mesh as mesh_lib
    from edl_tpu.train import classification as cls
    from edl_tpu.train.step import make_train_step

    n_dev = len(jax.devices())
    hw, classes, serve_topk, teacher_bs = 32, 10, 4, 4
    per_dev_batch = 8
    steady_steps, churn_steps, rejoin_steps = (8, 10, 10) if on_tpu \
        else (6, 6, 6)
    batch_size = per_dev_batch * n_dev
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": n_dev}))
    sharding = mesh_lib.data_sharding(mesh)

    teacher = ResNetTiny(num_classes=classes, dtype=jnp.float32)
    tstate = cls.create_state(teacher, jax.random.PRNGKey(7),
                              (1, hw, hw, 3), optax.identity())

    @jax.jit
    def tforward_topk(images):
        images = normalize_uint8(images)
        variables = {"params": tstate.params}
        if tstate.batch_stats is not None:
            variables["batch_stats"] = tstate.batch_stats
        val, idx = jax.lax.top_k(
            tstate.apply_fn(variables, images,
                            train=False).astype(jnp.float32), serve_topk)
        return idx.astype(jnp.int32), val.astype(jnp.float16)

    def tpredict(feeds):
        idx, val = tforward_topk(jnp.asarray(feeds["image"]))
        return {"logits.idx": idx, "logits.val": val}

    compressed_meta = {"logits": {"topk": serve_topk, "classes": classes,
                                  "values": "<f2"}}
    for b in (teacher_bs, 2 * teacher_bs, 4 * teacher_bs):
        tpredict({"image": np.zeros((b, hw, hw, 3), np.uint8)})

    def new_server(port=0):
        return TeacherServer(tpredict, port=port, max_batch=4 * teacher_bs,
                             buckets=(teacher_bs, 2 * teacher_bs,
                                      4 * teacher_bs),
                             compressed_meta=compressed_meta).start()

    server_a, server_b = new_server(), new_server()
    port_a = server_a.port
    endpoints = [f"127.0.0.1:{port_a}", f"127.0.0.1:{server_b.port}"]

    rng = np.random.default_rng(4)
    source = ArraySource({
        "image": rng.integers(0, 256, size=(8 * batch_size, hw, hw, 3),
                              dtype=np.uint8),
        "label": rng.integers(0, classes,
                              size=(8 * batch_size,)).astype(np.int32)})
    loader = DataLoader(source, batch_size)

    student = ResNetTiny(num_classes=classes, dtype=jnp.float32)
    state = cls.create_state(student, jax.random.PRNGKey(0), (1, hw, hw, 3),
                             optax.sgd(0.1, momentum=0.9))

    def distill_loss(state, params, batch):
        img = normalize_uint8(batch["image"])
        variables = {"params": params}
        if state.batch_stats is not None:
            variables["batch_stats"] = state.batch_stats
        logits, mutated = state.apply_fn(
            variables, img, train=True, mutable=["batch_stats"])
        loss = cls.sparse_distill_kl(logits, batch["logits.idx"],
                                     batch["logits.val"])
        return loss, {"batch_stats": mutated["batch_stats"]}

    step = make_train_step(distill_loss, donate=False)

    def batches():
        epoch = 0
        while True:
            yield from loader.epoch(epoch)
            epoch += 1

    dreader = DistillReader(batches, feeds=("image",), predicts=("logits",),
                            teachers=endpoints,
                            teacher_batch_size=teacher_bs,
                            rpc_timeout=60.0, pipeline_depth=4,
                            manage_interval=0.2, compress_topk=serve_topk,
                            sparse_predicts=True)
    wire_keys = ("image", "logits.idx", "logits.val")
    it = dreader()
    total = steady_steps + churn_steps + rejoin_steps
    stamps = []   # perf_counter after each SYNCED step
    t_kill = t_rejoin = None
    try:
        # warmup/compile outside the timeline
        b = {k: v for k, v in next(it).items() if k in wire_keys}
        state, metrics = step(state, mesh_lib.shard_batch(mesh, b))
        _sync(metrics["loss"])
        stamps.append(time.perf_counter())
        for i in range(total):
            if i == steady_steps:
                server_a.stop()          # teacher killed mid-run
                t_kill = time.perf_counter()
            if i == steady_steps + churn_steps:
                server_a = new_server(port_a)   # re-added, same endpoint
                t_rejoin = time.perf_counter()
            b = {k: v for k, v in next(it).items() if k in wire_keys}
            state, metrics = step(state, mesh_lib.shard_batch(mesh, b))
            _sync(metrics["loss"])
            stamps.append(time.perf_counter())
    finally:
        it.close()
        dreader.close()
        server_a.stop()
        server_b.stop()

    rates = [batch_size / (b - a) for a, b in zip(stamps, stamps[1:])]
    steady = float(np.median(rates[:steady_steps]))
    dip = float(min(rates[steady_steps:]))
    # recovery: first post-kill step whose rate is back within 80% of
    # steady; its timestamp minus the kill instant
    recovery_s = None
    for i in range(steady_steps, total):
        if rates[i] >= 0.8 * steady:
            recovery_s = stamps[i + 1] - t_kill
            break
    return {"steady_imgs_per_sec": round(steady, 1),
            "dip_imgs_per_sec": round(dip, 1),
            "recovery_s": round(recovery_s, 2)
            if recovery_s is not None else None,
            "kill_to_rejoin_s": round(t_rejoin - t_kill, 2),
            "post_rejoin_imgs_per_sec": round(
                float(np.median(rates[steady_steps + churn_steps:])), 1)}


def bench_checkpoint(on_tpu: bool) -> dict:
    """Checkpoint-plane stall: sync full-save vs async snapshot-then-write
    on the SAME resnet train state bench_resnet measures (the price of
    elasticity is paid per save — this is what the step loop sees).

    - `ckpt_save_stall_ms_sync`: the legacy epoch-end path — serialize +
      write + seal, all on the step loop (the sync baseline, captured in
      the same artifact as the async number);
    - `ckpt_save_stall_ms`: save_async — the loop blocks only for the
      device->host snapshot copy; serialization/write/seal ride the
      background writer (`ckpt_write_s`, overlapped);
    - `ckpt_restore_s`: restore wall time (parallel chunk-region reads);
    - `ckpt_bitwise_identical`: sync and async state.msgpack bytes match.
    Note the 1-core bench host: the win is the step-loop STALL shrinking
    to the copy, not wall-clock write overlap (no spare core to write on).
    """
    import shutil as _shutil
    import tempfile as _tempfile

    from edl_tpu.models.resnet import ResNet50_vd
    from edl_tpu.train import classification as cls
    from edl_tpu.train.checkpoint import CheckpointManager
    from edl_tpu.train.state import TrainStatus

    # The REAL resnet headline state both on TPU and in the CPU harness
    # (ResNetTiny's ~1MB state is all fixed fetch cost, no serialize
    # cost — it would understate the stall the async path removes); the
    # CPU world only shrinks the init resolution, params are identical.
    model = ResNet50_vd(num_classes=1000,
                        dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    hw = 224 if on_tpu else 32
    state = cls.create_state(model, jax.random.PRNGKey(0), (1, hw, hw, 3),
                             optax.sgd(0.1, momentum=0.9, nesterov=True))
    state_mb = sum(np.asarray(x).nbytes
                   for x in jax.tree.leaves(state)) / 2**20
    status = TrainStatus(epoch=0, step=1)
    root = _tempfile.mkdtemp(prefix="edl-ckpt-bench-")
    try:
        sync_dir, async_dir = os.path.join(root, "s"), os.path.join(root, "a")

        def median(xs):
            xs = sorted(xs)
            return xs[len(xs) // 2]

        # sync: full serialize+write stall, median of 3 (fresh manager /
        # dir per trial so every save writes version 0's full payload)
        sync_ms, async_ms, write_s = [], [], []
        for trial in range(3):
            mgr = CheckpointManager(f"{sync_dir}{trial}", process_index=0)
            t0 = time.perf_counter()
            mgr.save(state, status)
            sync_ms.append((time.perf_counter() - t0) * 1e3)

            mgr = CheckpointManager(f"{async_dir}{trial}", process_index=0)
            t0 = time.perf_counter()
            mgr.save_async(state, status)
            async_ms.append((time.perf_counter() - t0) * 1e3)
            mgr.close()
            write_s.append(mgr.stats()["write_s_last"])

        # restore (parallel chunk-region reads happen in sharded mode;
        # replicated restore is one msgpack read — time it regardless)
        mgr = CheckpointManager(f"{async_dir}0", process_index=0)
        fresh = cls.create_state(model, jax.random.PRNGKey(1), (1, hw, hw, 3),
                                 optax.sgd(0.1, momentum=0.9, nesterov=True))
        t0 = time.perf_counter()
        mgr.restore(fresh)
        restore_s = time.perf_counter() - t0

        with open(os.path.join(f"{sync_dir}0", "ckpt-0",
                               "state.msgpack"), "rb") as f:
            sync_bytes = f.read()
        with open(os.path.join(f"{async_dir}0", "ckpt-0",
                               "state.msgpack"), "rb") as f:
            async_bytes = f.read()
    finally:
        _shutil.rmtree(root, ignore_errors=True)
    sync_stall, async_stall = median(sync_ms), median(async_ms)
    return {"ckpt_save_stall_ms_sync": round(sync_stall, 3),
            "ckpt_save_stall_ms": round(async_stall, 3),
            "ckpt_stall_reduction_x": round(sync_stall
                                            / max(async_stall, 1e-9), 1),
            "ckpt_write_s": round(median(write_s), 4),
            "ckpt_restore_s": round(restore_s, 4),
            "ckpt_bitwise_identical": sync_bytes == async_bytes,
            "ckpt_state_mb": round(state_mb, 2)}


def bench_fused_opt(on_tpu: bool) -> dict:
    """Fused optimizer path (train/fused_opt.py): isolated update cost
    + resident/checkpoint byte cut, gated on the kernel parity report.

    - `opt_update_ms{,_fused,_int8}`: ms/step for the jitted
      apply_gradients alone (no fwd/bwd) on a ~2M-param world — the
      optax adamw chain vs the fused fp32 vs fused int8-moment path.
      On the CPU harness the fused columns run the jitted XLA fallback
      (the Pallas kernel is a TPU path), so they calibrate expression/
      schedule cost; the VMEM single-pass win is TPU-only.
    - `opt_state_bytes{,_int8}` + `opt_state_bytes_cut_x`: resident
      moment bytes (the >= 1.8x acceptance floor rides CI, this is the
      artifact number).
    - `opt_ckpt_state_bytes{,_int8}`: the SERIALIZED state payload
      (CheckpointManager state_bytes_last) — the same cut as it lands
      on disk.
    - `opt_resize_bytes_from_peers{,_int8}`: the donor-manifest bytes
      (sharded_checkpoint.snapshot_nbytes — exactly what
      restore_from_peers moves for a full joiner restore and what the
      donor advert quotes): the migration-wire half of the cut.
    - `opt_parity_ok`: update_parity_gate()["ok"] (fused-fp32 sgdm
      bitwise vs optax + kernel==XLA for every mode), the gate the
      numbers are meaningless without.
    """
    import shutil as _shutil
    import tempfile as _tempfile

    from edl_tpu.train import fused_opt as fo
    from edl_tpu.train.checkpoint import CheckpointManager
    from edl_tpu.train.state import TrainState, TrainStatus

    rng = np.random.default_rng(0)

    def leaf(*shape):
        return jnp.asarray(rng.normal(0, 0.02, size=shape)
                           .astype(np.float32))

    params = {f"w{i}": leaf(512, 512) for i in range(8)}
    params["tail"] = leaf(129)          # exercises lane padding
    grads = {k: leaf(*v.shape) for k, v in params.items()}

    def timed(tx):
        state = TrainState.create(
            apply_fn=None, params=jax.tree.map(jnp.copy, params), tx=tx)
        step = jax.jit(lambda s, g: s.apply_gradients(grads=g),
                       donate_argnums=(0,))
        state = step(state, grads)
        jax.block_until_ready(jax.tree.leaves(state))
        n = 20
        t0 = time.perf_counter()
        for _ in range(n):
            state = step(state, grads)
        jax.block_until_ready(jax.tree.leaves(state))
        return ((time.perf_counter() - t0) / n * 1e3,
                fo.opt_state_bytes(state.opt_state), state)

    dense_ms, dense_bytes, dense_state = timed(optax.adamw(1e-3))
    fused_ms, _, _ = timed(fo.fused_adam(1e-3, bucket_mb=4.0))
    int8_ms, int8_bytes, int8_state = timed(
        fo.fused_adam(1e-3, quant="int8", bucket_mb=4.0))

    # serialized payload, dense vs quantized moments (the disk/wire cut)
    from edl_tpu.train import sharded_checkpoint as _sc

    root = _tempfile.mkdtemp(prefix="edl-opt-bench-")
    try:
        ckpt_bytes, peer_bytes = {}, {}
        for name, st in (("dense", dense_state), ("int8", int8_state)):
            mgr = CheckpointManager(os.path.join(root, name),
                                    process_index=0)
            mgr.save(st, TrainStatus(epoch=0, step=1))
            ckpt_bytes[name] = mgr.stats()["state_bytes_last"]
            peer_bytes[name] = _sc.snapshot_nbytes(
                _sc.snapshot_host_tree(st))
    finally:
        _shutil.rmtree(root, ignore_errors=True)

    return {"opt_update_ms": round(dense_ms, 3),
            "opt_update_ms_fused": round(fused_ms, 3),
            "opt_update_ms_int8": round(int8_ms, 3),
            "opt_state_bytes": dense_bytes,
            "opt_state_bytes_int8": int8_bytes,
            "opt_state_bytes_cut_x": round(dense_bytes
                                           / max(int8_bytes, 1), 2),
            "opt_ckpt_state_bytes": ckpt_bytes["dense"],
            "opt_ckpt_state_bytes_int8": ckpt_bytes["int8"],
            "opt_resize_bytes_from_peers": peer_bytes["dense"],
            "opt_resize_bytes_from_peers_int8": peer_bytes["int8"],
            "opt_parity_ok": fo.update_parity_gate(steps=2)["ok"]}


def bench_elastic_downtime(on_tpu: bool) -> dict:
    """Elastic stop-resume downtime, measured for real: SIGKILL a
    training process mid-run (checkpoints every few steps, async), then
    respawn it and clock kill -> first post-restore optimizer step.

    `elastic_downtime_s` = process respawn + world re-formation + restore
    + re-compile + first step — the full price one membership change
    costs under the stop-resume elasticity model. The child is the
    elastic_demo trainer on CPU (hermetic: this harness's TPU tunnel
    plays no part), so the number calibrates the protocol overhead, not
    chip speed; `ckpt_restore_s` is parsed from the child's restore log
    line, and the child's final ckpt_stats JSON supplies the in-run
    save-stall seen under kill pressure.
    """
    import re
    import shutil as _shutil
    import signal
    import subprocess
    import sys
    import tempfile as _tempfile

    root = _tempfile.mkdtemp(prefix="edl-downtime-")
    ckpt_dir = os.path.join(root, "ckpt")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU tunnel
    env.update({"JAX_PLATFORMS": "cpu", "JAX_NUM_CPU_DEVICES": "1",
                "EDL_TPU_CHECKPOINT_PATH": ckpt_dir})
    ckpt_steps, step_time = 5, 0.05
    cmd = [sys.executable, "-m", "edl_tpu.examples.elastic_demo",
           "--epochs", "3", "--steps-per-epoch", "40",
           "--step-time", str(step_time), "--ckpt-steps", str(ckpt_steps)]

    def spawn(log_name):
        # cwd stays the repo root so the child imports this edl_tpu
        return subprocess.Popen(
            cmd, env=env, stdout=open(os.path.join(root, log_name), "wb"),
            stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.abspath(__file__)))

    def wait_for(pred, timeout, what):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if pred():
                return True
            time.sleep(0.05)
        raise TimeoutError(f"downtime bench: timeout waiting for {what}")

    def log_text(name):
        try:
            with open(os.path.join(root, name), "rb") as f:
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    victim = resumed = None
    try:
        victim = spawn("run1.log")
        # let it train past a couple of sealed mid-run checkpoints
        wait_for(lambda: sum(n.startswith("ckpt-") for n in
                             (os.listdir(ckpt_dir)
                              if os.path.isdir(ckpt_dir) else [])) >= 2,
                 120, "two sealed checkpoints")
        victim.kill()  # SIGKILL: the crash, not a graceful stop
        victim.wait(timeout=10)
        t_kill = time.perf_counter()

        resumed = spawn("run2.log")
        wait_for(lambda: "first-step-complete" in log_text("run2.log"),
                 180, "first post-restore step")
        downtime_s = time.perf_counter() - t_kill
        resumed.wait(timeout=300)
        text = log_text("run2.log")
        m = re.search(r"restored checkpoint .* in ([0-9.]+)s", text)
        restore_s = float(m.group(1)) if m else None
        m = re.search(r"ckpt_stats=(\{.*\})", text)
        child_stats = json.loads(m.group(1)) if m else {}
        m = re.search(r"first-step-complete global_step=(\d+)", text)
        resumed_step = int(m.group(1)) if m else None
    except (TimeoutError, OSError, subprocess.SubprocessError) as exc:
        print(f"elastic downtime bench failed: {exc}", file=sys.stderr)
        return {"elastic_downtime_s": None, "ckpt_restore_s": None}
    finally:
        for proc in (victim, resumed):
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
        _shutil.rmtree(root, ignore_errors=True)
    return {"elastic_downtime_s": round(downtime_s, 2),
            "ckpt_restore_s": restore_s,
            "downtime_resumed_at_step": resumed_step,
            "downtime_ckpt_every_steps": ckpt_steps,
            "downtime_replay_budget_s": round(ckpt_steps * step_time, 2),
            "downtime_save_stall_ms_mean":
                child_stats.get("ckpt_save_stall_ms_mean")}


def bench_elastic_downtime_p2p(on_tpu: bool) -> dict:
    """Resize downtime under the p2p live state-migration plane: run
    `elastic_demo --resize-p2p` (store + JobServer + 2 launcher pods,
    scripted shrink + grow through /resize, self-audited) and read its
    machine-readable summary.

    - `elastic_downtime_p2p_s`: the WORST surviving-pod training gap
      across the resizes — adoption observed at a step boundary ->
      first completed step of the new generation. The p2p analogue of
      the kill->first-step stop-resume number: a survivor never
      respawns, re-imports, re-jits or restores, so the gap collapses
      to one step boundary (vs `elastic_downtime_s` in this same
      artifact, which pays all four on every resize).
    - `resize_bytes_from_peers`: state the grown pod fetched from donor
      memory over the tensor wire instead of reading disk.
    The demo exits non-zero when any resize silently degraded to the
    disk recipe, so a regression here fails the bench loudly.
    """
    import re
    import shutil as _shutil
    import subprocess
    import sys
    import tempfile as _tempfile

    del on_tpu  # orchestration-plane measurement: CPU pods, hermetic
    root = _tempfile.mkdtemp(prefix="edl-p2p-bench-")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU tunnel
    env.update({"JAX_PLATFORMS": "cpu", "JAX_NUM_CPU_DEVICES": "1"})
    out = {"elastic_downtime_p2p_s": None, "resize_bytes_from_peers": None,
           "p2p_adoptions": None, "p2p_peer_restores": None,
           "p2p_demo_ok": False}
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "edl_tpu.examples.elastic_demo",
             "--resize-p2p"],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        m = re.search(r"p2p_summary=(\{.*\})", proc.stdout)
        if not m:
            print("p2p downtime bench: no summary "
                  f"(rc={proc.returncode})\n{proc.stdout[-2000:]}"
                  f"\n{proc.stderr[-2000:]}", file=sys.stderr)
            return out
        summary = json.loads(m.group(1))
        restore_s = [s for s in summary.get("peer_restore_s", [])
                     if s is not None]
        out.update({
            "elastic_downtime_p2p_s": summary.get("elastic_downtime_p2p_s"),
            "resize_bytes_from_peers":
                summary.get("resize_bytes_from_peers"),
            "p2p_adoptions": summary.get("adoptions"),
            "p2p_peer_restores": summary.get("peer_restores"),
            "p2p_peer_restore_s": (round(sorted(restore_s)[len(restore_s)
                                                          // 2], 4)
                                   if restore_s else None),
            "p2p_demo_ok": bool(summary.get("ok"))
            and proc.returncode == 0})
    except (subprocess.SubprocessError, OSError, ValueError) as exc:
        print(f"p2p downtime bench failed: {exc}", file=sys.stderr)
    finally:
        _shutil.rmtree(root, ignore_errors=True)
    return out


def bench_resize_reform(on_tpu: bool) -> dict:
    """Multi-process resize downtime WITHOUT restart: run
    `elastic_demo --resize-reform` (2-virtual-device launcher pods
    whose local dp mesh is sized by the elastic world, scripted shrink
    + grow, self-audited) and read its machine-readable summary.

    - `elastic_downtime_multihost_s`: the best (compile-cache-warm)
      surviving-pod gap through a TRUE device-world change — quiesce-
      seal -> mesh-reform -> peer-restore -> re-jit -> first step, all
      inside one OS process. The multi-host analogue of
      `elastic_downtime_p2p_s` (ROADMAP item 2's target: within ~2x).
    - `elastic_downtime_multihost_cold_s`: the same gap when the new
      world's shape is seen for the FIRST time — exactly one compile.
    - `reform_zero_restart`: True iff at least one pod rode two
      resizes on one pid (the no-process-restart proof the demo exits
      nonzero without).
    """
    import re
    import subprocess
    import sys

    del on_tpu  # orchestration-plane measurement: CPU pods, hermetic
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)   # the demo sets its own 2-device world
    env.pop("JAX_NUM_CPU_DEVICES", None)
    env["JAX_PLATFORMS"] = "cpu"
    out = {"elastic_downtime_multihost_s": None,
           "elastic_downtime_multihost_cold_s": None,
           "reform_restores_peers": None,
           "reform_zero_restart": False,
           "reform_demo_ok": False}
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "edl_tpu.examples.elastic_demo",
             "--resize-reform"],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        m = re.search(r"reform_summary=(\{.*\})", proc.stdout)
        if not m:
            print("reform downtime bench: no summary "
                  f"(rc={proc.returncode})\n{proc.stdout[-2000:]}"
                  f"\n{proc.stderr[-2000:]}", file=sys.stderr)
            return out
        summary = json.loads(m.group(1))
        out.update({
            "elastic_downtime_multihost_s":
                summary.get("elastic_downtime_multihost_s"),
            "elastic_downtime_multihost_cold_s":
                summary.get("elastic_downtime_multihost_cold_s"),
            "reform_restores_peers":
                summary.get("reform_restores_peers"),
            "reform_zero_restart":
                bool(summary.get("zero_restart_survivors")),
            "reform_demo_ok": bool(summary.get("ok"))
            and proc.returncode == 0})
    except (subprocess.SubprocessError, OSError, ValueError) as exc:
        print(f"reform downtime bench failed: {exc}", file=sys.stderr)
    return out


def bench_scaler(on_tpu: bool) -> dict:
    """Autoscaler decision quality on the deterministic simulator: how
    fast the ThroughputPolicy closes on the oracle allocation and what
    it pays getting there (edl_tpu/scaler; no training involved — the
    decision plane itself is the system under test).

    Per canonical curve shape (concave / flat / knee) from a mid-range
    starting allocation: ticks until the LAST resize, the converged vs
    oracle node gap, post-convergence resize count (must be 0), and the
    stop-resume downtime paid — using the r9-measured 1.2s
    elastic_downtime_s as the per-resize price. Deterministic (seeded
    sim, virtual clock), so regressions here are policy regressions."""
    from edl_tpu.scaler.policy import ThroughputPolicy
    from edl_tpu.scaler.simulator import (SimCluster, SimJob, concave,
                                          flat, knee, run_policy)
    del on_tpu  # host-side decision plane: identical on every platform
    cases = (("concave", concave(100.0, 0.5), 2),
             ("flat", flat(100.0), 4),
             ("knee", knee(100.0, 4), 7))
    per_curve = {}
    for name, curve, start in cases:
        sim = SimCluster([SimJob("j", curve, 1, 8, nodes=start,
                                 noise=0.01)],
                         tick_s=5.0, downtime_s=1.2, seed=0)
        policy = ThroughputPolicy(gain_threshold=0.05, cooldown_s=15.0,
                                  horizon_s=60.0)
        out = run_policy(sim, policy, ticks=150, settle_ticks=50)
        job = out["jobs"]["j"]
        per_curve[name] = {
            "decisions_to_converge": job["decisions_to_converge"],
            "gap_nodes": job["gap_nodes"],
            "oracle_nodes": job["oracle_nodes"],
            "final_nodes": job["final_nodes"],
            "resizes": job["resizes"],
            "downtime_paid_s": job["downtime_paid_s"],
            "post_convergence_resizes": job["post_convergence_resizes"]}
    return {
        "scaler_decisions_to_converge": max(
            c["decisions_to_converge"] for c in per_curve.values()),
        "scaler_alloc_gap_nodes": max(
            c["gap_nodes"] for c in per_curve.values()),
        "scaler_downtime_paid_s": round(sum(
            c["downtime_paid_s"] for c in per_curve.values()), 2),
        "scaler_post_convergence_resizes": sum(
            c["post_convergence_resizes"] for c in per_curve.values()),
        "scaler_per_curve": per_curve}


def bench_serving_slo(on_tpu: bool) -> dict:
    """Serving-elasticity decision quality on the deterministic
    SimServingPool (edl_tpu/scaler/serving): how fast the ServingPolicy
    restores the latency SLO after a load step, what it pays getting
    there, and whether steady load stays resize-free.

    Three canonical open-loop traces at the default SLO contract:
    steady (no-thrash baseline), a 4x step (reaction ticks = last SLO
    violation after the step), and a bounded burst (grow in, DRAIN back
    out). Deterministic (seeded sim, virtual clock), so regressions
    here are policy regressions."""
    from edl_tpu.scaler.serving import ServingConfig, ServingPolicy
    from edl_tpu.scaler.simulator import (SimServingPool, burst,
                                          run_serving_policy, steady, step)
    del on_tpu  # host-side decision plane: identical on every platform

    def policy():
        return ServingPolicy(ServingConfig(
            slo_p95_ms=250.0, breach_ticks=2, idle_ticks=5,
            cooldown_s=15.0, max_teachers=16))

    step_at = 40
    cases = (("steady", steady(200.0), None),
             ("step4x", step(100.0, 4.0, at=step_at), step_at),
             ("burst4x", burst(100.0, 4.0, at=40, length=25), 40))
    per_trace = {}
    for name, trace, at in cases:
        pool = SimServingPool("svc", trace, teachers=1, max_teachers=16,
                              tick_s=1.0, noise=0.01, seed=0)
        out = run_serving_policy(pool, policy(), ticks=200,
                                 settle_ticks=50)
        per_trace[name] = {
            "slo_attainment_pct": round(100.0 * out["slo_attainment"], 2),
            "reaction_ticks": (max(0, out["last_violation_tick"] - at)
                               if at is not None else 0),
            "resizes": out["resizes"],
            "post_convergence_resizes": out["post_convergence_resizes"],
            "final_teachers": out["final_teachers"]}
    return {
        "serving_slo_reaction_ticks":
            per_trace["step4x"]["reaction_ticks"],
        "serving_slo_attainment_pct": min(
            t["slo_attainment_pct"] for t in per_trace.values()),
        "serving_resizes_paid": sum(
            t["resizes"] for t in per_trace.values()),
        "serving_post_convergence_resizes": sum(
            t["post_convergence_resizes"] for t in per_trace.values()),
        "serving_per_trace": per_trace}


def bench_fleet(on_tpu: bool) -> dict:
    """Fleet-scale scheduling quality on the deterministic `FleetSim`
    (edl_tpu/scaler/fleet): hundreds of concurrent trainer jobs and
    serving pools from a seeded trace, every resize priced by the
    measured downtime ladder (0.061s p2p adopt / 0.138s in-place
    reform / 1.2s stop-resume).

    Reduced-scale cut of the tools/fleet_bench.py tournament so the
    artifact stays cheap: the preemptive policy vs plain fair-share on
    the spot-heavy trace (SLO attainment at equal-or-better goodput is
    the claim), and the spot-riding experiment (80% revocable capacity
    vs all-reserved; the ratio is the price of living on spot when
    every preemption notice is ridden as a scheduled seal-and-shrink).
    Deterministic (seeded sim, virtual clock), so regressions here are
    policy regressions."""
    from edl_tpu.scaler.fleet import FleetSim, FleetTrace, run_fleet
    from edl_tpu.scaler.fleet_policy import (FairSharePolicy,
                                             PreemptiveFairSharePolicy)
    del on_tpu  # host-side decision plane: identical on every platform
    kw = dict(cooldown_s=15.0, horizon_s=60.0)
    scale = dict(n_jobs=72, n_pools=12, ticks=160)
    trace = FleetTrace.generate("spot-heavy", 13, spot_fraction=0.5,
                                churn=0.15, **scale)
    fair = run_fleet(FleetSim(trace), FairSharePolicy(1, **kw))
    pre = run_fleet(FleetSim(trace),
                    PreemptiveFairSharePolicy(1, **kw))
    ride = {}
    for key, frac in (("reserved", 0.0), ("spot80", 0.8)):
        t = FleetTrace.generate("spot-ride", 21, spot_fraction=frac,
                                **scale)
        ride[key] = run_fleet(FleetSim(t),
                              PreemptiveFairSharePolicy(1, **kw))
    return {
        "fleet_jobs": len(trace.jobs),
        "fleet_pools": len(trace.pools),
        "fleet_goodput_rows_per_s": pre["goodput_rows_per_s"],
        "fleet_goodput_fair_share_rows_per_s":
            fair["goodput_rows_per_s"],
        "fleet_slo_attainment": pre["slo_attainment"],
        "fleet_slo_attainment_fair_share": fair["slo_attainment"],
        "fleet_jain_fairness": pre["jain_fairness"],
        "fleet_forced_evictions": pre["forced_evictions"],
        "fleet_forced_evictions_fair_share": fair["forced_evictions"],
        "fleet_lost_rows": pre["lost_rows"],
        "fleet_lost_rows_fair_share": fair["lost_rows"],
        "fleet_spot80_goodput_ratio": round(
            ride["spot80"]["goodput_rows_per_s"]
            / max(ride["reserved"]["goodput_rows_per_s"], 1e-9), 4),
        "fleet_spot80_notices_ridden": ride["spot80"]["notices_ridden"],
        "fleet_spot80_notices_issued": ride["spot80"]["notices_issued"],
        "fleet_spot80_forced_evictions":
            ride["spot80"]["forced_evictions"]}


def bench_serving_throughput(on_tpu: bool) -> dict:
    """Continuous batching + admission control on REAL TeacherServers
    (r23): the open-loop generator (`edl_tpu.distill.loadgen`) drives
    a sleepy fake chip, so these are scheduling numbers — the window
    Batcher's coalesce delay vs continuous admission at equal offered
    load, and per-class shedding under 2x overload with the delay-
    budget rule armed. `elastic_demo --serve-load` gates the same
    scenario in CI; this keeps the numbers on the scoreboard."""
    import time as _time

    from edl_tpu.distill.admission import AdmissionConfig
    from edl_tpu.distill.loadgen import run_open_loop
    from edl_tpu.distill.teacher_server import TeacherServer
    del on_tpu  # host-side serving plane: the chip is a sleep()

    def sleepy(per_row_s, base_s):
        def predict(feeds):
            rows = next(iter(feeds.values())).shape[0]
            _time.sleep(base_s + per_row_s * rows)
            return {"logits": np.zeros((rows, 4), np.float32)}
        return predict

    # A/B at mid load (half of one teacher's ~3k rows/s capacity)
    p95 = {}
    rps_sustained = {}
    for mode in ("window", "continuous"):
        server = TeacherServer(
            sleepy(0.0003, 0.001), port=0, host="127.0.0.1",
            max_batch=64, max_wait=0.02,
            admission=AdmissionConfig(batching=mode)).start()
        try:
            s = run_open_loop([f"127.0.0.1:{server.port}"],
                              duration_s=5.0, rps=100.0, rows=4,
                              seed=11).summary()
        finally:
            server.stop()
        p95[mode] = s["p95_ms"]
        rps_sustained[mode] = s["rps_sustained"]

    # 2x overload on 2 continuous teachers, shed rule armed: shedding
    # must concentrate on the low class (the per-class degradation
    # contract the CI dryrun asserts)
    adm = AdmissionConfig(batching="continuous", shed_ms=150.0)
    servers = [TeacherServer(sleepy(0.004, 0.004), port=0,
                             host="127.0.0.1", max_batch=8,
                             admission=adm).start() for _ in range(2)]
    try:
        over = run_open_loop(
            [f"127.0.0.1:{s.port}" for s in servers], duration_s=10.0,
            rps=111.0, rows=8,
            mix={"high": 0.1, "normal": 0.15, "low": 0.75},
            seed=12).summary()
    finally:
        for server in servers:
            server.stop()
    return {
        "serving_p95_ms_window": round(p95["window"], 2),
        "serving_p95_ms_continuous": round(p95["continuous"], 2),
        "serving_p95_window_vs_continuous_x": round(
            p95["window"] / max(p95["continuous"], 1e-9), 2),
        "serving_rps_sustained": rps_sustained["continuous"],
        "serving_overload_rps_sustained": over["rps_sustained"],
        "serving_shed_pct_by_class": {
            cls: c["shed_pct"]
            for cls, c in over["by_class"].items()}}


def bench_control_plane(on_tpu: bool) -> dict:
    """Event-driven control plane (ISSUE 8): watch streams vs polling.

    Three measurements, one artifact:
      - store_watch_latency_ms: PUT -> watcher-callback through the real
        TCP server + ClientWatch (median of 20), i.e. how fast a
        membership change reaches a consumer;
      - control_plane_reqs_per_idle_min: store requests during an IDLE
        window from a representative consumer set (4 ServiceWatchers +
        a blocked lock waiter), measured in poll mode
        (EDL_TPU_COORD_WATCH=0 — every consumer on its original loop)
        and watch mode in the same run; the ratio is the idle-load
        collapse (O(pods x poll rate) -> O(changes));
      - scaler_reaction_ms: fresh-utilization PUT -> decision-journal
        entry with the fallback interval at 30s, proving the scaler is
        no longer quantized to its tick.
    Host-side control plane: identical on every platform."""
    del on_tpu
    import threading

    from edl_tpu.coord.client import StoreClient
    from edl_tpu.coord.lock import DistributedLock
    from edl_tpu.coord.registry import ServiceRegistry
    from edl_tpu.coord.server import StoreServer
    from edl_tpu.coord.store import InMemStore

    idle_s = 3.0
    saved = os.environ.get("EDL_TPU_COORD_WATCH")

    def _idle_ops_per_min(watch_on: bool) -> float:
        os.environ["EDL_TPU_COORD_WATCH"] = "1" if watch_on else "0"
        store = InMemStore()
        with StoreServer(port=0, host="127.0.0.1", store=store,
                         sweep_interval=0.5) as srv:
            client = StoreClient(f"127.0.0.1:{srv.port}")
            registry = ServiceRegistry(client, root="bench")
            for i in range(2):
                registry.register_permanent("svc", f"h:{i}")
            watchers = [registry.watch_service("svc", interval=1.0)
                        for _ in range(4)]
            holder = DistributedLock(client, "/bench/lock", "holder",
                                     ttl=10.0)
            holder.try_acquire()

            def _wait_for_lock():
                # one BLOCKED waiter (the satellite's StoreLock shape):
                # wakes on the holder's DELETE at teardown
                waiter = DistributedLock(client, "/bench/lock", "waiter",
                                         ttl=10.0)
                if waiter.acquire(timeout=idle_s + 15.0, poll=0.2):
                    waiter.release()
            waiter_thread = threading.Thread(target=_wait_for_lock,
                                             daemon=True)
            waiter_thread.start()
            time.sleep(0.5)  # let subscriptions/initial syncs settle
            ops0 = store.op_count
            time.sleep(idle_s)
            ops = store.op_count - ops0
            for w in watchers:
                w.stop()
            holder.release()
            waiter_thread.join(timeout=10.0)
            client.close()
        return ops * (60.0 / idle_s)

    def _watch_latency_ms() -> float:
        os.environ["EDL_TPU_COORD_WATCH"] = "1"
        lat = []
        with StoreServer(port=0, host="127.0.0.1",
                         sweep_interval=0.5) as srv:
            client = StoreClient(f"127.0.0.1:{srv.port}")
            registry = ServiceRegistry(client, root="bench")
            seen = threading.Event()
            watcher = registry.watch_service(
                "lat", on_add=lambda m: seen.set(),
                on_update=lambda m: seen.set(), interval=30.0)
            for i in range(20):
                seen.clear()
                t0 = time.perf_counter()
                registry.register_permanent("lat", "h:1", info=str(i))
                assert seen.wait(5.0), "watch callback never fired"
                lat.append((time.perf_counter() - t0) * 1e3)
            watcher.stop()
            client.close()
        lat.sort()
        return lat[len(lat) // 2]

    def _scaler_reaction_ms() -> tuple[float, float]:
        os.environ["EDL_TPU_COORD_WATCH"] = "1"
        from edl_tpu.coord.collector import util_key
        from edl_tpu.scaler.controller import ScalerConfig, ScalerController
        from edl_tpu.scaler.policy import Proposal

        class _Hold:
            def decide(self, views, now):
                return [Proposal(v.job_id, v.world_size, v.world_size,
                                 "hold") for v in views]

            def restore(self, entries):
                pass

            def notify_resized(self, job_id, world, now):
                pass

        store = InMemStore()
        config = ScalerConfig()
        config.interval = 30.0
        config.min_tick_s = 0.0
        ctl = ScalerController(store, ["bjob"], _Hold(), config=config,
                               dry_run=True, elect=False)
        ctl.start()
        try:
            deadline = time.monotonic() + 10.0
            while not ctl.journal.tail() \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            n0 = len(ctl.journal.tail())
            t0 = time.perf_counter()
            store.put(util_key("bjob", "pod0"), json.dumps(
                {"examples_per_sec": 100.0, "published_unix": time.time(),
                 "world_size": 1}))
            while len(ctl.journal.tail()) == n0 \
                    and time.perf_counter() - t0 < 20.0:
                time.sleep(0.01)
            reaction = (time.perf_counter() - t0) * 1e3
        finally:
            ctl.stop()
        return reaction, config.interval

    try:
        latency_ms = _watch_latency_ms()
        poll_rpm = _idle_ops_per_min(watch_on=False)
        watch_rpm = _idle_ops_per_min(watch_on=True)
        reaction_ms, interval_s = _scaler_reaction_ms()
    finally:
        if saved is None:
            os.environ.pop("EDL_TPU_COORD_WATCH", None)
        else:
            os.environ["EDL_TPU_COORD_WATCH"] = saved
    return {
        "store_watch_latency_ms": round(latency_ms, 2),
        "control_plane_reqs_per_idle_min_poll": round(poll_rpm, 1),
        "control_plane_reqs_per_idle_min": round(watch_rpm, 1),
        "control_plane_watch_reduction_x": round(
            poll_rpm / max(watch_rpm, 1e-9), 1),
        "scaler_reaction_ms": round(reaction_ms, 1),
        "scaler_fallback_interval_s": interval_s,
    }


def bench_store_ha(on_tpu: bool) -> dict:
    """Replicated coordination store (ISSUE 11): the control plane
    survives losing its leader.

    One 3-replica group under a registry-shaped write stream with a
    live watch consumer; the leader is CRASHED (no resign — failover
    pays the real lease-expiry price):
      - store_failover_downtime_ms: last acked write before the kill ->
        first acked write after (the write-unavailability window;
        election TTL 0.6s dominates it);
      - store_events_lost: majority-acked writes missing from the
        revision-audited watch stream after resume-by-revision — the
        acceptance gate, MUST be 0;
      - store_watch_fanout_streams: concurrent watch streams a single
        FOLLOWER served during the run (fan-out rides followers, so
        watch capacity scales with replicas, not with the leader).
    Host-side control plane: identical on every platform. The deeper
    sweep (thousands of pods, hundreds of streams, single-vs-majority
    write cost) lives in tools/store_bench.py."""
    del on_tpu
    import threading

    from edl_tpu.coord.client import StoreClient
    from edl_tpu.coord.replication import ReplicaGroup

    fanout_streams = 64
    with ReplicaGroup(3, election_ttl=0.6) as group:
        leader = group.wait_leader(timeout=20.0)
        follower = next(s for s in group.servers if s is not leader)
        client = group.client(timeout=3.0)
        watcher = StoreClient(follower.endpoint, timeout=3.0)
        watch = watcher.watch("/job/", start_revision=0)
        fan = [follower.node.store.watch("/job/")
               for _ in range(fanout_streams)]

        acked: dict[str, int] = {}
        stop = threading.Event()
        gap = {"last_before": 0.0, "first_after": None}
        killed = {"at": None}

        def writer() -> None:
            i = 0
            while not stop.is_set() and i < 1500:
                try:
                    rev = client.put(f"/job/rank/{i % 16}", f"p-{i}")
                    now = time.perf_counter()
                    acked[f"p-{i}"] = rev
                    if killed["at"] is None:
                        gap["last_before"] = now
                    elif gap["first_after"] is None:
                        gap["first_after"] = now
                except Exception:  # noqa: BLE001 — window measured below
                    pass
                i += 1
                time.sleep(0.01)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            time.sleep(0.5)
            killed["at"] = time.perf_counter()
            group.kill_leader()
            group.wait_leader(timeout=20.0)
            deadline = time.monotonic() + 15.0
            while gap["first_after"] is None \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            time.sleep(0.3)
        finally:
            stop.set()
            t.join(timeout=15.0)

        seen: set[int] = set()
        deadline = time.monotonic() + 10.0
        max_acked = max(acked.values(), default=0)
        while time.monotonic() < deadline:
            batch = watch.get(timeout=0.5)
            if batch is None:
                if seen and max(seen) >= max_acked:
                    break
                continue
            seen.update(ev.revision for ev in batch.events)
        lost = sum(1 for rev in acked.values() if rev not in seen)
        for w in fan:
            w.cancel()
        watch.cancel()
        watcher.close()
        client.close()
    downtime_ms = 0.0
    if gap["first_after"] is not None:
        downtime_ms = (gap["first_after"] - gap["last_before"]) * 1e3
    return {
        "store_failover_downtime_ms": round(downtime_ms, 1),
        "store_events_lost": lost,
        "store_failover_acked_writes": len(acked),
        "store_watch_fanout_streams": fanout_streams + 1,
    }


def bench_store_fleet(on_tpu: bool) -> dict:
    """Fleet-scale control plane (ISSUE 18): relay fan-out + coalesced
    leases at a pod count no single leader could watch-serve directly.

    Runs ``tools/store_bench.py --fleet`` at smoke scale (the committed
    STORE_FLEET artifact holds the full 100k-pod / 1M-stream run) and
    reports the audited outcome:
      - store_fleet_pods / store_watch_streams: simulated registration
        + watch population, every stream revision-audited exactly-once
        across a leader kill;
      - store_fanout_events_per_sec: relay fan-out rate (shared-frame
        appends, one upstream stream per distinct prefix);
      - store_fleet_events_lost / store_fleet_duplicates: MUST be 0;
      - store_fleet_keepalive_reduction_x: coalesced host leases vs
        per-pod keepalive writes, live cohorts (>= 10x acceptance).
    Host-side control plane: identical on every platform."""
    del on_tpu
    import subprocess
    import sys as _sys
    import tempfile
    with tempfile.NamedTemporaryFile(suffix=".json") as tmp:
        proc = subprocess.run(
            [_sys.executable, "tools/store_bench.py", "--fleet",
             "--fleet-pods", "2000", "--fleet-streams", "20000",
             "--fleet-prefixes", "32", "--fleet-tcp-streams", "40",
             "--json", tmp.name],
            capture_output=True, text=True, timeout=900)
        try:
            out = json.load(open(tmp.name))
        except (json.JSONDecodeError, OSError):
            out = {}
    return {
        "store_fleet_pods": out.get("store_fleet_pods"),
        "store_watch_streams": out.get("store_watch_streams"),
        "store_fanout_events_per_sec": out.get(
            "store_fanout_events_per_sec"),
        "store_fleet_events_lost": out.get("store_fleet_events_lost"),
        "store_fleet_duplicates": out.get("store_fleet_duplicates"),
        "store_fleet_keepalive_reduction_x": out.get(
            "store_fleet_keepalive_reduction_x"),
        "store_fleet_gates_rc": proc.returncode,
    }


def bench_chaos(on_tpu: bool) -> dict:
    """Deterministic chaos soak (ISSUE 12): the elastic world under a
    seeded fault storm, judged by invariant audits.

    Runs ``python -m edl_tpu.chaos soak`` (store replica group +
    JobServer + worker pods + scaler + teacher pool) at a fixed seed
    and reports the audited outcome:
      - chaos_faults_survived / chaos_faults_injected: every injected
        fault must resolve (recovered or typed error — never a hang);
      - chaos_invariant_breaches: MUST be 0 (exactly-once watch
        delivery, journal<->resize_log parity, bitwise restores, drain
        discipline);
      - chaos_max_downtime_s: worst observed kill -> re-registration
        window across the storm;
      - chaos_fault_classes: distinct injector classes exercised.
    Host-side control plane: identical on every platform."""
    del on_tpu
    import subprocess
    import sys as _sys
    proc = subprocess.run(
        [_sys.executable, "-m", "edl_tpu.chaos", "soak", "--seed", "1",
         "--ticks", "12", "--settle-s", "10"],
        capture_output=True, text=True, timeout=300)
    summary = {}
    for line in proc.stdout.splitlines():
        if line.startswith("chaos_summary="):
            summary = json.loads(line.split("=", 1)[1])
            break
    stats = summary.get("stats", {})
    return {
        "chaos_faults_injected": stats.get("faults_injected"),
        "chaos_faults_survived": stats.get("faults_survived"),
        "chaos_invariant_breaches": len(summary.get("breaches", [])),
        "chaos_max_downtime_s": stats.get("max_downtime_s"),
        "chaos_fault_classes": len(stats.get("fault_classes", [])),
    }


def bench_obs(on_tpu: bool, step_s: float) -> dict:
    """Observability-plane overhead (ISSUE 13 acceptance: the registry
    must cost <1% of step time while live).

    - obs_overhead_pct: wall cost of the per-step metric updates a fully
      instrumented loop performs (counter.inc + gauge.set + histogram
      .observe, measured over 20k iterations) as a percentage of the
      MEASURED headline step time in this same artifact;
    - metrics_scrape_ms: one Prometheus-text render of a realistically
      populated registry (10 typed metrics + 8 stats-dict sources);
    - resize_trace_spans: spans captured for one traced resize driven
      through the REAL path (request_resize -> JobServer /resize ->
      store-attached epoch publication) under EDL_TPU_TRACE.
    Host-side plane: identical on every platform."""
    del on_tpu
    import shutil as _shutil
    import tempfile as _tempfile
    import timeit as _timeit

    from edl_tpu.obs import metrics as obs_metrics
    from edl_tpu.obs import trace as obs_trace

    reg = obs_metrics.Registry()
    c = reg.counter("bench_rows", "rows served")
    g = reg.gauge("bench_depth", "queue depth")
    h = reg.histogram("bench_step_ms", obs_metrics.LOG_BUCKETS_MS)

    def per_step():
        c.inc(64)
        g.set(3)
        h.observe(7.3)

    n = 20000
    per_step_s = _timeit.timeit(per_step, number=n) / n
    overhead_pct = 100.0 * per_step_s / max(step_s, 1e-9)

    for i in range(8):
        reg.register_stats(f"bench_src{i}", lambda: {
            "served_rows": 123456, "queue_depth": 2, "util": 0.73,
            "busy_s": 41.2, "inflight_groups": 1, "pending_hwm": 9,
            "latency_hist_ms": {"5.0": 10, "10.0": 4, "inf": 1}})
    for _ in range(3):
        reg.render()  # warm
    scrape_s = _timeit.timeit(reg.render, number=10) / 10

    # one REAL traced resize: demo-shaped JobServer with a store
    # attached, hit over HTTP under an active trace
    from edl_tpu.collective.job_server import (JobServer, JobState,
                                               request_resize)
    from edl_tpu.coord.store import InMemStore
    tmp = _tempfile.mkdtemp(prefix="edl-obs-bench-")
    spans = 0
    prev = os.environ.get("EDL_TPU_TRACE")
    try:
        os.environ["EDL_TPU_TRACE"] = tmp
        obs_trace.reconfigure()
        state = JobState("obs_bench", 1, 4, desired=2,
                         store=InMemStore())
        server = JobServer(state, port=0).start()
        try:
            request_resize(f"127.0.0.1:{server.port}", 3)
        finally:
            server.stop()
        loaded = obs_trace.load_spans(tmp)
        resizes = obs_trace.resize_phase_summary(loaded)
        spans = resizes[0]["spans"] if resizes else 0
    finally:
        if prev is None:
            os.environ.pop("EDL_TPU_TRACE", None)
        else:
            os.environ["EDL_TPU_TRACE"] = prev
        obs_trace.reconfigure()
        _shutil.rmtree(tmp, ignore_errors=True)

    return {
        "obs_overhead_pct": round(overhead_pct, 4),
        "obs_metric_update_us": round(per_step_s * 1e6, 3),
        "metrics_scrape_ms": round(scrape_s * 1e3, 3),
        "resize_trace_spans": spans,
    }


def distill_quality_extras() -> dict:
    """Surface the flagship distill QUALITY measurement (the reference's
    acc1 77.1->79.0 story) from the newest committed artifact —
    tools/distill_quality_tpu.py writes it; re-measuring in-bench would
    be a ~30-minute training study, not a benchmark step."""
    import glob
    import re
    arts = sorted(
        glob.glob(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "DISTILL_QUALITY_r*.json")),
        key=lambda p: int(re.search(r"_r(\d+)", p).group(1)))
    if not arts:
        return {}
    with open(arts[-1]) as f:
        doc = json.load(f)
    return {"distill_acc1_delta": doc.get("distill_acc1_delta"),
            "distill_acc1_alone": doc.get("alone_acc1"),
            "distill_acc1_distilled": doc.get("distilled_acc1"),
            "distill_quality_artifact": os.path.basename(arts[-1])}


def main() -> None:
    on_tpu = jax.devices()[0].platform == "tpu"
    resnet = bench_resnet(on_tpu)
    loader = bench_input_plane(on_tpu)
    transformer = bench_transformer(on_tpu)
    flash = bench_flash_kernel(on_tpu)
    hybrid = bench_hybrid_mesh(on_tpu)
    dcn = bench_dcn_comm(on_tpu)
    moe = bench_moe(on_tpu)
    distill = bench_distill(on_tpu)
    churn = bench_distill_churn(on_tpu)
    ckpt = bench_checkpoint(on_tpu)
    fused = bench_fused_opt(on_tpu)
    downtime = bench_elastic_downtime(on_tpu)
    p2p = bench_elastic_downtime_p2p(on_tpu)
    if downtime.get("elastic_downtime_s") \
            and p2p.get("elastic_downtime_p2p_s"):
        p2p["elastic_downtime_reduction_x"] = round(
            downtime["elastic_downtime_s"]
            / p2p["elastic_downtime_p2p_s"], 1)
    reform = bench_resize_reform(on_tpu)
    if p2p.get("elastic_downtime_p2p_s") \
            and reform.get("elastic_downtime_multihost_s"):
        # ROADMAP item 2's target ratio: a device-world change vs the
        # unchanged-device-set adoption, same artifact
        reform["elastic_downtime_multihost_vs_adopt_x"] = round(
            reform["elastic_downtime_multihost_s"]
            / p2p["elastic_downtime_p2p_s"], 2)
    scaler = bench_scaler(on_tpu)
    serving_slo = bench_serving_slo(on_tpu)
    fleet = bench_fleet(on_tpu)
    serving_throughput = bench_serving_throughput(on_tpu)
    control_plane = bench_control_plane(on_tpu)
    store_ha = bench_store_ha(on_tpu)
    store_fleet = bench_store_fleet(on_tpu)
    chaos = bench_chaos(on_tpu)
    # overhead is judged against THIS artifact's measured step time
    headline_step_s = (resnet.get("batch_size", 256)
                       / max(resnet["imgs_per_sec"], 1e-9))
    obs = bench_obs(on_tpu, headline_step_s)
    cores_to_feed_jpeg = (resnet["imgs_per_sec"]
                          / max(loader["imgs_per_sec_per_core"], 1e-9))
    # the headline feed question, recomputed against the packed +
    # device-augment path: host work per image is ONE gathered memcpy
    # (augmentation runs on the chip), so the cores needed to feed the
    # measured device rate collapse
    cores_to_feed = (resnet["imgs_per_sec"]
                     / max(loader["packed_imgs_per_sec"], 1e-9))
    print(json.dumps({
        "metric": "resnet50_vd_train_imgs_per_sec",
        "value": resnet["imgs_per_sec"],
        "unit": "img/s",
        "vs_baseline": resnet["vs_baseline"],
        "extras": {
            # host->device through this harness is a network tunnel;
            # on a TPU VM the pipeline number converges to the headline
            "resnet_pipeline_imgs_per_sec": resnet["pipeline_imgs_per_sec"],
            # loader-ONLY (no device): the JPEG decode/augment plane;
            # scales ~linearly with host cores (cv2 drops the GIL)
            "loader_imgs_per_sec": loader["imgs_per_sec"],
            "loader_host_cores": loader["host_cores"],
            "loader_imgs_per_sec_per_core":
                loader["imgs_per_sec_per_core"],
            # host cores at which the loader saturates the chip rate,
            # on the PACKED + device-augment feed (the production path:
            # pre-decoded mmap gather + jitted on-chip crop/flip) —
            # _jpeg is the decode-on-host plane it replaced
            "loader_cores_to_feed_headline": round(cores_to_feed, 1),
            "loader_cores_to_feed_headline_jpeg":
                round(cores_to_feed_jpeg, 1),
            # packed records: host-side rate is ONE np.take gather per
            # batch per core + the emitted augment seed; pack ratio is
            # the disk price (pre-decoded uint8 bytes / jpeg bytes)
            "loader_imgs_per_sec_packed": loader["packed_imgs_per_sec"],
            "loader_pack_ratio_bytes": loader["pack_ratio_bytes"],
            # the resnet step fed end-to-end from packed records with
            # device-side flip (prefetch_to_device(augment=...))
            "resnet_pipeline_imgs_per_sec_packed":
                resnet["pipeline_packed_imgs_per_sec"],
            # multi-process shared-memory loader (DataLoader
            # num_workers): worker processes + shm ring hand-off —
            # the past-the-GIL path; scaling is vs the threaded
            # single-process number above (≈linear to min(workers,
            # cores) on real multi-core hosts, <1 on a 1-core host
            # where it can only measure IPC overhead)
            "loader_imgs_per_sec_mp": loader["mp_imgs_per_sec"],
            "loader_mp_workers": loader["mp_workers"],
            "loader_mp_scaling": loader["mp_scaling"],
            # resnet pipeline number above is now captured through the
            # mp loader feed (workers collate into shm; the parent
            # copies each ring view before device_put so the placed
            # batch can't alias a recycled slot)
            "resnet_pipeline_loader_workers":
                resnet["pipeline_loader_workers"],
            "transformer_tokens_per_sec": transformer["tokens_per_sec"],
            "transformer_mfu": transformer["mfu"],
            # r5: the perf-notes prediction measured — MFU past the
            # modest-M GEMM regime (d_model 2048 + remat)
            "transformer_tokens_per_sec_large":
                transformer["tokens_per_sec_large"],
            "transformer_mfu_large": transformer["mfu_large"],
            "flash_attn_speedup": flash["speedup_vs_dense"],
            "flash_attn_seq_len": flash["seq_len"],
            # hybrid ICI x DCN mesh vs flat on the same devices: the
            # comms win on real multi-slice, parity (~1.0) on
            # single-link worlds (CPU / one chip)
            "hybrid_mesh_flat_step_ms": hybrid["flat_step_ms"],
            "hybrid_mesh_step_ms": hybrid["hybrid_step_ms"],
            # REFRESHED (r21): the ratio is now flat-jit vs the hybrid
            # mesh on the DCN-aware bucketed gradient path (the
            # multi-slice default); _jit is the old single-reduction
            # hybrid number for trend continuity
            "hybrid_mesh_comm_step_ms": hybrid["hybrid_comm_step_ms"],
            "hybrid_vs_flat_step_ratio":
                hybrid["hybrid_vs_flat_step_ratio"],
            "hybrid_vs_flat_step_ratio_jit":
                hybrid["hybrid_vs_flat_step_ratio_jit"],
            "hybrid_mesh_n_slices": hybrid["n_slices"],
            # DCN-aware gradient path (doc/design_comm.md), numbers
            # gated on bitwise-dense parity + the compressed loss
            # envelope: per-chip cross-slice bytes/step and the
            # schedulable comm/compute overlap of the bucketed plan
            **dcn,
            # expert-parallel dispatch (hierarchical all-to-all + int8
            # DCN leg) behind comm.moe_parity_gate, plus the ep
            # resize-under-load gap through the sharded-checkpoint
            # planner (tools/comm_bench.py --moe has the mode sweep)
            **moe,
            # distill wire numbers are MEDIAN OF 3 with [min, max]
            "distill_student_imgs_per_sec": distill["imgs_per_sec"],
            "distill_student_imgs_per_sec_spread":
                distill["imgs_per_sec_spread"],
            "distill_vs_colocated_baseline":
                distill["vs_colocated_baseline"],
            # bounds for the disaggregated headline (BASELINE.md math):
            # ceiling = student pipeline with a nop teacher; teacher =
            # per-chip serving capacity under concurrent clients
            "distill_student_ceiling_imgs_per_sec":
                distill["student_ceiling_imgs_per_sec"],
            "distill_student_ceiling_spread":
                distill["student_ceiling_spread"],
            "teacher_imgs_per_sec": distill["teacher_imgs_per_sec"],
            "teacher_imgs_per_sec_spread":
                distill["teacher_imgs_per_sec_spread"],
            "teacher_chip_imgs_per_sec":
                distill["teacher_chip_imgs_per_sec"],
            "teacher_coalesce_batch_rows_mean":
                distill["coalesce_batch_rows_mean"],
            # r6: the overlapped serving path — reader requests in
            # flight per teacher connection, server adaptive-coalesce
            # window + intake depth (e2e and teacher-only runs)
            "distill_pipeline_depth": distill["pipeline_depth"],
            "teacher_coalesce_window_ms": distill["coalesce_window_ms"],
            "teacher_pending_hwm": distill["pending_hwm"],
            "teacher_serving_batch_rows_mean":
                distill["serving_batch_rows_mean"],
            "teacher_serving_pending_hwm":
                distill["serving_pending_hwm"],
            # r5: served top-k wire — bytes/img in the response
            # direction, dense fp32 vs compressed (idx+fp16 val)
            "distill_wire_logits_bytes_dense":
                distill["wire_logits_bytes_dense"],
            "distill_wire_logits_bytes": distill["wire_logits_bytes"],
            "distill_serve_topk": distill["serve_topk"],
            # distill under teacher churn: kill + re-add mid-run
            # (VERDICT r5 ask #6)
            "distill_churn_steady_imgs_per_sec":
                churn["steady_imgs_per_sec"],
            "distill_churn_dip_imgs_per_sec": churn["dip_imgs_per_sec"],
            "distill_churn_recovery_s": churn["recovery_s"],
            "distill_churn_kill_to_rejoin_s": churn["kill_to_rejoin_s"],
            "distill_churn_post_rejoin_imgs_per_sec":
                churn["post_rejoin_imgs_per_sec"],
            # checkpoint plane: step-loop stall per save, sync (the old
            # epoch-end path, same artifact as the baseline clause asks)
            # vs async snapshot-then-write, + write/restore wall time
            # and the bitwise sync==async payload check
            **ckpt,
            # fused optimizer path: isolated update ms (optax vs fused
            # fp32 vs int8 moments), resident + serialized state-byte
            # cut, all gated on the kernel parity report
            # (tools/opt_bench.py has the optimizer x impl x size sweep)
            **fused,
            # elastic stop-resume downtime: SIGKILL a trainer mid-run,
            # respawn, clock kill -> first post-restore step
            **downtime,
            # p2p live-migration resize downtime (same artifact as the
            # disk baseline above): survivors adopt in place, joiners
            # restore from donor memory over the tensor wire
            **p2p,
            # multi-process resize WITHOUT restart (reform state
            # machine): survivors ride a true device-world change in
            # place — warm (cached shape) and cold (one compile) gaps,
            # same artifact as the single-host numbers above
            **reform,
            # autoscaler decision plane on the deterministic simulator:
            # ticks-to-converge / vs-oracle gap / downtime paid across
            # concave+flat+knee curves (edl_tpu/scaler)
            **scaler,
            # serving-elasticity plane on the SimServingPool traces:
            # ticks to restore the latency SLO after a 4x load step,
            # worst-trace attainment %, resizes paid (scaler/serving)
            **serving_slo,
            # fleet-scale scheduling on the seeded FleetSim: preemptive
            # gang fair-share vs plain fair-share on the spot-heavy
            # trace (SLO attainment at equal-or-better goodput), and
            # the 80%-spot goodput ratio with every preemption notice
            # ridden as a scheduled seal-and-shrink (tools/
            # fleet_bench.py runs the full policy x trace x ladder
            # tournament)
            **fleet,
            # teacher-pool serving tier under the open-loop generator:
            # window vs continuous batching p95 at equal sustained rps,
            # and per-class shed % under 2x overload with the delay-
            # budget rule armed (tools/serve_load_bench.py has the
            # full rate sweep)
            **serving_throughput,
            # event-driven control plane: PUT -> watcher-callback
            # latency over TCP, idle store request volume poll- vs
            # watch-mode (same consumer set), and the scaler's
            # fresh-util -> decision reaction vs its fallback interval
            **control_plane,
            # replicated store HA: leader-kill failover window +
            # zero-lost-events audit + follower watch fan-out
            # (tools/store_bench.py has the load sweep)
            **store_ha,
            # fleet-scale control plane: relay fan-out + coalesced
            # host leases, exactly-once audited across a leader kill
            # (tools/store_bench.py --fleet has the 100k/1M run)
            **store_fleet,
            # seeded chaos soak: faults injected/survived across the
            # injector classes, invariant breaches (must be 0), worst
            # observed recovery window (tools/chaos_bench.py sweeps
            # seeds x fault mixes)
            **chaos,
            # observability plane: per-step metric-update cost vs the
            # measured headline step (<1% acceptance), scrape render
            # time, spans per traced resize (tools/obs_bench.py has
            # the on/off sweep)
            **obs,
            # flagship distill QUALITY (committed artifact; see
            # tools/distill_quality_tpu.py)
            **distill_quality_extras(),
        },
    }))


if __name__ == "__main__":
    main()
