"""Headline benchmark: ResNet50_vd training throughput (img/s).

Mirrors the reference's headline number — ResNet50_vd ImageNet training at
1828 img/s on 8x V100 (README.md:70), i.e. 228.5 img/s per accelerator.
This harness times the jitted bf16 training step (label smoothing 0.1, SGD
momentum, the reference recipe's loss path) on the available TPU chip(s)
and reports aggregate img/s; `vs_baseline` is per-accelerator throughput
relative to the reference's per-V100 number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import optax


def main() -> None:
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"

    from edl_tpu.models.resnet import ResNet50_vd, ResNetTiny
    from edl_tpu.parallel import mesh as mesh_lib
    from edl_tpu.train import classification as cls

    n_dev = len(jax.devices())
    if on_tpu:
        model = ResNet50_vd(num_classes=1000, dtype=jnp.bfloat16)
        per_dev_batch, hw, classes, steps = 128, 224, 1000, 30
    else:  # CPU smoke mode so the harness is testable anywhere
        model = ResNetTiny(num_classes=10, dtype=jnp.float32)
        per_dev_batch, hw, classes, steps = 8, 32, 10, 4

    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": n_dev}))
    batch_size = per_dev_batch * n_dev
    state = cls.create_state(model, jax.random.PRNGKey(0), (1, hw, hw, 3),
                             optax.sgd(0.1, momentum=0.9, nesterov=True))
    step = cls.make_classification_step(classes, smoothing=0.1, donate=True)

    batch = mesh_lib.shard_batch(mesh, {
        "image": jax.random.normal(jax.random.PRNGKey(1),
                                   (batch_size, hw, hw, 3), jnp.float32),
        "label": jax.random.randint(jax.random.PRNGKey(2), (batch_size,),
                                    0, classes),
    })

    for _ in range(3):  # warmup / compile
        state, metrics = step(state, batch)
    float(metrics["loss"])  # value fetch = hard sync (block_until_ready
    # alone does not force execution through remote-device tunnels)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    imgs_per_sec = steps * batch_size / dt
    per_accel = imgs_per_sec / n_dev
    baseline_per_accel = 1828.0 / 8.0  # reference README.md:70, 8x V100
    print(json.dumps({
        "metric": "resnet50_vd_train_imgs_per_sec",
        "value": round(imgs_per_sec, 1),
        "unit": "img/s",
        "vs_baseline": round(per_accel / baseline_per_accel, 3),
    }))


if __name__ == "__main__":
    main()
