"""Chaos soak pod worker — a real subprocess exercising the real seams.

One worker is the soak's stand-in for a launcher pod, built from the
SAME primitives production pods use (no chaos-only protocol): it claims
a rank slot with a leased `PodRegister`, consumes the mark stream over
a resumable watch, publishes utilization records the autoscaler
digests, and runs a checkpoint plane — sealing sharded-format versions
(``train/ckpt_io``, numpy-only: a worker never imports jax) and
restore-verifying EVERY retained version each pass, crc-checked, with
fallback-to-previous on corruption.

Everything the worker observes goes to an append-only JSONL report
(one line per event, flushed immediately so a SIGKILL loses at most
the in-flight line): registration, lease losses and re-claims, watch
batches (revisions + compaction markers), seal digests, restore
digests, detected corruption, and every typed error survived. The
report is the worker's half of the invariant audit: the soak's
`InvariantAuditor` cross-checks it against what was injected.

Faults this process is expected to survive or die loudly under:
SIGKILL (the supervisor respawns a new incarnation on the same slot —
same checkpoint dir, so it restores the previous incarnation's state),
SIGSTOP/SIGCONT (leases may expire; the worker re-claims and reports),
store partitions and wire faults (typed store errors, backoff, retry),
and on-disk checkpoint corruption (typed detection + fallback).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import signal
import sys
import time

import numpy as np

from edl_tpu.collective import register as reg
from edl_tpu.collective.cluster import Cluster, Pod
from edl_tpu.collective.reform import (ReformConfig, ReformMachine,
                                       wait_until)
from edl_tpu.coord.client import StoreClient
from edl_tpu.coord.collector import util_key
from edl_tpu.obs import recorder as flight
from edl_tpu.train import ckpt_io
from edl_tpu.utils.backoff import Backoff
from edl_tpu.utils.config import env_float
from edl_tpu.utils.exceptions import EdlCheckpointCorrupt, EdlError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.chaos.worker")


def marks_prefix(job_id: str) -> str:
    return f"/{job_id}/marks/"


def world_key(job_id: str) -> str:
    return f"/{job_id}/world"


def preempt_key(job_id: str, pod_id: str) -> str:
    """The spot-preemption notice mailbox for ONE pod incarnation.

    Keyed by pod_id (not slot) on purpose: the respawned incarnation
    after the hard kill carries a fresh pod_id, so a stale notice can
    never re-preempt the replacement."""
    return f"/{job_id}/preempt/{pod_id}"


class Reporter:
    """Append-only JSONL event log, flushed per line."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")

    def __call__(self, kind: str, **fields) -> None:
        rec = {"kind": kind, "ts": round(time.time(), 3), **fields}
        self._fh.write(json.dumps(rec, sort_keys=True) + "\n")
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()


def _payload(slot: int, version: int) -> dict[str, np.ndarray]:
    """Deterministic per-(slot, version) state: the seal/restore digest
    pair is checkable without shipping the arrays anywhere."""
    rng = np.random.default_rng(slot * 100_003 + version)
    return {"w": rng.standard_normal((64, 8)).astype(np.float32),
            "b": np.arange(version + 8, dtype=np.int64)}


def _digest(arrays: dict[str, np.ndarray]) -> str:
    h = hashlib.sha256()
    for name in sorted(arrays):
        arr = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(arr.dtype).encode() + str(arr.shape).encode())
        h.update(memoryview(arr).cast("B"))
    return h.hexdigest()


class CheckpointRig:
    """Seal + verify loop over the sharded chunk format (ckpt_io).

    Seal: write chunks + crc'd index into a tmp dir, atomic-rename to
    ``ckpt-N`` (the manager's torn-save discipline), keep the newest 3.
    Verify: for EVERY retained version, load each chunk through a
    crc-checking `ChunkFiles`, assemble the full arrays, digest — a
    corrupt version is reported, quarantined (renamed ``corrupt-N``)
    and the previous sealed version is what the worker falls back to.
    """

    KEEP = 3

    def __init__(self, directory: str, slot: int, report: Reporter):
        self.directory = directory
        self.slot = slot
        self.report = report
        os.makedirs(directory, exist_ok=True)
        for name in os.listdir(directory):  # torn saves from a SIGKILL
            if name.startswith(".tmp-"):
                shutil.rmtree(os.path.join(directory, name),
                              ignore_errors=True)
        self.version = 1 + max(self.versions(), default=-1)

    def versions(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("ckpt-") and name[5:].isdigit():
                out.append(int(name[5:]))
        return sorted(out)

    def seal(self) -> None:
        version = self.version
        arrays = _payload(self.slot, version)
        leaves, chunks = [], []
        for i, name in enumerate(sorted(arrays)):
            arr = arrays[name]
            fname = ckpt_io.chunk_name(i, tuple(0 for _ in arr.shape))
            chunks.append((fname, arr))
            leaves.append({"key": name, "shape": list(arr.shape),
                           "dtype": str(arr.dtype),
                           "chunks": [{"offset": [0] * arr.ndim,
                                       "shape": list(arr.shape),
                                       "file": fname}]})
        tmp = os.path.join(self.directory, f".tmp-{version}")
        shutil.rmtree(tmp, ignore_errors=True)
        ckpt_io.write_snapshot(tmp, {"leaves": leaves, "chunks": chunks,
                                     "process_index": 0})
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump({"version": version}, f)
        os.rename(tmp, os.path.join(self.directory, f"ckpt-{version}"))
        self.report("seal", version=version, digest=_digest(arrays))
        self.version += 1
        for old in self.versions()[:-self.KEEP]:
            shutil.rmtree(os.path.join(self.directory, f"ckpt-{old}"),
                          ignore_errors=True)

    def _read_version(self, version: int) -> dict[str, np.ndarray]:
        vdir = os.path.join(self.directory, f"ckpt-{version}")
        merged = ckpt_io.read_merged_index(vdir)
        files = ckpt_io.ChunkFiles(vdir, crcs=ckpt_io.checksum_map(merged))
        try:
            out = {}
            for key, entry in merged.items():
                region = tuple(slice(0, s) for s in entry["shape"])
                out[key] = np.array(
                    ckpt_io.read_region(files.load, entry, region))
            return out
        finally:
            files.close()

    def _quarantine(self, version: int, exc: Exception) -> None:
        """Typed detection -> quarantine -> the newest GOOD version is
        the fallback (reported so the auditor can pair detection with
        the injected corruption)."""
        self.report("ckpt_corrupt_detected", version=version,
                    error=str(exc))
        flight.record("corruption", plane="chaos-rig",
                      slot=self.slot, version=version, error=str(exc))
        vdir = os.path.join(self.directory, f"ckpt-{version}")
        os.rename(vdir, os.path.join(self.directory,
                                     f"corrupt-{version}"))
        good = [v for v in self.versions() if v != version]
        self.report("ckpt_fallback", bad=version,
                    to=max(good) if good else None)

    def verify_all(self) -> None:
        for version in self.versions():
            try:
                arrays = self._read_version(version)
            except EdlCheckpointCorrupt as exc:
                self._quarantine(version, exc)
                continue
            self.report("restore", version=version,
                        digest=_digest(arrays),
                        newest=version == self.versions()[-1])

    # -- reform-ladder restore halves (collective/reform.py executors) ----

    def restore_newest(self) -> dict[str, np.ndarray]:
        """The ladder's restore phase: crc-verified read of the newest
        sealed version. `EdlCheckpointCorrupt` propagates — that is the
        typed peer-restore failure the machine downgrades on."""
        versions = self.versions()
        if not versions:
            raise EdlCheckpointCorrupt("no sealed version to restore")
        arrays = self._read_version(versions[-1])
        self.report("restore", version=versions[-1],
                    digest=_digest(arrays), newest=True)
        return arrays

    def fallback_previous(self) -> dict[str, np.ndarray]:
        """The ladder's disk downgrade: quarantine the newest (corrupt)
        version and restore the previous good one."""
        versions = self.versions()
        if not versions:
            raise EdlCheckpointCorrupt("nothing to fall back to")
        self._quarantine(versions[-1],
                         EdlCheckpointCorrupt("reform restore failed"))
        good = self.versions()
        if not good:
            raise EdlCheckpointCorrupt("no good version left")
        arrays = self._read_version(good[-1])
        self.report("restore", version=good[-1],
                    digest=_digest(arrays), newest=False)
        return arrays


def run_reform(store: StoreClient, job: str, pod_id: str, generation: int,
               rig: CheckpointRig, report: Reporter) -> str:
    """The worker's reform ladder for one cluster-generation bump: the
    jax-free half of the reform state machine (collective/reform.py),
    exercised under the soak's compound `reform` faults. Phases:

      quiesce       no device to settle — a bounded no-op
      mesh-reform   re-read the leader-published cluster doc at (or
                    past) the new generation under the phase deadline —
                    a store partition mid-phase times out into the
                    typed stop-resume downgrade
      restore       crc-verified read of the newest sealed version
                    (corruption downgrades to the previous good one)

    Returns the machine's result; "stop-resume" tells the caller to
    release + re-claim its rank (the worker-scale clean downgrade —
    the same membership blip a real stop-resume produces). Every start
    is reported before the ladder and every outcome after: the pairing
    IS the I6 invariant the auditor holds.
    """
    report("reform_start", generation=generation)
    config = ReformConfig(quiesce_s=2.0, mesh_s=2.0, restore_s=6.0,
                          rejit_s=2.0)
    machine = ReformMachine(generation, config, who=pod_id)

    def mesh_reform(deadline: float) -> None:
        def check() -> bool:
            try:
                rec = store.get(reg.cluster_key(job))
                if rec is None:
                    return False
                return Cluster.from_json(rec.value).version >= generation
            except (EdlError, OSError, ValueError):
                return False
        if not wait_until(check, deadline, interval=0.1):
            raise EdlError(f"cluster doc unreadable or stale (< v"
                           f"{generation}) past the mesh deadline")

    machine.run_ladder(
        quiesce=lambda dl: None,
        mesh_reform=mesh_reform,
        restore_peers=lambda dl: rig.restore_newest(),
        restore_disk=lambda dl: rig.fallback_previous())
    doc = machine.finish()
    report("reform_done", generation=generation, result=doc["result"],
           restore=doc["restore"], error=doc["error"],
           phases={p["phase"]: p["s"] for p in doc["phases"]})
    return doc["result"]


def run_worker(args) -> int:
    report = Reporter(args.report)
    stop = {"flag": False}

    def _term(signum, frame):  # noqa: ARG001 — signal signature
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _term)
    # flight-recorder wiring: a crashing worker dumps its ring next to
    # its report (the soak collects both); SIGUSR2 dumps a live one
    report_dir = os.path.dirname(os.path.abspath(args.report))
    flight.install_dump_handlers(report_dir, tag=args.pod_id)
    report("started", pod_id=args.pod_id, slot=args.slot, pid=os.getpid(),
           verify=ckpt_io.verify_enabled())

    store = StoreClient(args.endpoints, timeout=2.0, connect_retries=8,
                        retry_interval=0.1)
    rig = CheckpointRig(args.ckpt_dir, args.slot, report)
    rig.seal()  # a sealed version exists from the first instant: the
    # corruptor never races an empty directory

    pod = Pod(pod_id=args.pod_id, addr="127.0.0.1", n_devices=1)
    register = reg.PodRegister(store, args.job, pod,
                               max_nodes=args.max_nodes, ttl=args.ttl)
    backoff = Backoff(base=0.1, max_delay=1.0)
    rank = None
    watch = None
    watch_from = 0  # resume anchor across watch re-creation
    watch_client = StoreClient(args.endpoints, timeout=2.0,
                               connect_retries=8, retry_interval=0.1)
    last_seal = time.monotonic()
    last_verify = time.monotonic()
    last_gen: int | None = None  # reform-ladder generation cursor
    # spot-notice contract: >0 = a noticed preemption is honored as a
    # scheduled quiesce-seal-donate before the kill deadline; 0 = the
    # notice is IGNORED (the soak's --weaken-preempt negative control:
    # the worker trains into the hard kill and the auditor's I7 must
    # catch the lost progress)
    notice_s = env_float("EDL_TPU_SPOT_NOTICE_S", 2.0)
    preempted = False
    try:
        while not stop["flag"]:
            # -- membership: claim once, re-claim whenever the lease dies
            if preempted:
                pass  # donated: never re-claim; the deadline kill ends us
            elif rank is None or register.lost.is_set():
                if register.lost.is_set():
                    report("lease_lost", rank=rank)
                    register.release()
                    register = reg.PodRegister(store, args.job, pod,
                                               max_nodes=args.max_nodes,
                                               ttl=args.ttl)
                try:
                    rank = register.claim(timeout=10.0)
                    report("registered", rank=rank)
                    backoff.reset()
                except (EdlError, OSError) as exc:
                    report("typed_error", where="claim", error=str(exc))
                    if _sleep(backoff, stop):
                        break
                    continue
            # -- spot notices: a noticed preemption is a SCHEDULED
            # shrink, not a surprise. Quiesce (this loop is between
            # steps by construction), seal so nothing acked is
            # unsealed, then DONATE the rank so the survivors reform
            # without us — and park until the deadline kill. The
            # notice mailbox is keyed by pod_id, so only this
            # incarnation can be preempted by it.
            if notice_s > 0 and not preempted and rank is not None:
                try:
                    rec = store.get(preempt_key(args.job, args.pod_id))
                except (EdlError, OSError):
                    rec = None
                if rec is not None:
                    try:
                        doc = json.loads(rec.value)
                    except ValueError:
                        doc = {}
                    deadline = float(doc.get("deadline_unix",
                                             time.time() + notice_s))
                    report("preempt_notice",
                           deadline_unix=round(deadline, 3))
                    rig.seal()
                    try:
                        register.release()
                    except (EdlError, OSError):
                        pass
                    rank = None
                    preempted = True
                    report("preempt_ready",
                           margin_s=round(deadline - time.time(), 3))
            # -- the mark stream: resumable watch, resync on compaction
            if watch is None:
                try:
                    watch = watch_client.watch(marks_prefix(args.job),
                                               start_revision=watch_from)
                    report("watch_created", start_revision=watch_from)
                except EdlError as exc:
                    report("typed_error", where="watch", error=str(exc))
            if watch is not None:
                batch = watch.get(timeout=0.05)
                while batch is not None:
                    if batch.compacted:
                        marks, rev = store.get_prefix(
                            marks_prefix(args.job))
                        report("watch_compacted", revision=batch.revision,
                               resync_marks=len(marks), resync_rev=rev)
                        watch_from = max(watch_from, rev)
                    else:
                        report("watch", revisions=[e.revision
                                                   for e in batch.events])
                        if batch.events:
                            watch_from = max(watch_from,
                                             batch.events[-1].revision)
                    batch = watch.get(timeout=0.0)
            # -- reform ladder: a cluster-generation bump that keeps
            # this pod is a device-world change it must ride through in
            # place (or cleanly downgrade out of) — never a wedge (I6)
            try:
                _world_now, gen_now = _cluster_world(store, args.job)
            except (EdlError, OSError, ValueError):
                gen_now = None
            if gen_now is not None:
                if last_gen is None:
                    last_gen = gen_now  # the baseline generation
                elif gen_now > last_gen:
                    last_gen = gen_now
                    if rank is not None:
                        result = run_reform(store, args.job, args.pod_id,
                                            gen_now, rig, report)
                        if result == "stop-resume":
                            # the clean downgrade at worker scale:
                            # release + re-claim (a real membership
                            # blip; the barrier re-forms the world)
                            try:
                                register.release()
                            except (EdlError, OSError):
                                pass
                            register = reg.PodRegister(
                                store, args.job, pod,
                                max_nodes=args.max_nodes, ttl=args.ttl)
                            rank = None
            # -- utilization: what the autoscaler's collector digests
            # (a donated pod publishes nothing: it is leaving the world)
            if preempted:
                time.sleep(args.interval)
                continue
            try:
                world, generation = _cluster_world(store, args.job)
                rate = 50.0 * (world ** 0.7) if world else 0.0
                store.put(util_key(args.job, args.pod_id), json.dumps({
                    "examples_per_sec": round(rate, 3),
                    "world_size": world or None,
                    "generation": generation,
                    "published_unix": time.time(),
                    "pod_id": args.pod_id}),
                    lease=register.lease or 0)
            except (EdlError, OSError) as exc:
                report("typed_error", where="util", error=str(exc))
            # -- checkpoint plane
            now = time.monotonic()
            if now - last_seal >= args.seal_every:
                last_seal = now
                rig.seal()
            if now - last_verify >= args.verify_every:
                last_verify = now
                rig.verify_all()
            if stop["flag"]:
                break
            time.sleep(args.interval)
    finally:
        if watch is not None:
            watch.cancel()
        try:
            register.release()
        except (EdlError, OSError):
            pass
        report("stopped", graceful=True)
        report.close()
        watch_client.close()
        store.close()
        # graceful-exit dump (SIGKILLed incarnations never reach here —
        # their rings die with them, which is exactly what a crash ring
        # models; the excepthook covers the crashing-but-alive case)
        flight.dump_to(report_dir, tag=args.pod_id, reason="exit")
    return 0


def _sleep(backoff: Backoff, stop: dict) -> bool:
    time.sleep(min(backoff.delay(), 1.0))
    return stop["flag"]


def _cluster_world(store: StoreClient, job_id: str
                   ) -> tuple[int, int | None]:
    rec = store.get(reg.cluster_key(job_id))
    if rec is None:
        return 0, None
    cluster = Cluster.from_json(rec.value)
    return cluster.world_size, cluster.version


def add_worker_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--endpoints", required=True)
    parser.add_argument("--job", required=True)
    parser.add_argument("--pod-id", required=True)
    parser.add_argument("--slot", type=int, required=True)
    parser.add_argument("--report", required=True)
    parser.add_argument("--ckpt-dir", required=True)
    parser.add_argument("--max-nodes", type=int, default=8)
    parser.add_argument("--ttl", type=float, default=2.0)
    parser.add_argument("--interval", type=float, default=0.15)
    parser.add_argument("--seal-every", type=float, default=1.2)
    parser.add_argument("--verify-every", type=float, default=0.8)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="edl_tpu.chaos.worker")
    add_worker_args(parser)
    return run_worker(parser.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
