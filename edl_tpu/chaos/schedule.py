"""Seeded, replayable fault schedules.

A ``ChaosSchedule`` is a virtual-clock script of ``(t, fault, target,
duration, params)`` events drawn ENTIRELY from a seed: two runs with the
same (seed, ticks, mix) produce byte-identical schedules —
``fingerprint()`` proves it — so any soak failure replays exactly by
seed. Event TIMES are virtual seconds from soak start; the soak maps
them onto the wall clock. What is deterministic is the injection
script; the world's reaction (thread interleavings, which packet a wire
fault eats) is not, which is why the soak asserts INVARIANTS, not
states.

Target strings are symbolic (``pod:1``, ``replica:leader``,
``replica:follower``) and resolved live by the soak at injection time —
"kill the leader" must mean the leader AT THAT MOMENT, not the one at
schedule-generation time.

Pure stdlib: schedules print, hash and diff on a box with nothing
installed (``python -m edl_tpu.chaos schedule --seed 1``).
"""

from __future__ import annotations

import hashlib
import json
import random
from dataclasses import asdict, dataclass, field

# The injector catalog (doc/design_chaos.md). The first five are the
# acceptance classes; the last two drive the planes whose audit
# artifacts (resize_log, drain_log, journal) the soak cross-checks.
FAULT_CLASSES = (
    "wire",             # seeded drop/delay/close/garble at the wire seams
    "process-kill",     # SIGKILL a pod worker's process group
    "process-pause",    # SIGSTOP for `duration`, then SIGCONT
    "store-partition",  # sever a replica from its peers (client-reachable)
    "leader-kill",      # crash the current store leader (no resign)
    "ckpt-corrupt",     # bit-flip/truncate a sealed chunk on disk
    "resize",           # JobServer fault-injected resize (trainer world)
    "pool-resize",      # serving-pool resize through the actuator
    "reform",           # resize + a mid-phase fault (kill a donor,
                        # SIGSTOP a survivor, partition the store) —
                        # the reform state machine's I6 drill
    "relay",            # SIGKILL the watch-relay tier mid-stream: the
                        # downstreams must resume by revision off the
                        # respawned relay (zero lost, zero duplicated —
                        # I1 runs through the relay-attached consumer)
    "preempt",          # spot preemption NOTICE -> hard kill at the
                        # deadline: the noticed worker must quiesce-
                        # seal-donate before the kill (I7: no acked
                        # progress lost, no kill before the deadline)
)

# Per-class weights for the tail of the schedule (the head cycles every
# class once, so the five-class acceptance floor never depends on luck).
_WEIGHTS = {
    "wire": 4, "process-kill": 3, "process-pause": 2,
    "store-partition": 2, "leader-kill": 1, "ckpt-corrupt": 3,
    "resize": 2, "pool-resize": 2, "reform": 2, "relay": 1,
    "preempt": 2,
}


@dataclass(frozen=True)
class FaultEvent:
    t: float                 # virtual seconds from soak start
    fault: str               # one of FAULT_CLASSES
    target: str              # symbolic: pod:N, replica:leader, pool, job
    duration: float = 0.0    # transient faults: active window seconds
    params: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return asdict(self)


def _draw_event(rng: random.Random, fault: str, t: float, *,
                pods: int) -> FaultEvent:
    if fault == "wire":
        mode = rng.choice(["drop", "delay", "close", "garble"])
        return FaultEvent(t, "wire", "wire:all", duration=round(
            rng.uniform(0.4, 1.2), 3),
            params={"mode": mode, "rate": round(rng.uniform(0.1, 0.4), 3),
                    "delay_s": round(rng.uniform(0.02, 0.1), 3)})
    if fault == "process-kill":
        return FaultEvent(t, "process-kill", f"pod:{rng.randrange(pods)}")
    if fault == "process-pause":
        return FaultEvent(t, "process-pause", f"pod:{rng.randrange(pods)}",
                          duration=round(rng.uniform(0.5, 1.5), 3))
    if fault == "store-partition":
        # half asymmetric (the leader keeps serving clients while cut
        # off from quorum), half follower-side
        target = rng.choice(["replica:leader", "replica:follower"])
        return FaultEvent(t, "store-partition", target,
                          duration=round(rng.uniform(1.0, 2.5), 3))
    if fault == "leader-kill":
        return FaultEvent(t, "leader-kill", "replica:leader")
    if fault == "preempt":
        # duration = the notice window: long enough for a live worker
        # to quiesce-seal-donate (its loop polls the notice key every
        # interval), short enough that riding it is a real deadline
        return FaultEvent(t, "preempt", f"pod:{rng.randrange(pods)}",
                          duration=round(rng.uniform(2.0, 3.0), 3))
    if fault == "relay":
        # duration = dead window before the respawn: long enough that
        # downstream watches hit the reconnect/backoff path, short
        # enough that the store's event history still holds their
        # resume revisions (so recovery is resume, not resync)
        return FaultEvent(t, "relay", "relay",
                          duration=round(rng.uniform(1.0, 2.0), 3))
    if fault == "ckpt-corrupt":
        return FaultEvent(t, "ckpt-corrupt", f"pod:{rng.randrange(pods)}",
                          params={"mode": rng.choice(["bitflip",
                                                      "truncate"])})
    if fault == "resize":
        return FaultEvent(t, "resize", "job")
    if fault == "pool-resize":
        return FaultEvent(t, "pool-resize", "pool",
                          params={"delta": rng.choice([-1, 1, 1])})
    if fault == "reform":
        # a resize immediately compounded with a mid-phase fault: the
        # workers' reform ladders must complete or cleanly downgrade
        # under it (InvariantAuditor I6 pairs every start with an end)
        sub = rng.choice(["kill-donor", "pause-survivor",
                          "partition-store"])
        return FaultEvent(t, "reform", "job",
                          duration=round(rng.uniform(0.5, 1.5), 3),
                          params={"sub": sub})
    raise ValueError(f"unknown fault class {fault!r}")


class ChaosSchedule:
    """An ordered list of `FaultEvent`s plus its generation recipe."""

    def __init__(self, events: list[FaultEvent], *, seed: int,
                 tick_s: float):
        self.events = sorted(events, key=lambda e: (e.t, e.fault, e.target))
        self.seed = seed
        self.tick_s = tick_s

    @classmethod
    def generate(cls, seed: int, ticks: int, *, tick_s: float = 1.5,
                 pods: int = 2, mix: list[str] | None = None
                 ) -> "ChaosSchedule":
        """One fault per tick. The head of the schedule cycles through
        ``mix`` (default: every class) once in seeded order, the tail
        draws weighted — so a run long enough for the acceptance floor
        (>= len(mix) ticks) always spans every requested class."""
        rng = random.Random(seed)
        mix = list(mix) if mix else list(FAULT_CLASSES)
        head = list(mix)
        rng.shuffle(head)
        weights = [_WEIGHTS.get(f, 1) for f in mix]
        events = []
        for i in range(ticks):
            fault = head[i] if i < len(head) \
                else rng.choices(mix, weights)[0]
            t = round((i + 1) * tick_s + rng.uniform(0.0, tick_s / 3), 3)
            events.append(_draw_event(rng, fault, t, pods=pods))
        return cls(events, seed=seed, tick_s=tick_s)

    def classes(self) -> set[str]:
        return {e.fault for e in self.events}

    def to_jsonable(self) -> list[dict]:
        return [e.to_dict() for e in self.events]

    def fingerprint(self) -> str:
        """sha256 of the canonical JSON — the replay contract: same
        (seed, ticks, tick_s, pods, mix) => same fingerprint, always."""
        blob = json.dumps(self.to_jsonable(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()

    def __len__(self) -> int:
        return len(self.events)

    def __iter__(self):
        return iter(self.events)
