"""The chaos soak: one host, the full elastic world, a seeded storm.

``python -m edl_tpu.chaos soak --seed 1 --ticks 24`` builds the whole
single-host elastic control plane —

  - a 3-replica coordination store group (coord/replication.py), the
    same quorum-lease/fencing stack production runs;
  - a JobServer with a store-attached JobState (resize epochs publish);
  - pod workers as REAL subprocesses (chaos/worker.py) supervised like
    a launcher would: spawned to the desired world, respawned on death,
    trimmed on shrink;
  - a leader-elected ScalerController (ThroughputPolicy) observing the
    workers' published utilization and actuating /resize;
  - a serving pool (TeacherPoolActuator + stub teachers) draining on
    every shrink;
  - a mark probe: a writer streaming acked writes while a watch
    consumes the event stream (the I1 exactly-once ledger)

— then injects the seeded `ChaosSchedule` into it through the
`faults` injectors, heals everything, lets the world settle, and runs
the `InvariantAuditor` over the artifacts. Exit 0 iff zero invariant
breaches. The schedule is seed-exact (``--print-schedule`` /
``fingerprint``); the run's artifacts land in ``--artifacts`` (or a
temp dir) for post-mortem replay of the audit.

``--weaken-checksums`` runs the same storm with chunk crc verification
disabled in the workers (EDL_TPU_CKPT_VERIFY=0): the injected
corruption then sails through the runtime and the AUDITOR must catch
it as an I3 bitwise-equality breach — the CI gate asserts this run
exits nonzero, proving the audit has teeth.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import sys
import tempfile
import threading
import time

from edl_tpu.chaos import faults as fl
from edl_tpu.chaos.audit import InvariantAuditor, load_worker_reports
from edl_tpu.chaos.schedule import ChaosSchedule
from edl_tpu.chaos.worker import marks_prefix, preempt_key, world_key
from edl_tpu.collective import register as reg
from edl_tpu.collective.cluster import form_cluster
from edl_tpu.collective.process import start_trainer, terminate_trainer
from edl_tpu.coord.client import StoreClient
from edl_tpu.coord.replication import ReplicaServer
from edl_tpu.obs import recorder as flight
from edl_tpu.utils.exceptions import EdlError, EdlStoreError
from edl_tpu.utils.logging import get_logger
from edl_tpu.utils.net import free_port

log = get_logger("edl_tpu.chaos.soak")

JOB = "chaosjob"


class StubTeacher:
    """In-process TeacherHandle whose queue drains on a clock — enough
    surface for the actuator's full drain protocol (deregister -> wait
    for quiet stats -> graceful stop) without a serving stack."""

    def __init__(self, index: int):
        self.endpoint = f"stub:{index}"
        self._born = time.monotonic()
        self._gone = False

    def stats(self) -> dict | None:
        if self._gone:
            return None
        age = time.monotonic() - self._born
        return {"queue_depth": max(0, 2 - int(age / 0.1)),
                "inflight_groups": 0}

    def deregister(self) -> None:
        pass

    def stop(self) -> None:
        self._gone = True

    def kill(self) -> None:
        self._gone = True


class Supervisor:
    """The launcher role, minimized: keep `desired` worker subprocesses
    alive (respawn on death, trim on shrink), publish the cluster doc
    when membership settles, and mirror `desired` into the store for
    the workers' utilization records."""

    def __init__(self, state, store: StoreClient, *, report_dir: str,
                 ckpt_root: str, endpoints: str, max_nodes: int,
                 worker_env: dict):
        self.state = state
        self.store = store
        self.report_dir = report_dir
        self.ckpt_root = ckpt_root
        self.endpoints = endpoints
        self.max_nodes = max_nodes
        self.worker_env = worker_env
        self.journal: list[dict] = []        # guarded-by: _lock
        self._handles: dict[int, tuple[str, object]] = {}  # guarded-by: _lock
        self._incarnation: dict[int, int] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._cluster_version = 0
        self._last_pod_ids: set[str] = set()
        self._last_world_pub = -1
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="chaos-supervisor")

    def start(self) -> "Supervisor":
        self._thread.start()
        return self

    def handle(self, slot: int):
        with self._lock:
            ent = self._handles.get(slot)
            return ent[1] if ent else None

    def entry(self, slot: int) -> tuple[str, object] | None:
        with self._lock:
            return self._handles.get(slot)

    def live_slots(self) -> dict[int, bool]:
        with self._lock:
            return {slot: proc.alive()
                    for slot, (_, proc) in self._handles.items()}

    def _note(self, kind: str, **fields) -> None:
        with self._lock:
            self.journal.append({"kind": kind,
                                 "ts": round(time.time(), 3), **fields})

    def _spawn(self, slot: int) -> None:
        with self._lock:
            inc = self._incarnation.get(slot, 0)
            self._incarnation[slot] = inc + 1
        pod_id = f"pod{slot}-{inc}"
        cmd = [sys.executable, "-m", "edl_tpu.chaos", "worker",
               "--endpoints", self.endpoints, "--job", JOB,
               "--pod-id", pod_id, "--slot", str(slot),
               "--report", os.path.join(self.report_dir,
                                        f"{pod_id}.jsonl"),
               "--ckpt-dir", os.path.join(self.ckpt_root, f"pod{slot}"),
               "--max-nodes", str(self.max_nodes)]
        proc = start_trainer(cmd, self.worker_env,
                             os.path.join(self.report_dir, "log"),
                             rank=slot)
        with self._lock:
            self._handles[slot] = (pod_id, proc)
        self._note("spawn", slot=slot, pod_id=pod_id, pid=proc.pid)

    def _run(self) -> None:
        while not self._stop.wait(0.25):
            desired = self.state.snapshot()["desired_nodes"]
            with self._lock:
                slots = dict(self._handles)
            for slot in range(desired):
                ent = slots.get(slot)
                if ent is None:
                    self._spawn(slot)
                elif not ent[1].alive():
                    self._note("death_detected", slot=slot,
                               pod_id=ent[0])
                    self._spawn(slot)
            for slot, (pod_id, proc) in slots.items():
                if slot >= desired:
                    terminate_trainer(proc, grace=2.0)
                    with self._lock:
                        if self._handles.get(slot, (None, None))[1] \
                                is proc:
                            del self._handles[slot]
                    self._note("trim", slot=slot, pod_id=pod_id)
            try:
                self._publish(desired)
            except (EdlError, OSError) as exc:
                log.debug("supervisor publish failed: %s", exc)

    def _publish(self, desired: int) -> None:
        if desired != self._last_world_pub:
            self.store.put(world_key(JOB), str(desired))
            self._last_world_pub = desired
        pods, _ = reg.live_pods(self.store, JOB)
        ids = {p.pod_id for p in pods}
        if ids and ids != self._last_pod_ids:
            self._cluster_version += 1
            cluster = form_cluster(JOB, self._cluster_version, pods)
            self.store.put(reg.cluster_key(JOB), cluster.to_json())
            self._last_pod_ids = ids
            self._note("cluster_published",
                       version=self._cluster_version, pods=sorted(ids))

    def resume_all(self) -> None:
        """SIGCONT every supervised worker (the settle phase's heal —
        a pause window may still be pending when the storm ends)."""
        from edl_tpu.collective.process import resume_trainer
        with self._lock:
            handles = list(self._handles.values())
        for _pod_id, proc in handles:
            resume_trainer(proc)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        with self._lock:
            handles = list(self._handles.values())
            self._handles.clear()
        for _pod_id, proc in handles:
            terminate_trainer(proc, grace=3.0)


class MarkProbe:
    """The I1 ledger: a writer streams acked marks, a dedicated watch
    consumes the event stream; both sides' records feed the audit.

    With ``relay_endpoint`` a SECOND consumer watches the same prefix
    THROUGH the watch-relay tier: its deliveries get their own ledger
    (``relay_seen``/``relay_duplicates``) so I1's exactly-once check
    runs over the relay path too — including across a relay SIGKILL,
    where the downstream must resume by revision off the respawn."""

    def __init__(self, endpoints: str, *, rate_s: float = 0.06,
                 relay_endpoint: str | None = None):
        self.acked: dict[str, int] = {}   # writer-thread only until stop
        self.refused = 0                  # writer-thread only until stop
        self.seen: dict[int, str] = {}    # consumer-thread only until stop
        self.duplicates = 0               # consumer-thread only until stop
        self.branch_anomalies = 0         # consumer-thread only until stop
        self.relay_seen: dict[int, str] = {}   # relay-consumer only
        self.relay_duplicates = 0              # relay-consumer only
        self.relay_branch_anomalies = 0        # relay-consumer only
        self.final_values: list[str] = []
        self._rate_s = rate_s
        self._client = StoreClient(endpoints, timeout=2.0,
                                   connect_retries=6, retry_interval=0.1)
        self._watch_client = StoreClient(endpoints, timeout=2.0,
                                         connect_retries=6,
                                         retry_interval=0.1)
        self._watch = self._watch_client.watch(marks_prefix(JOB),
                                               start_revision=0)
        self._stop = threading.Event()
        self._writer = threading.Thread(target=self._write_loop,
                                        daemon=True, name="chaos-marks-w")
        self._consumer = threading.Thread(target=self._consume_loop,
                                          daemon=True,
                                          name="chaos-marks-r")
        self._relay_client: StoreClient | None = None
        self._relay_watch = None
        self._relay_consumer: threading.Thread | None = None
        if relay_endpoint:
            # via_relay=False: we ARE dialing the relay — no re-route
            self._relay_client = StoreClient(relay_endpoint, timeout=2.0,
                                             connect_retries=6,
                                             retry_interval=0.1)
            self._relay_watch = self._relay_client.watch(
                marks_prefix(JOB), start_revision=0, via_relay=False)
            self._relay_consumer = threading.Thread(
                target=self._relay_consume_loop, daemon=True,
                name="chaos-marks-relay-r")

    def start(self) -> "MarkProbe":
        self._writer.start()
        self._consumer.start()
        if self._relay_consumer is not None:
            self._relay_consumer.start()
        return self

    def _write_loop(self) -> None:
        i = 0
        while not self._stop.wait(self._rate_s):
            value = f"mark-{i}"
            try:
                rev = self._client.put(f"{marks_prefix(JOB)}{i:07d}",
                                       value)
                self.acked[value] = rev
            except EdlStoreError:
                # a refusal/timeout is NOT an ack: the mark may or may
                # not exist; the audit only holds acked marks to the
                # exactly-once bar
                self.refused += 1
            i += 1

    def _ingest(self, batch, seen: dict[int, str]) -> tuple[int, int]:
        """Fold one watch batch into a (revision -> value) ledger;
        returns (duplicates, branch_anomalies) deltas."""
        dups = branches = 0
        for ev in batch.events:
            if ev.type != "PUT":
                continue
            prev = seen.get(ev.revision)
            if prev == ev.value:
                # the same (revision, value) twice = a true replay
                # duplicate (the resume contract broken)
                dups += 1
            elif prev is not None:
                # same revision, DIFFERENT value: the watcher
                # observed a deposed leader's uncommitted suffix
                # whose revision numbers the new reign reused —
                # the documented weaker-than-Raft anomaly. Keep
                # the later (committed-branch) value.
                branches += 1
            seen[ev.revision] = ev.value
        return dups, branches

    def _consume_loop(self) -> None:
        while not self._stop.is_set():
            batch = self._watch.get(timeout=0.2)
            if batch is None:
                continue
            dups, branches = self._ingest(batch, self.seen)
            self.duplicates += dups
            self.branch_anomalies += branches

    def _relay_consume_loop(self) -> None:
        # identical ledger discipline, but every event arrived through
        # the relay tier — so a relay kill/respawn that lost or
        # replayed anything shows up here, not just on the direct path
        while not self._stop.is_set():
            batch = self._relay_watch.get(timeout=0.2)
            if batch is None:
                continue
            dups, branches = self._ingest(batch, self.relay_seen)
            self.relay_duplicates += dups
            self.relay_branch_anomalies += branches

    def probe_put(self) -> bool:
        try:
            self._client.put(f"/{JOB}/probe/live", str(time.time()))
            return True
        except EdlStoreError:
            return False

    def close(self) -> dict:
        return self.stop_and_collect()

    def _doc(self) -> dict:
        doc = {"acked": self.acked, "seen": self.seen,
               "duplicates": self.duplicates,
               "branch_anomalies": self.branch_anomalies,
               "refused": self.refused,
               "final_values": self.final_values}
        if self._relay_watch is not None:
            doc["relay_seen"] = self.relay_seen
            doc["relay_duplicates"] = self.relay_duplicates
            doc["relay_branch_anomalies"] = self.relay_branch_anomalies
        return doc

    def stop_and_collect(self) -> dict:
        if self._stop.is_set():  # idempotent: the crash path re-enters
            return self._doc()
        self._stop.set()
        self._writer.join(timeout=10.0)
        # drain whatever the watches still hold (the relay consumer may
        # additionally be mid-resume off a respawned relay)
        deadline = time.monotonic() + 8.0
        max_acked = max(self.acked.values(), default=0)
        while time.monotonic() < deadline:
            direct_ok = self.seen and max(self.seen) >= max_acked
            relay_ok = self._relay_watch is None or (
                self.relay_seen and max(self.relay_seen) >= max_acked)
            if direct_ok and relay_ok:
                break
            time.sleep(0.1)
        self._consumer.join(timeout=5.0)
        self._watch.cancel()
        if self._relay_consumer is not None:
            self._relay_consumer.join(timeout=5.0)
        if self._relay_watch is not None:
            self._relay_watch.cancel()
        try:
            records, _ = self._client.get_prefix(marks_prefix(JOB))
            self.final_values = [r.value for r in records]
        except EdlStoreError:
            pass
        self._client.close()
        self._watch_client.close()
        if self._relay_client is not None:
            self._relay_client.close()
        return self._doc()


class SoakWorld:
    """Build, storm, settle, audit — one soak run."""

    def __init__(self, args):
        self.args = args
        self.rng = random.Random(args.seed * 7919 + 17)
        self.artifacts = args.artifacts or tempfile.mkdtemp(
            prefix="edl-chaos-")
        self._own_artifacts = args.artifacts is None
        self.injections: list[dict] = []
        self.pool_journal: list[dict] = []
        self._pending: list[tuple[float, str, object]] = []
        self._noticed: set[str] = set()  # pod_ids with an outstanding
        # spot notice: one notice per incarnation (a real spot plane
        # coalesces repeats; the kill clears the entry)
        self._wire_active: fl.WireChaos | None = None
        self.max_downtime_s = 0.0

    # -- construction -------------------------------------------------------

    def build(self) -> None:
        from edl_tpu.collective.job_server import JobServer, JobState
        from edl_tpu.scaler.controller import (ScalerConfig,
                                               ScalerController)
        from edl_tpu.scaler.policy import ThroughputPolicy
        from edl_tpu.scaler.serving import TeacherPoolActuator

        ports = [free_port() for _ in range(3)]
        self.endpoints = [f"127.0.0.1:{p}" for p in ports]
        self.endpoints_spec = ",".join(self.endpoints)
        self.replicas: list[ReplicaServer | None] = [
            ReplicaServer(self.endpoints[i], ports[i], host="127.0.0.1",
                          group_endpoints=self.endpoints,
                          election_ttl=0.6, commit_timeout=1.5).start()
            for i in range(3)]
        self._wait_leader(20.0)

        self.store = StoreClient(self.endpoints_spec, timeout=2.0,
                                 connect_retries=8, retry_interval=0.1)
        self.state = JobState(JOB, 1, self.args.max_nodes,
                              desired=self.args.pods,
                              seed=self.args.seed, store=self.store)
        self.job_server = JobServer(self.state, port=0).start()

        worker_env = dict(os.environ)
        worker_env.setdefault("EDL_TPU_WIRE_STALL_S", "10")
        if self.args.weaken_checksums:
            worker_env["EDL_TPU_CKPT_VERIFY"] = "0"
        if getattr(self.args, "weaken_preempt", False):
            worker_env["EDL_TPU_SPOT_NOTICE_S"] = "0"
        self.report_dir = os.path.join(self.artifacts, "reports")
        self.ckpt_root = os.path.join(self.artifacts, "ckpt")
        os.makedirs(self.report_dir, exist_ok=True)
        self.supervisor = Supervisor(
            self.state, self.store, report_dir=self.report_dir,
            ckpt_root=self.ckpt_root, endpoints=self.endpoints_spec,
            max_nodes=self.args.max_nodes, worker_env=worker_env).start()

        self.journal_path = os.path.join(self.artifacts, "scaler.jsonl")
        self.scaler_store = StoreClient(self.endpoints_spec, timeout=2.0,
                                        connect_retries=8,
                                        retry_interval=0.1)
        self.scaler = ScalerController(
            self.scaler_store, [JOB],
            ThroughputPolicy(cooldown_s=4.0, horizon_s=30.0),
            config=ScalerConfig(interval=1.0, cooldown_s=4.0,
                                staleness_s=4.0, downtime_s=0.3),
            job_server=f"127.0.0.1:{self.job_server.port}",
            journal_path=self.journal_path, owner="chaos-soak").start()

        self.actuator = TeacherPoolActuator(
            lambda i: StubTeacher(i), min_teachers=1,
            max_teachers=4, drain_deadline_s=self.args.drain_deadline,
            service="chaos-teachers")
        self.pool_journal.append({"to": 1, "ts": round(time.time(), 3)})
        self.actuator.resize(1)

        # The watch-relay tier as a REAL subprocess (coord/relay.py):
        # the probe's relay consumer rides through it, and the "relay"
        # fault class SIGKILLs it mid-stream — recovery must look like
        # a server restart (reconnect + resume by revision).
        self._relay_env = worker_env
        self.relay_port = free_port()
        self.relay_endpoint = f"127.0.0.1:{self.relay_port}"
        self.relay_proc = None
        self._spawn_relay(wait=True)

        self.probe = MarkProbe(self.endpoints_spec,
                               relay_endpoint=self.relay_endpoint).start()

    def _spawn_relay(self, wait: bool = False) -> None:
        if self.relay_proc is not None and self.relay_proc.alive():
            return
        cmd = [sys.executable, "-m", "edl_tpu.coord.relay", "serve",
               "--host", "127.0.0.1", "--port", str(self.relay_port),
               "--upstream", self.endpoints_spec]
        self.relay_proc = start_trainer(
            cmd, self._relay_env, os.path.join(self.report_dir, "log"),
            rank=90)  # rank only names the log file (workerlog.90)
        if not wait:
            return
        probe = StoreClient(self.relay_endpoint, timeout=1.0,
                            connect_retries=1, retry_interval=0.05)
        try:
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                if probe.ping():
                    return
                time.sleep(0.1)
            raise EdlStoreError(
                f"relay at {self.relay_endpoint} not up within 15s")
        finally:
            probe.close()

    def _wait_leader(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if any(s is not None and s.node.is_leader()
                   for s in self.replicas):
                return
            time.sleep(0.05)
        raise EdlStoreError("no store leader within "
                            f"{timeout}s of {self.endpoints}")

    # -- injection ----------------------------------------------------------

    def _leader_index(self) -> int | None:
        for i, srv in enumerate(self.replicas):
            if srv is not None and srv.node.is_leader():
                return i
        return None

    def _resolve_replica(self, target: str) -> int | None:
        leader = self._leader_index()
        if target == "replica:leader":
            return leader
        for i, srv in enumerate(self.replicas):
            if srv is not None and i != leader:
                return i
        return None

    def inject(self, event) -> None:
        rec = {"t": event.t, "fault": event.fault, "target": event.target,
               "duration": event.duration, "params": dict(event.params),
               "wall": round(time.time(), 3), "resolution": None}
        self.injections.append(rec)
        # flight-recorder trail: every injection lands in the ring the
        # crash dump / run-dir dump carries, beside the resize/election
        # events the faults provoke
        flight.record("chaos_fault", fault=event.fault,
                      target=event.target, t=event.t)
        fault = event.fault
        try:
            if fault == "wire":
                if self._wire_active is not None:
                    rec["resolution"] = {"skipped": "wire window overlap"}
                    return
                chaos = fl.WireChaos(
                    self.rng.randrange(1 << 30),
                    modes=(event.params["mode"],),
                    rate=event.params["rate"],
                    delay_s=event.params.get("delay_s", 0.05)).install()
                self._wire_active = chaos
                rec["hook"] = id(chaos)
                self._pending.append(
                    (time.monotonic() + event.duration, "wire-heal",
                     chaos))
            elif fault in ("process-kill", "process-pause"):
                slot = int(event.target.split(":", 1)[1])
                handle = self.supervisor.handle(slot)
                if handle is None:
                    rec["resolution"] = {"skipped": f"no pod at {slot}"}
                    return
                if fault == "process-kill":
                    fl.ProcessChaos.sigkill(handle)
                else:
                    fl.ProcessChaos.sigstop(handle)
                    self._pending.append(
                        (time.monotonic() + event.duration,
                         "sigcont", handle))
                rec["slot"] = slot
            elif fault == "store-partition":
                idx = self._resolve_replica(event.target)
                if idx is None:
                    rec["resolution"] = {"skipped": "no such replica"}
                    return
                srv = self.replicas[idx]
                fl.StorePartitioner.sever(srv.node, True)
                rec["replica"] = self.endpoints[idx]
                rec["was_leader"] = event.target == "replica:leader"
                self._pending.append(
                    (time.monotonic() + event.duration,
                     "partition-heal", srv.node))
            elif fault == "leader-kill":
                idx = self._leader_index()
                if idx is None:
                    rec["resolution"] = {"skipped": "no leader right now"}
                    return
                srv = self.replicas[idx]
                srv.kill()
                self.replicas[idx] = None
                rec["replica"] = self.endpoints[idx]
                self._pending.append(
                    (time.monotonic() + 1.5, "replica-respawn", idx))
            elif fault == "relay":
                proc = self.relay_proc
                if proc is None or not proc.alive():
                    rec["resolution"] = {"skipped": "relay already down"}
                    return
                # snapshot of the relay consumer's cursor: resolution
                # demands it ADVANCES past this after the respawn
                rec["relay_rev_at_inject"] = max(self.probe.relay_seen,
                                                 default=0)
                fl.ProcessChaos.sigkill(proc)
                rec["pid"] = proc.pid
                self._pending.append(
                    (time.monotonic() + max(event.duration, 1.0),
                     "relay-respawn", None))
            elif fault == "ckpt-corrupt":
                slot = int(event.target.split(":", 1)[1])
                mode = ("bitflip" if self.args.weaken_checksums
                        else event.params.get("mode", "bitflip"))
                done = None
                for probe_slot in ([slot] + list(range(self.args.pods))):
                    done = fl.CheckpointCorruptor.corrupt(
                        os.path.join(self.ckpt_root, f"pod{probe_slot}"),
                        self.rng, mode)
                    if done is not None:
                        break
                if done is None:
                    rec["resolution"] = {"skipped": "no sealed ckpt yet"}
                else:
                    rec["corrupted"] = done
            elif fault == "resize":
                snap = self.state.random_resize()
                rec["desired"] = snap["desired_nodes"]
            elif fault == "reform":
                # the compound drill: a resize AND a mid-phase fault —
                # the workers' reform ladders must complete or cleanly
                # downgrade under it (the auditor's I6 pairs every
                # reform_start with its outcome)
                snap = self.state.random_resize()
                rec["desired"] = snap["desired_nodes"]
                sub = event.params.get("sub", "kill-donor")
                rec["sub"] = sub
                if sub == "partition-store":
                    idx = self._resolve_replica("replica:follower")
                    if idx is not None:
                        srv = self.replicas[idx]
                        fl.StorePartitioner.sever(srv.node, True)
                        rec["replica"] = self.endpoints[idx]
                        self._pending.append(
                            (time.monotonic() + event.duration,
                             "partition-heal", srv.node))
                else:
                    slot = self.rng.randrange(self.args.pods)
                    handle = self.supervisor.handle(slot)
                    if handle is not None:
                        rec["slot"] = slot
                        if sub == "kill-donor":
                            fl.ProcessChaos.sigkill(handle)
                        else:
                            fl.ProcessChaos.sigstop(handle)
                            self._pending.append(
                                (time.monotonic() + event.duration,
                                 "sigcont", handle))
            elif fault == "preempt":
                # spot preemption: a NOTICE now, the hard kill exactly
                # at the deadline (never before — I7 audits the order).
                # The worker's contract is quiesce-seal-donate inside
                # the window; --weaken-preempt turns that honoring off
                # and the auditor must then catch the lost progress.
                slot = int(event.target.split(":", 1)[1])
                ent = self.supervisor.entry(slot)
                if ent is None:
                    rec["resolution"] = {"skipped": f"no pod at {slot}"}
                    return
                pod_id, proc = ent
                if not proc.alive():
                    rec["resolution"] = {"skipped":
                                         f"pod{slot} dead at notice"}
                    return
                if pod_id in self._noticed:
                    rec["resolution"] = {"skipped":
                                         f"{pod_id} already noticed"}
                    return
                self._noticed.add(pod_id)
                deadline = time.time() + event.duration
                self.store.put(preempt_key(JOB, pod_id), json.dumps(
                    {"deadline_unix": round(deadline, 3), "nodes": 1}))
                rec["slot"] = slot
                rec["pod_id"] = pod_id
                self._pending.append(
                    (time.monotonic() + event.duration, "preempt-kill",
                     (proc, rec)))
            elif fault == "pool-resize":
                delta = int(event.params.get("delta", 1))
                cur = self.pool_journal[-1]["to"]
                if cur <= 1:
                    delta = abs(delta)   # a clamped no-op exercises
                elif cur >= 4:           # nothing: bounce off the rails
                    delta = -abs(delta)  # so grows AND drains happen
                desired = max(1, min(4, cur + delta))
                self.pool_journal.append({"to": desired,
                                          "ts": round(time.time(), 3)})
                self.actuator.resize(desired)
                rec["desired"] = desired
        except Exception as exc:  # noqa: BLE001 — an injector crashing
            # is a soak bug, not a system breach; surface it loudly
            rec["resolution"] = {"typed_error": f"injector: {exc}"}
            log.exception("injector for %s failed", fault)

    def run_pending(self) -> None:
        now = time.monotonic()
        due = [p for p in self._pending if p[0] <= now]
        self._pending = [p for p in self._pending if p[0] > now]
        for _, kind, payload in due:
            try:
                if kind == "wire-heal":
                    payload.uninstall()
                    if self._wire_active is payload:
                        self._wire_active = None
                elif kind == "sigcont":
                    fl.ProcessChaos.sigcont(payload)
                elif kind == "partition-heal":
                    fl.StorePartitioner.heal(payload)
                elif kind == "replica-respawn":
                    self._respawn_replica(payload)
                elif kind == "relay-respawn":
                    self._spawn_relay()
                elif kind == "preempt-kill":
                    proc, inj = payload
                    if proc.alive():
                        fl.ProcessChaos.sigkill(proc)
                    # the audit holds kill_wall >= notice + window:
                    # this stamp is the kill side of that contract
                    inj["kill_wall"] = round(time.time(), 3)
                    self._noticed.discard(inj.get("pod_id", ""))
            except Exception:  # noqa: BLE001 — retried at settle
                log.exception("pending action %s failed", kind)

    def _respawn_replica(self, idx: int) -> None:
        if self.replicas[idx] is not None:
            return
        port = int(self.endpoints[idx].rsplit(":", 1)[1])
        try:
            self.replicas[idx] = ReplicaServer(
                self.endpoints[idx], port, host="127.0.0.1",
                group_endpoints=self.endpoints,
                election_ttl=0.6, commit_timeout=1.5).start()
            log.info("respawned replica %s", self.endpoints[idx])
        except OSError as exc:
            log.warning("replica respawn %s failed (%s); retrying",
                        self.endpoints[idx], exc)
            self._pending.append(
                (time.monotonic() + 1.0, "replica-respawn", idx))

    # -- the run ------------------------------------------------------------

    def storm(self, schedule: ChaosSchedule) -> None:
        t0 = time.monotonic()
        for event in schedule:
            while time.monotonic() - t0 < event.t:
                self.run_pending()
                time.sleep(0.03)
            log.info("inject t=%.2f %s @ %s", event.t, event.fault,
                     event.target)
            self.inject(event)
        # drain remaining heals
        while self._pending:
            self.run_pending()
            time.sleep(0.05)

    def settle(self) -> None:
        """Heal everything, then give the world a bounded window to
        converge before the audit freezes the artifacts."""
        if self._wire_active is not None:
            self._wire_active.uninstall()
            self._wire_active = None
        for srv in self.replicas:
            if srv is not None:
                fl.StorePartitioner.heal(srv.node)
        for i, srv in enumerate(self.replicas):
            if srv is None:
                self._respawn_replica(i)
        self._spawn_relay()
        self.supervisor.resume_all()
        self._wait_leader(20.0)
        deadline = time.monotonic() + self.args.settle_s
        while time.monotonic() < deadline:
            desired = self.state.snapshot()["desired_nodes"]
            live = self.supervisor.live_slots()
            if len(live) == desired and all(live.values()) \
                    and self.probe.probe_put():
                break
            time.sleep(0.2)
        # one more worker verify pass over the final checkpoint state
        time.sleep(1.5)

    def resolve(self) -> None:
        """Fill every injection's resolution from the artifacts.

        Bounded retry: recovery is asynchronous (a respawned worker is
        still claiming its rank, a fresh incarnation still mid-verify
        over a corrupted dir), so an unrecovered verdict is re-derived
        from fresh artifacts for up to ~12 s before it stands. A fault
        that STAYS unrecovered past the window is the breach."""
        deadline = time.monotonic() + 12.0
        while True:
            self._resolve_pass()
            failed = [i for i in self.injections
                      if i["resolution"] is not None
                      and i["resolution"].get("recovered") is False]
            if not failed or time.monotonic() >= deadline:
                return
            for inj in failed:
                inj["resolution"] = None
            time.sleep(1.0)

    def _resolve_pass(self) -> None:
        reports = self._reports_by_slot()
        probe_ok = self.probe.probe_put()
        leader_ok = self._leader_index() is not None
        desired = self.state.snapshot()["desired_nodes"]
        live = self.supervisor.live_slots()
        for inj in self.injections:
            if inj["resolution"] is not None:
                continue
            fault = inj["fault"]
            if fault == "wire":
                inj["resolution"] = (
                    {"recovered": True, "probe_put": True} if probe_ok
                    else {"recovered": False,
                          "detail": "store unreachable after heal"})
            elif fault == "process-kill":
                inj["resolution"] = self._resolve_respawn(inj, reports)
            elif fault == "process-pause":
                slot = inj.get("slot")
                after = [r for r in reports.get(f"pod{slot}", ())
                         if r.get("ts", 0) > inj["wall"]
                         + inj["duration"]]
                inj["resolution"] = (
                    {"recovered": True} if after else
                    {"recovered": False,
                     "detail": f"pod{slot} silent after SIGCONT"})
            elif fault in ("store-partition", "leader-kill"):
                inj["resolution"] = (
                    {"recovered": True} if (leader_ok and probe_ok) else
                    {"recovered": False,
                     "detail": f"leader={leader_ok} probe={probe_ok}"})
            elif fault == "ckpt-corrupt":
                inj["resolution"] = self._resolve_corrupt(inj, reports)
            elif fault == "resize":
                ok = len(live) == desired and all(live.values())
                inj["resolution"] = (
                    {"recovered": True} if ok else
                    {"recovered": False,
                     "detail": f"live={live} desired={desired}"})
            elif fault == "reform":
                # world converged + the store answers again; the reform
                # PROTOCOL (every ladder completes or cleanly
                # downgrades) is I6's job over the worker reports
                ok = (len(live) == desired and all(live.values())
                      and probe_ok)
                inj["resolution"] = (
                    {"recovered": True} if ok else
                    {"recovered": False,
                     "detail": f"live={live} desired={desired} "
                               f"probe={probe_ok}"})
            elif fault == "relay":
                alive = (self.relay_proc is not None
                         and self.relay_proc.alive())
                # recovered = respawned AND the relay consumer's cursor
                # moved past where it stood at the kill — the stream
                # RESUMED, it didn't just reconnect to silence. (Loss/
                # duplication accounting is I1's job over relay_seen.)
                cursor = max(self.probe.relay_seen, default=0)
                moved = cursor > inj.get("relay_rev_at_inject", 0)
                inj["resolution"] = (
                    {"recovered": True, "relay_rev": cursor}
                    if alive and moved else
                    {"recovered": False,
                     "detail": f"alive={alive} cursor={cursor} "
                               f"at_inject="
                               f"{inj.get('relay_rev_at_inject')}"})
            elif fault == "preempt":
                # process-level recovery only: the respawned
                # incarnation re-registers. Whether the NOTICE was
                # honored (seal-donate before the deadline, nothing
                # lost) is I7's job over the reports.
                inj["resolution"] = self._resolve_respawn(inj, reports)
            elif fault == "pool-resize":
                want = self.pool_journal[-1]["to"]
                got = self.actuator.pool_size()
                inj["resolution"] = (
                    {"recovered": True} if got == want else
                    {"recovered": False,
                     "detail": f"pool={got} wanted={want}"})
            else:
                inj["resolution"] = {"skipped": f"unknown fault {fault}"}

    def _resolve_respawn(self, inj: dict, reports: dict) -> dict:
        slot = inj.get("slot")
        regs = [r for r in reports.get(f"pod{slot}", ())
                if r.get("kind") == "registered"
                and r.get("ts", 0) > inj["wall"]]
        if not regs:
            # a slot the world shrank below is RETIRED, not owed a
            # respawn — the kill resolved into the smaller world
            if slot >= self.state.snapshot()["desired_nodes"]:
                return {"recovered": True,
                        "detail": f"slot {slot} retired by shrink"}
            return {"recovered": False,
                    "detail": f"no re-registration on slot {slot}"}
        downtime = regs[0]["ts"] - inj["wall"]
        self.max_downtime_s = max(self.max_downtime_s, downtime)
        return {"recovered": True, "downtime_s": round(downtime, 3)}

    def _resolve_corrupt(self, inj: dict, reports: dict) -> dict:
        if self.args.weaken_checksums:
            # detection is OFF by design: the breach must come from the
            # auditor's bitwise check, not from runtime verification
            return {"skipped": "checksums weakened — audit must catch"}
        done = inj.get("corrupted") or {}
        slot_dir = os.path.basename(done.get("root", ""))
        hits = [r for r in reports.get(slot_dir, ())
                if r.get("kind") == "ckpt_corrupt_detected"
                and int(r.get("version", -1)) == int(done.get("version",
                                                             -2))]
        if hits:
            return {"recovered": True, "typed_error": hits[0]["error"]}
        return {"recovered": False,
                "detail": f"corruption of {done} never detected"}

    def _reports_by_slot(self) -> dict[str, list[dict]]:
        """Worker reports merged per SLOT (incarnations share a slot's
        checkpoint dir, so seal/restore pairing must merge them)."""
        merged: dict[str, list[dict]] = {}
        for pod_id, records in load_worker_reports(
                self.report_dir).items():
            slot = pod_id.split("-", 1)[0]
            merged.setdefault(slot, []).extend(records)
        for records in merged.values():
            records.sort(key=lambda r: r.get("ts", 0.0))
        return merged

    # -- teardown + audit ---------------------------------------------------

    def shutdown(self) -> dict:
        """Idempotent teardown (the crash path calls it too)."""
        if getattr(self, "_closed", False):
            return getattr(self, "_probe_doc", {})
        self._closed = True
        probe_doc = {}
        if hasattr(self, "probe"):
            probe_doc = self.probe.stop_and_collect()
        for name in ("scaler", "supervisor"):
            if hasattr(self, name):
                getattr(self, name).stop()
        if hasattr(self, "actuator"):
            self.actuator.wait_drains(
                timeout=self.args.drain_deadline + 5)
            self.actuator.close()
        if hasattr(self, "job_server"):
            self.job_server.stop()
        if getattr(self, "relay_proc", None) is not None:
            terminate_trainer(self.relay_proc, grace=2.0)
        for srv in getattr(self, "replicas", []):
            if srv is not None:
                srv.stop()
        for name in ("store", "scaler_store"):
            if hasattr(self, name):
                getattr(self, name).close()
        self._probe_doc = probe_doc
        return probe_doc

    def cleanup(self) -> None:
        if self._own_artifacts:
            shutil.rmtree(self.artifacts, ignore_errors=True)


def run_soak(args) -> int:
    mix = None
    if getattr(args, "mix", None):
        mix = [m.strip() for m in args.mix.split(",") if m.strip()]
    schedule = ChaosSchedule.generate(args.seed, args.ticks,
                                      tick_s=args.tick_s, pods=args.pods,
                                      mix=mix)
    print(f"chaos schedule: seed={args.seed} ticks={args.ticks} "
          f"events={len(schedule)} classes={sorted(schedule.classes())} "
          f"fingerprint={schedule.fingerprint()}", flush=True)
    if args.print_schedule:
        for e in schedule:
            print(json.dumps(e.to_dict(), sort_keys=True))
        return 0

    os.environ.setdefault("EDL_TPU_WIRE_STALL_S", "10")
    lock_report = None
    if args.lockgraph:
        from edl_tpu.analysis import lockgraph
        graph = lockgraph.install()

    # Global deadline: a soak that WEDGES is itself an invariant breach
    # (the "never a hang" clause) — die loudly with a diagnosis instead
    # of hanging CI.
    budget = args.ticks * args.tick_s + args.settle_s + 90.0
    hang = threading.Timer(budget, _die_hanging, args=(budget,))
    hang.daemon = True
    hang.start()

    world = SoakWorld(args)
    # the run-dir dump + I2 third witness must cover THIS storm only
    flight.recorder().clear()
    try:
        world.build()
        world.storm(schedule)
        world.settle()
        world.resolve()
        probe_doc = world.shutdown()
        if args.lockgraph:
            lock_report = graph.report()

        # Flight-recorder collection: the soak process's own ring (the
        # JobServer/actuator/replica events live here) lands in the run
        # dir beside the workers' dumps (each worker wrote its own
        # flight-<pod>.json on exit/crash) — and the auditor reads the
        # ring's resize events as a third witness for I2.
        recorder_dump = flight.recorder().to_dict(reason="soak-end")
        with open(os.path.join(world.artifacts, "flight-soak.json"),
                  "w") as f:
            json.dump(recorder_dump, f, indent=1, default=str)

        auditor = InvariantAuditor(
            injections=world.injections,
            worker_reports=world._reports_by_slot(),
            probe=probe_doc,
            scaler_journal=_load_journal(world.journal_path),
            job_resize_log=list(world.state.resize_log),
            pool_journal=world.pool_journal,
            pool_resize_log=list(world.actuator.resize_log),
            drain_log=list(world.actuator.drain_log),
            drain_deadline_s=args.drain_deadline,
            recorder=recorder_dump)
        report = auditor.audit()
        if lock_report is not None and not lock_report["ok"]:
            report.breach(f"lockgraph: {len(lock_report['cycles'])} "
                          f"cycles, {len(lock_report['hazards'])} "
                          "hazards")
        report.stats["fault_classes"] = sorted(
            {i["fault"] for i in world.injections})
        report.stats["max_downtime_s"] = round(world.max_downtime_s, 3)
        report.stats["schedule_fingerprint"] = schedule.fingerprint()
        report.stats["seed"] = args.seed
        with open(os.path.join(world.artifacts, "chaos_report.json"),
                  "w") as f:
            json.dump({"report": report.to_dict(),
                       "injections": world.injections}, f, indent=1)
        print("chaos_summary=" + json.dumps(report.to_dict(),
                                            sort_keys=True), flush=True)
        for b in report.breaches:
            log.error("INVARIANT BREACH: %s", b)
        if report.ok:
            print(f"chaos soak: {report.stats['faults_injected']} faults "
                  f"across {len(report.stats['fault_classes'])} classes, "
                  "zero invariant breaches")
        else:
            print(f"chaos soak: {len(report.breaches)} invariant "
                  "breach(es)")
        return 0 if report.ok else 1
    finally:
        hang.cancel()
        try:
            world.shutdown()
        except Exception:  # noqa: BLE001 — teardown on the crash path
            log.exception("soak teardown failed")
        if args.lockgraph:
            from edl_tpu.analysis import lockgraph
            lockgraph.uninstall()
        world.cleanup()


def _load_journal(path: str) -> list[dict]:
    from edl_tpu.chaos.audit import load_jsonl
    return load_jsonl(path)


def _die_hanging(budget: float) -> None:
    import faulthandler
    print(f"chaos soak exceeded its {budget:.0f}s global deadline — "
          "dumping stacks and aborting (a hang IS a breach)",
          flush=True)
    faulthandler.dump_traceback()
    os._exit(3)
