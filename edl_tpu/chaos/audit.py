"""Post-hoc invariant audits over the artifacts the planes already emit.

The auditor never inspects live state — it reads what the system wrote
while it ran: worker JSONL reports, the soak's injection journal, the
scaler's decision journal (file), the JobServer's ``resize_log``, the
pool actuator's ``resize_log``/``drain_log``, and the store probe's
acked-vs-delivered ledgers. Every invariant is therefore checkable
after the fact, replayable from a failed run's artifact directory, and
independent of timing.

Invariants (doc/design_chaos.md maps each to its artifact):

  I1  zero lost / zero duplicated watch events, by revision audit
      (acked writes vs the probe watcher's deliveries + final resync)
  I2  scaler journal <-> JobServer resize_log one-for-one (and pool
      journal <-> actuator resize_log)
  I3  restored state bitwise-equal to its sealed version (seal digest
      == restore digest, per retained version) unless the corruption
      was DETECTED and typed
  I4  no hard kills outside the drain deadline (drain_log)
  I5  every injected fault either recovered or surfaced as a typed
      error — never silently unresolved
  I6  every reform either completes in place or degrades to a clean
      stop-resume — never a wedge, never a torn world (every
      `reform_start` in a worker report pairs with a `reform_done`
      whose result is "in-place" or "stop-resume", unless the worker
      died mid-ladder — which is a process fault the respawn covers)
  I7  a NOTICED spot preemption rides as a scheduled shrink: the
      worker quiesce-seal-donates (`preempt_ready`) before the
      deadline, the hard kill never lands before the deadline, and
      the respawned incarnation restores a version >= the preempt
      seal — no acked progress lost across a noticed preemption
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field


@dataclass
class ChaosReport:
    breaches: list[str] = field(default_factory=list)
    stats: dict = field(default_factory=dict)

    def breach(self, what: str) -> None:
        self.breaches.append(what)

    @property
    def ok(self) -> bool:
        return not self.breaches

    def to_dict(self) -> dict:
        return {"ok": self.ok, "breaches": self.breaches,
                "stats": self.stats}


def load_jsonl(path: str) -> list[dict]:
    """Tolerates a torn final line (a SIGKILL'd writer)."""
    out: list[dict] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return out


class InvariantAuditor:
    """Audit one soak run's artifacts into a `ChaosReport`."""

    def __init__(self, *, injections: list[dict],
                 worker_reports: dict[str, list[dict]],
                 probe: dict,
                 scaler_journal: list[dict],
                 job_resize_log: list[dict],
                 pool_journal: list[dict],
                 pool_resize_log: list[dict],
                 drain_log: list[dict],
                 drain_deadline_s: float,
                 recorder: dict | None = None):
        self.injections = injections
        self.worker_reports = worker_reports
        self.probe = probe
        self.scaler_journal = scaler_journal
        self.job_resize_log = job_resize_log
        self.pool_journal = pool_journal
        self.pool_resize_log = pool_resize_log
        self.drain_log = drain_log
        self.drain_deadline_s = drain_deadline_s
        # flight-recorder dump of the soak process (obs/recorder.py
        # to_dict shape) — I2's third witness when provided
        self.recorder = recorder

    # -- I1: the mark stream -----------------------------------------------

    def _audit_probe(self, rep: ChaosReport) -> None:
        acked: dict[str, int] = self.probe.get("acked", {})
        seen: dict[int, str] = {int(k): v for k, v in
                                self.probe.get("seen", {}).items()}
        final: set[str] = set(self.probe.get("final_values", ()))
        dup = int(self.probe.get("duplicates", 0))
        if dup:
            rep.breach(f"I1: {dup} duplicate watch deliveries")
        # Commit-gated fan-out made this a hard invariant (it was a
        # documented stat before r20): a watcher that observes the same
        # revision with two different values saw a doomed leader's
        # uncommitted suffix — which the commit gate must make
        # impossible. Pinned to ZERO.
        branch = int(self.probe.get("branch_anomalies", 0))
        if branch:
            rep.breach(f"I1: {branch} branch anomalies — a watcher "
                       "observed uncommitted (later-discarded) entries; "
                       "commit-gated fan-out is broken")
        rep.stats["branch_anomalies"] = branch
        # Loss is judged by VALUE, not by (value, revision): across a
        # leader failover a watcher may have observed the deposed
        # leader's uncommitted suffix — entries later discarded and
        # whose revision numbers the new reign reuses (the documented
        # weaker-than-Raft gap, surfaced by this very soak; see
        # doc/design_chaos.md). The contract the elastic machinery
        # consumes — every ACKED write delivered (or visible after
        # resync), no revision delivered twice — is what I1 holds.
        delivered = set(seen.values())
        lost = [v for v in acked
                if v not in delivered and v not in final]
        if lost:
            rep.breach(f"I1: {len(lost)} acked marks neither delivered "
                       f"nor visible after resync (e.g. {lost[:3]})")
        rep.stats["marks_acked"] = len(acked)
        rep.stats["marks_delivered"] = len(seen)
        # Relay-attached consumer (coord/relay.py): when the probe ran
        # a second watch THROUGH the watch-relay tier, the exact same
        # exactly-once bar applies to its ledger — a relay SIGKILL must
        # look like a server restart (resume by revision), so zero
        # duplicates, zero branch anomalies, and every acked value
        # delivered or visible after resync, same as the direct path.
        relay_seen_raw = self.probe.get("relay_seen")
        if relay_seen_raw is not None:
            relay_seen = {int(k): v for k, v in relay_seen_raw.items()}
            rdup = int(self.probe.get("relay_duplicates", 0))
            if rdup:
                rep.breach(f"I1: {rdup} duplicate deliveries through "
                           "the relay tier")
            rbranch = int(self.probe.get("relay_branch_anomalies", 0))
            if rbranch:
                rep.breach(f"I1: {rbranch} branch anomalies through the "
                           "relay — relayed watchers observed "
                           "uncommitted entries; the relay leaked past "
                           "the commit gate")
            relay_delivered = set(relay_seen.values())
            rlost = [v for v in acked
                     if v not in relay_delivered and v not in final]
            if rlost:
                rep.breach(f"I1: {len(rlost)} acked marks lost through "
                           f"the relay tier (e.g. {rlost[:3]})")
            rep.stats["relay_marks_delivered"] = len(relay_seen)
            rep.stats["relay_branch_anomalies"] = rbranch
        # Worker-side sequence observability: within one watch session
        # revisions normally increase strictly. Across a leader
        # failover they may NOT — the same uncommitted-suffix anomaly
        # as above (a watcher that observed the doomed branch re-sees
        # reused revision numbers on the new branch). That is a
        # documented contract gap, not a per-run breach, so anomalies
        # are COUNTED (the stat makes the gap visible in every soak
        # summary) while the exactly-once-by-value gate above stays the
        # hard invariant. A new incarnation ("started") or a fresh
        # subscription ("watch_created") legitimately resets the
        # cursor.
        anomalies = 0
        for records in self.worker_reports.values():
            last = -1
            for r in records:
                kind = r.get("kind")
                if kind in ("started", "watch_created"):
                    last = -1
                elif kind == "watch":
                    revs = r.get("revisions", [])
                    anomalies += sum(1 for a, b in zip([last] + revs,
                                                       revs) if b <= a)
                    if revs:
                        last = max(last, revs[-1])
                elif kind == "watch_compacted":
                    last = max(last, int(r.get("resync_rev", last)))
        rep.stats["watch_sequence_anomalies"] = anomalies

    # -- I2: journals vs served logs ---------------------------------------

    def _audit_journals(self, rep: ChaosReport) -> None:
        journaled = [int(e["applied"]) for e in self.scaler_journal
                     if e.get("action") == "resize"
                     and e.get("applied") is not None]
        served = [int(e["to"]) for e in self.job_resize_log
                  if e.get("source") == "resize"]
        if journaled != served:
            rep.breach(f"I2: scaler journal {journaled} != JobServer "
                       f"served resizes {served}")
        rep.stats["scaler_resizes"] = len(served)
        pool_asked = [int(e["to"]) for e in self.pool_journal]
        pool_served = [int(e["to"]) for e in self.pool_resize_log]
        if pool_asked != pool_served:
            rep.breach(f"I2: pool journal {pool_asked} != actuator "
                       f"resize_log {pool_served}")
        rep.stats["pool_resizes"] = len(pool_served)
        # Third witness (obs flight recorder): the serving path records
        # one ring event per resize AS IT HAPPENS — independent of both
        # the journal (scaler-side) and resize_log (server-side) lists.
        # All three must tell the same story. Skipped when the ring
        # overflowed (events aged out -> the comparison is void, and
        # the stat says so) or no dump was provided.
        if self.recorder is not None:
            events = self.recorder.get("events", [])
            if int(self.recorder.get("dropped", 0)) > 0:
                rep.stats["recorder_witness"] = "overflowed"
            else:
                rec_job = [int(e["to"]) for e in events
                           if e.get("kind") == "resize"
                           and e.get("plane") == "job"
                           and e.get("source") == "resize"]
                if rec_job != served:
                    rep.breach(f"I2: flight recorder saw job resizes "
                               f"{rec_job} != resize_log {served}")
                rec_pool = [int(e["to"]) for e in events
                            if e.get("kind") == "resize"
                            and e.get("plane") == "serving"]
                if rec_pool != pool_served:
                    rep.breach(f"I2: flight recorder saw pool resizes "
                               f"{rec_pool} != actuator {pool_served}")
                rep.stats["recorder_witness"] = "ok"
                rep.stats["recorder_events"] = len(events)

    # -- I3: checkpoint bitwise equality ------------------------------------

    def _audit_checkpoints(self, rep: ChaosReport) -> None:
        sealed = 0
        for pod, records in self.worker_reports.items():
            # seal digests per (slot dir is shared across incarnations,
            # so merge every incarnation of the slot before judging)
            seals: dict[int, str] = {}
            for r in records:
                if r.get("kind") == "seal":
                    seals[int(r["version"])] = r["digest"]
                    sealed += 1
            detected = {int(r["version"]) for r in records
                        if r.get("kind") == "ckpt_corrupt_detected"}
            flagged: set[int] = set()
            for r in records:
                if r.get("kind") != "restore":
                    continue
                v = int(r["version"])
                want = seals.get(v)
                if want is None or v in flagged:
                    continue
                if r["digest"] != want and v not in detected:
                    flagged.add(v)
                    rep.breach(
                        f"I3: {pod} restored ckpt-{v} with digest "
                        f"{r['digest'][:12]} != sealed "
                        f"{want[:12]} and no corruption was detected")
        rep.stats["versions_sealed"] = sealed

    # -- I4: drain discipline -----------------------------------------------

    def _audit_drains(self, rep: ChaosReport) -> None:
        for entry in self.drain_log:
            if entry.get("hard_killed") \
                    and float(entry.get("wait_s", 0.0)) \
                    < self.drain_deadline_s:
                rep.breach(f"I4: {entry.get('endpoint')} hard-killed "
                           f"after only {entry.get('wait_s')}s (deadline "
                           f"{self.drain_deadline_s}s)")
        rep.stats["drains"] = len(self.drain_log)
        rep.stats["hard_kills"] = sum(1 for e in self.drain_log
                                      if e.get("hard_killed"))

    # -- I5: every fault resolved -------------------------------------------

    def _audit_faults(self, rep: ChaosReport) -> None:
        survived = 0
        for inj in self.injections:
            res = inj.get("resolution")
            if res is None:
                rep.breach(f"I5: fault {inj.get('fault')} @ "
                           f"{inj.get('target')} t={inj.get('t')} has no "
                           "resolution (injected but never verified)")
            elif res.get("recovered") or res.get("typed_error") \
                    or res.get("skipped"):
                survived += 1
            else:
                rep.breach(f"I5: fault {inj.get('fault')} @ "
                           f"{inj.get('target')} unresolved: {res}")
        rep.stats["faults_injected"] = len(self.injections)
        rep.stats["faults_survived"] = survived

    # -- I6: reform ladders complete or cleanly downgrade --------------------

    # records a reform ladder legitimately writes between its start and
    # its outcome (the restore halves report through the same rig)
    _LADDER_KINDS = frozenset({"restore", "ckpt_corrupt_detected",
                               "ckpt_fallback"})
    _REFORM_RESULTS = frozenset({"in-place", "stop-resume"})

    def _audit_reforms(self, rep: ChaosReport) -> None:
        started = completed = downgrades = died = 0
        for pod, records in self.worker_reports.items():
            for i, r in enumerate(records):
                if r.get("kind") != "reform_start":
                    continue
                started += 1
                gen = int(r.get("generation", -1))
                verdict = None
                for s in records[i + 1:]:
                    kind = s.get("kind")
                    if kind == "reform_done" \
                            and int(s.get("generation", -1)) >= gen:
                        verdict = "done"
                        result = s.get("result")
                        if result not in self._REFORM_RESULTS:
                            rep.breach(
                                f"I6: {pod} reform gen={gen} ended "
                                f"with unknown result {result!r}")
                        else:
                            completed += 1
                            if result == "stop-resume":
                                downgrades += 1
                        break
                    if kind == "started":
                        # a fresh incarnation: the worker died
                        # mid-ladder — a process fault (respawn
                        # covers it), not a wedge
                        verdict = "died"
                        died += 1
                        break
                    if kind not in self._LADDER_KINDS:
                        verdict = "wedged"
                        rep.breach(
                            f"I6: {pod} reform gen={gen} neither "
                            f"completed nor degraded — the worker "
                            f"moved on ({kind!r}) with the ladder "
                            "open (torn world)")
                        break
                # ladder still in flight when the run froze its
                # artifacts (no further records): not a wedge — the
                # settle window bounds how often this can happen
        rep.stats["reforms_started"] = started
        rep.stats["reforms_completed"] = completed
        rep.stats["reform_downgrades"] = downgrades
        rep.stats["reforms_died_midladder"] = died

    # -- I7: noticed preemptions ride as scheduled seal-and-donate -----------

    def _audit_preempts(self, rep: ChaosReport) -> None:
        noticed = ridden = 0
        for inj in self.injections:
            if inj.get("fault") != "preempt":
                continue
            res = inj.get("resolution") or {}
            if "skipped" in res:
                continue
            noticed += 1
            slot = inj.get("slot")
            records = self.worker_reports.get(f"pod{slot}", [])
            wall = float(inj.get("wall", 0.0))
            deadline = wall + float(inj.get("duration", 0.0))
            kill = inj.get("kill_wall")
            horizon = (kill if kill is not None else deadline) + 0.5
            ready = [r for r in records
                     if r.get("kind") == "preempt_ready"
                     and wall <= r.get("ts", 0.0) <= horizon]
            if not ready:
                rep.breach(
                    f"I7: pod{slot} hard-killed at t={inj.get('t')} "
                    "with no preempt_ready — the spot notice was not "
                    "honored (no quiesce-seal-donate before the "
                    "deadline)")
                continue
            ok = True
            if kill is not None and kill < deadline - 0.25:
                ok = False
                rep.breach(
                    f"I7: pod{slot} killed {deadline - kill:.2f}s "
                    "BEFORE the notice deadline — the window is a "
                    "contract, not a suggestion")
            # no acked progress lost: the respawned incarnation must
            # restore a version >= the one sealed at the notice (the
            # donated worker seals nothing afterwards, so that IS the
            # newest acked state). I3 separately holds the digests.
            seals = [int(r["version"]) for r in records
                     if r.get("kind") == "seal"
                     and r.get("ts", 0.0) <= ready[0]["ts"]]
            restores = [int(r["version"]) for r in records
                        if r.get("kind") == "restore"
                        and r.get("ts", 0.0) > (kill or deadline)]
            retired = "retired" in str(res.get("detail", ""))
            if seals and not restores and not retired:
                ok = False
                rep.breach(
                    f"I7: pod{slot} never restored after the "
                    "preemption kill — the donated seal went unread")
            elif seals and restores and max(restores) < max(seals):
                ok = False
                rep.breach(
                    f"I7: pod{slot} restored ckpt-{max(restores)} < "
                    f"the preempt seal ckpt-{max(seals)} — acked "
                    "progress lost across a NOTICED preemption")
            if ok:
                ridden += 1
        rep.stats["preempts_noticed"] = noticed
        rep.stats["preempts_ridden"] = ridden

    def audit(self) -> ChaosReport:
        rep = ChaosReport()
        self._audit_probe(rep)
        self._audit_journals(rep)
        self._audit_checkpoints(rep)
        self._audit_drains(rep)
        self._audit_faults(rep)
        self._audit_reforms(rep)
        self._audit_preempts(rep)
        typed = sum(1 for recs in self.worker_reports.values()
                    for r in recs if r.get("kind") == "typed_error")
        rep.stats["worker_typed_errors"] = typed
        return rep


def load_worker_reports(report_dir: str) -> dict[str, list[dict]]:
    out: dict[str, list[dict]] = {}
    try:
        names = sorted(os.listdir(report_dir))
    except OSError:
        return out
    for name in names:
        if name.endswith(".jsonl"):
            out[name[:-6]] = load_jsonl(os.path.join(report_dir, name))
    return out
