"""CLI: ``python -m edl_tpu.chaos {soak,schedule,worker}``."""

from __future__ import annotations

import argparse
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="edl_tpu.chaos",
        description="seeded fault injection + invariant audits")
    sub = parser.add_subparsers(dest="cmd", required=True)

    soak = sub.add_parser(
        "soak", help="run the single-host elastic world under a seeded "
                     "fault schedule; exit nonzero on invariant breach")
    soak.add_argument("--seed", type=int, default=1)
    soak.add_argument("--ticks", type=int, default=24,
                      help="faults to inject (one per schedule tick)")
    soak.add_argument("--tick-s", type=float, default=1.5)
    soak.add_argument("--pods", type=int, default=2,
                      help="initial trainer-world size")
    soak.add_argument("--max-nodes", type=int, default=4)
    soak.add_argument("--settle-s", type=float, default=12.0,
                      help="post-storm convergence window")
    soak.add_argument("--drain-deadline", type=float, default=5.0)
    soak.add_argument("--artifacts", default=None,
                      help="keep run artifacts (reports, journals, "
                           "chaos_report.json) in this dir")
    soak.add_argument("--weaken-checksums", action="store_true",
                      help="disable chunk crc verification in workers: "
                           "the injected corruption must then be caught "
                           "by the AUDITOR (run exits nonzero)")
    soak.add_argument("--weaken-preempt", action="store_true",
                      help="workers IGNORE spot-preemption notices "
                           "(EDL_TPU_SPOT_NOTICE_S=0): the hard kill "
                           "then lands on unsealed progress and the "
                           "auditor's I7 must catch it (run exits "
                           "nonzero)")
    soak.add_argument("--mix", default=None,
                      help="comma-joined fault-class subset (default: "
                           "every class)")
    soak.add_argument("--print-schedule", action="store_true",
                      help="print the seeded schedule and exit")
    soak.add_argument("--no-lockgraph", dest="lockgraph",
                      action="store_false",
                      help="skip the lock-order race detector")

    sched = sub.add_parser(
        "schedule", help="print a seed's fault schedule + fingerprint "
                         "(the replay contract, stdlib-only)")
    sched.add_argument("--seed", type=int, default=1)
    sched.add_argument("--ticks", type=int, default=24)
    sched.add_argument("--tick-s", type=float, default=1.5)
    sched.add_argument("--pods", type=int, default=2)

    worker = sub.add_parser(
        "worker", help="one soak pod worker (spawned by the soak's "
                       "supervisor; runnable standalone for debugging)")
    from edl_tpu.chaos.worker import add_worker_args
    add_worker_args(worker)

    args = parser.parse_args(argv)
    if args.cmd == "worker":
        from edl_tpu.chaos.worker import run_worker
        return run_worker(args)
    if args.cmd == "schedule":
        import json

        from edl_tpu.chaos.schedule import ChaosSchedule
        schedule = ChaosSchedule.generate(args.seed, args.ticks,
                                          tick_s=args.tick_s,
                                          pods=args.pods)
        for e in schedule:
            print(json.dumps(e.to_dict(), sort_keys=True))
        print(f"fingerprint={schedule.fingerprint()}")
        return 0
    # soak: prove the orchestrator itself never pulled jax — the chaos
    # gate must run on a box with no accelerator stack
    from edl_tpu.chaos.soak import run_soak
    rc = run_soak(args)
    heavy = [m for m in ("jax", "flax", "optax") if m in sys.modules]
    if heavy:
        print(f"FAIL chaos orchestrator imported {heavy}")
        return rc or 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
