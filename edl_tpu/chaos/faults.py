"""Fault injectors — at the seams, not monkeypatched internals.

Four injector families, one per seam the system already exposes:

- `WireChaos` installs into the fault hooks of ``coord/wire.py`` and
  ``data/tensor_wire.py`` (every framed-JSON and tensor frame crosses
  there): seeded drop (raise), delay (sleep), hard-close, and
  garble-on-read. Faults surface to consumers exactly as real network
  failures do — ConnectionError subtypes on the paths that already
  handle them — so chaos exercises the SAME retry/reconnect/resync
  code production faults would.
- `ProcessChaos` signals real OS process groups through
  ``collective/process.py`` handles: SIGKILL (crash), SIGSTOP/SIGCONT
  (grey failure — alive to the OS, dead to every deadline).
- `StorePartitioner` severs a `ReplicaNode` from a chosen subset of its
  peers (``set_partition``) while its server socket keeps accepting
  clients — including the asymmetric drill where a deposed leader is
  still reachable by a client but cannot reach quorum.
- `CheckpointCorruptor` truncates or bit-flips a sealed chunk file on
  disk (below the npy header, so the corruption is silent to np.load
  and only integrity checksums can catch it).

Everything is driven by the soak's seeded schedule; the injectors
themselves are mechanism, not policy.
"""

from __future__ import annotations

import os
import random
import socket
import struct
import time

from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.chaos.faults")


class ChaosDropped(ConnectionError):
    """A frame eaten by the wire injector (ConnectionError so every
    existing transport-error path handles it as a real network fault)."""


class WireChaos:
    """Seeded per-frame fault policy for the wire seams.

    One instance installs into BOTH wire modules; ``modes`` picks which
    faults are live (drop/delay/close/garble) and ``rate`` the per-frame
    probability. Draws come from the injector's own RNG — the schedule
    (when and which mode) is seed-exact; which individual frame a fault
    eats depends on thread interleaving, by design.
    """

    def __init__(self, seed: int, *, modes: tuple[str, ...] = ("drop",),
                 rate: float = 0.2, delay_s: float = 0.05):
        self._rng = random.Random(seed)
        self.modes = modes
        self.rate = rate
        self.delay_s = delay_s
        self._prev_wire = None
        self._prev_tensor = None
        self._installed = False
        self.frames_faulted = 0

    # -- hook protocol (coord/wire.py + data/tensor_wire.py) ---------------

    def _hit(self) -> bool:
        return self._rng.random() < self.rate

    def on_send(self, sock: socket.socket, nbytes: int) -> None:
        if "delay" in self.modes and self._hit():
            self.frames_faulted += 1
            time.sleep(self.delay_s)
        if "close" in self.modes and self._hit():
            self.frames_faulted += 1
            try:
                sock.close()
            except OSError:
                pass
            raise ChaosDropped("chaos: connection hard-closed on send")
        if "drop" in self.modes and self._hit():
            self.frames_faulted += 1
            raise ChaosDropped(f"chaos: dropped {nbytes}-byte frame")

    def on_recv(self, sock: socket.socket, data: bytes, kind: str) -> bytes:
        if "garble" in self.modes and data and self._hit():
            self.frames_faulted += 1
            i = self._rng.randrange(len(data))
            return data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]
        if "delay" in self.modes and self._hit():
            self.frames_faulted += 1
            time.sleep(self.delay_s)
        return data

    # -- install/uninstall (a scoped window in the soak) -------------------

    def install(self) -> "WireChaos":
        from edl_tpu.coord import wire
        from edl_tpu.data import tensor_wire
        if not self._installed:
            self._prev_wire = wire.install_fault_hook(self)
            self._prev_tensor = tensor_wire.install_fault_hook(self)
            self._installed = True
        return self

    def uninstall(self) -> None:
        from edl_tpu.coord import wire
        from edl_tpu.data import tensor_wire
        if self._installed:
            wire.install_fault_hook(self._prev_wire)
            tensor_wire.install_fault_hook(self._prev_tensor)
            self._installed = False

    def __enter__(self) -> "WireChaos":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


class ProcessChaos:
    """Process-plane faults over `collective/process.py` handles."""

    @staticmethod
    def sigkill(handle) -> bool:
        from edl_tpu.collective.process import kill_trainer
        return kill_trainer(handle)

    @staticmethod
    def sigstop(handle) -> bool:
        from edl_tpu.collective.process import pause_trainer
        return pause_trainer(handle)

    @staticmethod
    def sigcont(handle) -> bool:
        from edl_tpu.collective.process import resume_trainer
        return resume_trainer(handle)


class StorePartitioner:
    """Partition a replica node from (a subset of) its peers. Client
    traffic to the node's own server socket keeps flowing — that is
    the point: the quorum/fencing path is exercised from the CLIENT's
    side, not by making the node vanish."""

    @staticmethod
    def sever(node, peers: bool | list[str] = True) -> None:
        node.set_partition(peers)

    @staticmethod
    def heal(node) -> None:
        node.set_partition(None)


def _npy_data_offset(path: str) -> int:
    """Byte offset where a .npy file's array data starts (v1/v2/v3
    headers) — a corruption below this is invisible to np.load and
    catchable only by integrity checksums."""
    with open(path, "rb") as f:
        magic = f.read(8)
        if magic[:6] != b"\x93NUMPY":
            return 0
        major = magic[6]
        if major >= 2:
            (hlen,) = struct.unpack("<I", f.read(4))
            return 12 + hlen
        (hlen,) = struct.unpack("<H", f.read(2))
        return 10 + hlen


class CheckpointCorruptor:
    """Corrupt a sealed checkpoint chunk on disk, deterministically per
    RNG: pick the newest ``ckpt-N`` under a root, pick a chunk file,
    then bit-flip one payload byte (``bitflip``) or cut the file short
    (``truncate``). Returns a record of what was done — the soak's
    auditor pairs it with the victim's detection report."""

    @staticmethod
    def corrupt(ckpt_root: str, rng: random.Random,
                mode: str = "bitflip") -> dict | None:
        try:
            versions = sorted(
                int(n.split("-", 1)[1]) for n in os.listdir(ckpt_root)
                if n.startswith("ckpt-") and n.split("-", 1)[1].isdigit())
        except OSError:
            return None
        if not versions:
            return None
        version = versions[-1]
        vdir = os.path.join(ckpt_root, f"ckpt-{version}")
        chunks = sorted(n for n in os.listdir(vdir) if n.endswith(".npy"))
        if not chunks:
            return None
        fname = rng.choice(chunks)
        path = os.path.join(vdir, fname)
        size = os.path.getsize(path)
        start = _npy_data_offset(path)
        if mode == "truncate":
            new_size = max(start, int(size * 0.6))
            with open(path, "r+b") as f:
                f.truncate(new_size)
            detail = {"truncated_to": new_size}
        else:
            if size <= start:
                return None  # empty payload: nothing silent to flip
            offset = rng.randrange(start, size)
            with open(path, "r+b") as f:
                f.seek(offset)
                byte = f.read(1)
                f.seek(offset)
                f.write(bytes([byte[0] ^ 0xFF]))
            detail = {"offset": offset}
        log.info("corrupted %s (%s %s)", path, mode, detail)
        return {"root": ckpt_root, "version": version, "file": fname,
                "mode": mode, **detail}
