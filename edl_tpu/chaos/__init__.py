"""Deterministic chaos plane: seeded fault injection at every seam.

``python -m edl_tpu.chaos soak`` runs the full single-host elastic
world under a seed-exact fault schedule and exits nonzero on any
invariant breach — see doc/design_chaos.md for the injector catalog,
the schedule/seed replay contract, and the invariant-to-artifact map.

Lazy (PEP 562): importing the package costs nothing; the orchestrator
itself never imports jax (asserted by the soak), so the chaos gate
runs on a box with no accelerator stack.
"""

_LAZY = {
    "ChaosSchedule": ("edl_tpu.chaos.schedule", "ChaosSchedule"),
    "FaultEvent": ("edl_tpu.chaos.schedule", "FaultEvent"),
    "FAULT_CLASSES": ("edl_tpu.chaos.schedule", "FAULT_CLASSES"),
    "WireChaos": ("edl_tpu.chaos.faults", "WireChaos"),
    "ProcessChaos": ("edl_tpu.chaos.faults", "ProcessChaos"),
    "StorePartitioner": ("edl_tpu.chaos.faults", "StorePartitioner"),
    "CheckpointCorruptor": ("edl_tpu.chaos.faults", "CheckpointCorruptor"),
    "InvariantAuditor": ("edl_tpu.chaos.audit", "InvariantAuditor"),
    "ChaosReport": ("edl_tpu.chaos.audit", "ChaosReport"),
}

__all__ = list(_LAZY)


def __getattr__(name: str):
    try:
        module_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module 'edl_tpu.chaos' has no attribute {name!r}") from None
    import importlib
    value = getattr(importlib.import_module(module_name), attr)
    globals()[name] = value
    return value
