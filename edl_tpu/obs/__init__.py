"""edl_tpu.obs — the unified observability plane.

Three legs, one import surface (doc/design_obs.md):

- :mod:`edl_tpu.obs.metrics` — typed Counter/Gauge/Histogram (fixed
  log buckets: snapshots difference exactly), the per-process
  registry every ``stats()`` dict registers into, the Prometheus-text
  scrape endpoint (``EDL_TPU_METRICS_PORT``) and the store-published
  JSON snapshot;
- :mod:`edl_tpu.obs.trace` — causal spans with context propagated
  in-band through both wire planes (``EDL_TPU_TRACE``), merged and
  exported by ``python -m edl_tpu.obs trace``;
- :mod:`edl_tpu.obs.recorder` — the always-on bounded flight recorder
  ring (``EDL_TPU_FLIGHT_RECORDER_N``), dumped on crash/SIGUSR2 and
  consumed by the chaos InvariantAuditor.

Pure stdlib and jax/numpy-free by contract: the scrape/trace/recorder
plane must run on a scheduler node, a bare CI runner, and inside every
trainer alike. The layering row in analysis/layers.toml makes the
contract a CI gate; ``python -m edl_tpu.obs selftest`` asserts it at
runtime.
"""

from edl_tpu.obs import metrics, recorder, trace

__all__ = ["metrics", "recorder", "trace"]
