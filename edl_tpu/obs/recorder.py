"""Flight recorder: an always-on bounded ring of structured events.

Every process keeps the last N notable control-plane events — elections,
failovers, resizes, drains, corruption detections, chaos faults — in a
fixed-size ring (``EDL_TPU_FLIGHT_RECORDER_N``, default 256; 0 turns
recording off). Recording is a deque append under a leaf lock: cheap
enough to leave on in production, which is the point — when a process
dies, the ring holds the minutes *before* the crash, the part a log
level you'd have to enable in advance always misses.

Dump paths:
- crash: an excepthook chain writes the ring next to the process's
  normal artifacts before delegating to the previous hook;
- ``SIGUSR2``: a live process dumps on demand (the "what has this pod
  seen" probe);
- explicit: ``dump_to(dir)`` — the chaos soak collects every worker's
  ring into the run directory and the InvariantAuditor reads recorder
  resize events as a third witness beside the scaler journal and the
  JobServer resize_log.

Pure stdlib, jax/numpy-free (layers.toml obs row).
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import Any

from edl_tpu.utils import config
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.obs.recorder")

DEFAULT_CAPACITY = 256


class FlightRecorder:
    """Bounded ring of ``{ts, kind, **fields}`` events."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            capacity = config.env_int("EDL_TPU_FLIGHT_RECORDER_N",
                                      DEFAULT_CAPACITY)
        self.capacity = max(0, int(capacity))
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity or 1)       # guarded-by: _lock
        self._total = 0                      # guarded-by: _lock

    def record(self, kind: str, **fields: Any) -> None:
        if self.capacity <= 0:
            return
        event = {"ts": round(time.time(), 6), "kind": str(kind)}
        event.update(fields)
        with self._lock:
            self._ring.append(event)
            self._total += 1

    def events(self, kind: str | None = None) -> list[dict]:
        with self._lock:
            events = list(self._ring)
        if kind is not None:
            events = [e for e in events if e.get("kind") == kind]
        return events

    @property
    def dropped(self) -> int:
        """Events that aged out of the ring (recorded - retained)."""
        with self._lock:
            return self._total - len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._total = 0

    def to_dict(self, reason: str = "dump") -> dict:
        with self._lock:
            events = list(self._ring)
            total = self._total
        return {"pid": os.getpid(), "dumped_at": round(time.time(), 6),
                "reason": reason, "capacity": self.capacity,
                "recorded_total": total,
                "dropped": total - len(events), "events": events}

    def dump(self, path: str, reason: str = "dump") -> str | None:
        """Write the ring as JSON; best-effort (a dump must never turn
        a crash into a different crash). Returns the path or None."""
        try:
            directory = os.path.dirname(path)
            if directory:
                os.makedirs(directory, exist_ok=True)
            doc = self.to_dict(reason)
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(doc, fh, sort_keys=True, default=str)
            os.replace(tmp, path)
            return path
        except Exception:  # noqa: BLE001 — dumping is best-effort
            return None


_GLOBAL = FlightRecorder()


def recorder() -> FlightRecorder:
    return _GLOBAL


def record(kind: str, **fields: Any) -> None:
    """Record into the process-global ring (the one the dump hooks and
    the chaos soak collect)."""
    _GLOBAL.record(kind, **fields)


def dump_to(directory: str, tag: str | None = None,
            reason: str = "dump") -> str | None:
    """Dump the global ring to ``<dir>/flight-<tag or pid>.json``."""
    name = f"flight-{tag or os.getpid()}.json"
    return _GLOBAL.dump(os.path.join(directory, name), reason=reason)


_hooks_installed = False
_hook_lock = threading.Lock()


def install_dump_handlers(directory: str, tag: str | None = None) -> None:
    """Crash + SIGUSR2 dump wiring (idempotent per process).

    - unhandled exception: dump ``flight-<tag>.json`` with the crash
      type recorded, then delegate to the previous excepthook;
    - SIGUSR2 (main thread only — signal API restriction): dump on
      demand without dying.
    """
    global _hooks_installed
    with _hook_lock:
        if _hooks_installed:
            return
        _hooks_installed = True
    prev_hook = sys.excepthook

    def _crash_hook(exc_type, exc, tb):
        record("crash", error=f"{exc_type.__name__}: {exc}")
        dump_to(directory, tag=tag, reason="crash")
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _crash_hook

    import signal as _signal

    def _usr2(signum, frame):  # noqa: ARG001 — signal signature
        path = dump_to(directory, tag=tag, reason="sigusr2")
        log.info("flight recorder dumped to %s", path)

    try:
        _signal.signal(_signal.SIGUSR2, _usr2)
    except (ValueError, AttributeError, OSError):
        # not the main thread / platform without SIGUSR2: crash-dump
        # wiring above still applies
        log.debug("SIGUSR2 dump handler not installed")
