"""Causal spans: Dapper-style trace propagation across the wire seams.

A *span* is a named, timed operation; spans carry ``(trace_id,
span_id)`` and parent onto whatever context is current on their thread
— or onto an explicit remote context extracted from a wire frame. Both
wire planes propagate context in-band: ``coord/wire.py`` attaches a
``"_tc"`` key to request frames, ``data/tensor_wire.py`` attaches it to
the JSON header's ``meta``. One resize therefore becomes ONE causally
linked tree across every process it touches:

    resize.request (scaler/demo)                     <- root
      resize.actuate (JobServer /resize)             <- HTTP header hop
        store.put (epoch publication)                <- coord wire hop
        resize.adopt (surviving trainer)             <- epoch-doc hop
          resize.first_fresh_util                    <- util publisher
        resize.restore_peers (grown pod)
          migrate.fetch x chunks                     <- tensor wire hop
            migrate.serve_fetch (donor process)

Enablement: ``EDL_TPU_TRACE`` — unset/0 = off (spans are a single
attribute read + ``if`` on the hot path), ``1`` = on with the default
sink directory ``./edl_trace``, any other value = on with that value as
the sink directory. Every process appends finished spans to its own
``spans-<pid>.jsonl`` in the sink dir (timestamps are wall-clock so
files from different processes merge); a bounded in-process ring keeps
the most recent spans readable without file I/O (tests, resize_bench's
phase column). ``python -m edl_tpu.obs trace <dir>`` merges the files
into per-trace trees and exports Chrome-trace/Perfetto JSON.

Pure stdlib, jax/numpy-free (layers.toml obs row).
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Any

from edl_tpu.utils import config

DEFAULT_DIR = "edl_trace"
RING_CAP = 4096

_tls = threading.local()
_lock = threading.Lock()
_ring: collections.deque = collections.deque(maxlen=RING_CAP)
_file = None          # guarded-by: _lock
_file_pid = None      # guarded-by: _lock (fork detection)
_cached: tuple[bool, str | None] | None = None


def _setting() -> tuple[bool, str | None]:
    """(enabled, sink_dir) — parsed once per process; tests reset via
    `reconfigure()`."""
    global _cached
    if _cached is None:
        raw = (config.env_str("EDL_TPU_TRACE") or "").strip()
        if not raw or raw.lower() in ("0", "false", "no", "off"):
            _cached = (False, None)
        elif raw.lower() in ("1", "true", "yes", "on"):
            _cached = (True, DEFAULT_DIR)
        else:
            _cached = (True, raw)
    return _cached


def reconfigure() -> None:
    """Re-read EDL_TPU_TRACE and drop the sink file handle + ring
    (tests flip the env mid-process; real processes never need this)."""
    global _cached, _file, _file_pid
    with _lock:
        _cached = None
        if _file is not None:
            try:
                _file.close()
            except OSError:
                pass
        _file = None
        _file_pid = None
        _ring.clear()


def enabled() -> bool:
    return _setting()[0]


def sink_dir() -> str | None:
    return _setting()[1]


def _new_id() -> str:
    return os.urandom(8).hex()


def current() -> tuple[str, str] | None:
    """The active ``(trace_id, span_id)`` on this thread, or None."""
    return getattr(_tls, "ctx", None)


def _emit(record: dict) -> None:
    global _file, _file_pid
    _ring.append(record)
    directory = sink_dir()
    if directory is None:
        return
    line = json.dumps(record, separators=(",", ":"), default=str)
    with _lock:
        if _file is None or _file_pid != os.getpid():
            # per-process file: concurrent writers never interleave, and
            # a fork (mp loader workers) gets its own file not a shared fd
            try:
                os.makedirs(directory, exist_ok=True)
                _file = open(os.path.join(
                    directory, f"spans-{os.getpid()}.jsonl"), "a")
                _file_pid = os.getpid()
            except OSError:
                return
        try:
            _file.write(line + "\n")
            _file.flush()   # pods die by signal mid-demo: don't buffer
        except (OSError, ValueError):
            pass


class Span:
    """A started span; ``end()`` stamps the duration and emits it.
    Returned by :func:`start_span` for operations that end on another
    thread or at a later callback (the in-place adoption gap)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0",
                 "attrs", "_done")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: str | None, attrs: dict | None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0 = time.time()
        self.attrs = dict(attrs or {})
        self._done = False

    @property
    def context(self) -> tuple[str, str]:
        return (self.trace_id, self.span_id)

    def end(self, **attrs: Any) -> None:
        if self._done:
            return
        self._done = True
        self.attrs.update(attrs)
        _emit({"tid": self.trace_id, "sid": self.span_id,
               "parent": self.parent_id, "name": self.name,
               "pid": os.getpid(), "t0": round(self.t0, 6),
               "dur": round(time.time() - self.t0, 6),
               "attrs": self.attrs})


def start_span(name: str, parent: tuple[str, str] | None = None,
               attrs: dict | None = None) -> Span | None:
    """Begin a span (None when tracing is off). Does NOT alter the
    thread's current context — use :func:`span` for scoped work."""
    if not enabled():
        return None
    ctx = parent if parent is not None else current()
    if ctx is not None:
        trace_id, parent_id = ctx
    else:
        trace_id, parent_id = _new_id(), None
    return Span(name, trace_id, _new_id(), parent_id, attrs)


@contextlib.contextmanager
def span(name: str, parent: tuple[str, str] | None = None,
         attrs: dict | None = None):
    """Scoped span: children started inside the body (this thread, or
    remote via the wire seams) parent onto it. Yields the Span (None
    when tracing is off) so callers can add attrs."""
    if not enabled():
        yield None
        return
    s = start_span(name, parent=parent, attrs=attrs)
    prev = current()
    _tls.ctx = s.context
    try:
        yield s
    finally:
        _tls.ctx = prev
        s.end()


def instant(name: str, parent: tuple[str, str] | None = None,
            attrs: dict | None = None) -> None:
    """Zero-duration marker span (the 'first fresh util' tick)."""
    s = start_span(name, parent=parent, attrs=attrs)
    if s is not None:
        s.end()


def event(name: str, dur_s: float,
          parent: tuple[str, str] | None = None,
          attrs: dict | None = None) -> None:
    """Emit a pre-measured finished span (the utils/timeline shim's
    path: the operation already happened, only its duration is known).
    Parents onto the current/explicit context like any other span."""
    if not enabled():
        return
    ctx = parent if parent is not None else current()
    if ctx is not None:
        trace_id, parent_id = ctx
    else:
        trace_id, parent_id = _new_id(), None
    now = time.time()
    _emit({"tid": trace_id, "sid": _new_id(), "parent": parent_id,
           "name": name, "pid": os.getpid(),
           "t0": round(now - dur_s, 6), "dur": round(dur_s, 6),
           "attrs": dict(attrs or {})})


@contextlib.contextmanager
def adopt(ctx):
    """Make a remote context current for the body (no span of its own):
    spans opened inside parent onto the remote span. ``ctx`` may be
    None or malformed (straight off a wire frame) — then it's a no-op."""
    ctx = parse_context(ctx)
    if ctx is None or not enabled():
        yield
        return
    prev = current()
    _tls.ctx = ctx
    try:
        yield
    finally:
        _tls.ctx = prev


def parse_context(raw) -> tuple[str, str] | None:
    """Validate a wire-shaped context (list/tuple of two id strings) —
    garbled frames yield None, never an exception."""
    if (isinstance(raw, (list, tuple)) and len(raw) == 2
            and all(isinstance(x, str) and 0 < len(x) <= 64 for x in raw)):
        return (raw[0], raw[1])
    return None


def inject() -> list[str] | None:
    """The current context in wire shape (``["tid", "sid"]``), or None
    when tracing is off / no span is active."""
    ctx = current() if enabled() else None
    return [ctx[0], ctx[1]] if ctx is not None else None


def attach(d: dict) -> dict:
    """Copy-on-write attach of the current context to a wire dict under
    the reserved ``"_tc"`` key (both wire planes call this on their
    send path). Returns ``d`` untouched when there is nothing to add."""
    ctx = inject()
    if ctx is None or "_tc" in d:
        return d
    out = dict(d)
    out["_tc"] = ctx
    return out


def extract(d: dict) -> tuple[str, str] | None:
    """Pop the propagated context off a received wire dict (request
    msg or tensor-frame meta); tolerant of absence and garbling."""
    if not isinstance(d, dict):
        return None
    return parse_context(d.pop("_tc", None))


def finished(prefix: str | None = None) -> list[dict]:
    """Snapshot of the in-process ring of finished spans (newest last),
    optionally filtered by name prefix."""
    spans = list(_ring)
    if prefix is not None:
        spans = [s for s in spans if s["name"].startswith(prefix)]
    return spans


def clear_ring() -> None:
    _ring.clear()


# -- merged-trace analysis (CLI `python -m edl_tpu.obs trace`, the
#    resize_bench phase column, and bench_obs all read through these) --

def load_spans(directory: str) -> list[dict]:
    """Every span from every ``spans-*.jsonl`` in ``directory``
    (garbled lines skipped — a killed pod can tear its last write)."""
    out: list[dict] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for fname in names:
        if not (fname.startswith("spans-") and fname.endswith(".jsonl")):
            continue
        try:
            with open(os.path.join(directory, fname)) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and "tid" in rec:
                        out.append(rec)
        except OSError:
            continue
    return out


def group_traces(spans: list[dict]) -> dict[str, list[dict]]:
    """trace_id -> spans sorted by start time."""
    traces: dict[str, list[dict]] = {}
    for s in spans:
        traces.setdefault(s["tid"], []).append(s)
    for tid in traces:
        traces[tid].sort(key=lambda s: s.get("t0", 0.0))
    return traces


def span_tree(spans: list[dict]) -> list[tuple[dict, int]]:
    """Depth-first (span, depth) ordering of one trace's spans.
    Orphans (parent span lost — a killed process) surface at depth 0
    rather than disappearing."""
    by_id = {s["sid"]: s for s in spans}
    children: dict[str | None, list[dict]] = {}
    for s in spans:
        parent = s.get("parent")
        if parent is not None and parent not in by_id:
            parent = None
        children.setdefault(parent, []).append(s)
    for v in children.values():
        v.sort(key=lambda s: s.get("t0", 0.0))
    out: list[tuple[dict, int]] = []

    def walk(parent_id, depth):
        for s in children.get(parent_id, []):
            out.append((s, depth))
            walk(s["sid"], depth + 1)

    walk(None, 0)
    return out


# The resize phase vocabulary: span-name prefixes -> the budget phase
# they account to (doc/design_obs.md has the full catalog).
RESIZE_PHASES = (
    ("decision", ("scaler.decide", "resize.request")),
    ("actuation", ("resize.actuate",)),
    ("restore", ("resize.adopt", "resize.restore_peers")),
    ("first_fresh_util", ("resize.first_fresh_util",)),
)


def resize_phase_summary(spans: list[dict]) -> list[dict]:
    """Per-resize-trace phase breakdown: every trace containing a
    resize-family span becomes ``{trace_id, spans, t0, phases: {phase:
    seconds}, downtime_s}`` where downtime_s is the restore-phase span
    time (the measured survivor gap / peer-restore wall time)."""
    out = []
    for tid, tspans in sorted(group_traces(spans).items()):
        names = {s["name"] for s in tspans}
        if not any(n.startswith(("resize.", "scaler.decide"))
                   for n in names):
            continue
        phases: dict[str, float] = {}
        for phase, prefixes in RESIZE_PHASES:
            total = sum(s.get("dur", 0.0) for s in tspans
                        if s["name"].startswith(prefixes))
            if total or any(s["name"].startswith(prefixes)
                            for s in tspans):
                phases[phase] = round(total, 6)
        restore = [s for s in tspans
                   if s["name"].startswith(("resize.adopt",
                                            "resize.restore_peers"))]
        out.append({
            "trace_id": tid,
            "spans": len(tspans),
            "t0": min(s.get("t0", 0.0) for s in tspans),
            "phases": phases,
            "downtime_s": round(max((s.get("dur", 0.0) for s in restore),
                                    default=0.0), 6)})
    return out


def to_chrome(spans: list[dict]) -> dict:
    """Chrome-trace ("Trace Event Format") JSON — loadable in
    chrome://tracing and Perfetto. Complete ("X") events; each trace id
    gets a synthetic thread lane so concurrent resizes don't stack."""
    events = []
    lanes: dict[str, int] = {}
    for s in sorted(spans, key=lambda s: s.get("t0", 0.0)):
        lane = lanes.setdefault(s["tid"], len(lanes) + 1)
        events.append({
            "name": s["name"], "ph": "X", "cat": "edl",
            "ts": round(s.get("t0", 0.0) * 1e6, 1),
            "dur": max(round(s.get("dur", 0.0) * 1e6, 1), 1.0),
            "pid": s.get("pid", 0), "tid": lane,
            "args": dict(s.get("attrs") or {},
                         trace_id=s["tid"], span_id=s["sid"])})
    return {"traceEvents": events, "displayTimeUnit": "ms"}
