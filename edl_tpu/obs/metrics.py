"""Typed metrics primitives + the per-process registry.

One scrape surface for the ~10 subsystems that each grew a bespoke
``stats()`` dict: Counter/Gauge/Histogram primitives with FIXED log
buckets (generalizing the teacher Batcher's ``LATENCY_BUCKETS_MS``
pattern — fixed, not a reservoir, so two cumulative snapshots
difference EXACTLY into a windowed histogram and quantiles never drift
under load), a process-wide :class:`Registry`, a Prometheus-text scrape
endpoint (``EDL_TPU_METRICS_PORT``), and a JSON snapshot that can be
published into the coordination store so the Collector/scaler read the
same numbers a human scrapes.

Existing ``stats()`` dicts stay the subsystem API — they register as
*sources* (``registry().register_stats("teacher", server.stats)``) and
the registry renders their numeric fields as gauges at collect time.
Collection NEVER runs a source callback while holding the registry
lock (sources take their own subsystem locks; holding ours across the
call would manufacture lock-order edges the lockgraph plane exists to
kill).

Pure stdlib — jax/numpy-free, asserted by ``python -m edl_tpu.obs
selftest`` and the obs row in analysis/layers.toml.
"""

from __future__ import annotations

import bisect
import itertools
import json
import math
import re
import threading
import time
from typing import Any, Callable, Iterable

from edl_tpu.utils import config
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.obs.metrics")

# The canonical fixed log-bucket ladder (ms): the teacher server's
# LATENCY_BUCKETS_MS generalized — a 1/2.5/5-per-decade series wide
# enough for sub-ms wire ops and multi-second restores alike.
LOG_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                  500.0, 1000.0, 2500.0, 5000.0, 10000.0)

_INF = float("inf")


def log_buckets(lo: float, hi: float, per_decade: int = 3
                ) -> tuple[float, ...]:
    """A 1-2.5-5 log ladder covering [lo, hi] — fixed edges by
    construction, so snapshots taken at different times difference
    exactly bucket-by-bucket."""
    if lo <= 0 or hi <= lo:
        raise ValueError(f"need 0 < lo < hi, got {lo}, {hi}")
    steps = (1.0, 2.5, 5.0)[:max(1, min(per_decade, 3))]
    out = []
    decade = 10.0 ** math.floor(math.log10(lo))
    while decade <= hi:
        for s in steps:
            edge = decade * s
            if lo <= edge <= hi * (1 + 1e-9):
                out.append(edge)
        decade *= 10.0
    return tuple(out) or (hi,)


class Counter:
    """Monotonic cumulative count. Thread-safe; the lock is a leaf
    (no callback ever runs under it)."""

    __slots__ = ("name", "help", "_lock", "_v")
    kind = "counter"

    def __init__(self, name: str = "", help: str = ""):  # noqa: A002
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._v = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class Gauge:
    """Point-in-time value. Thread-safe leaf lock."""

    __slots__ = ("name", "help", "_lock", "_v")
    kind = "gauge"

    def __init__(self, name: str = "", help: str = ""):  # noqa: A002
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._v = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._v -= n

    @property
    def value(self) -> float:
        with self._lock:
            return self._v


class Histogram:
    """Fixed-bucket cumulative histogram.

    ``edges`` are upper bounds; observations above the last edge land in
    the open-ended ``inf`` bucket. Because the edges never move, the
    windowed view over any interval is the exact per-bucket difference
    of two cumulative snapshots (:meth:`window`) — the property the
    teacher registrar's windowed p50/p95 differencing relies on, now a
    shared primitive instead of a pattern copied between modules.

    Snapshots use the same sparse ``{upper_edge: count}`` dict shape the
    Batcher already ships over the wire (keys may arrive as strings off
    JSON; :meth:`quantile` accepts both).
    """

    __slots__ = ("name", "help", "edges", "_lock", "_counts", "_sum", "_n")
    kind = "histogram"

    def __init__(self, edges: Iterable[float] = LOG_BUCKETS_MS,
                 name: str = "", help: str = ""):  # noqa: A002
        self.name = name
        self.help = help
        self.edges = tuple(sorted(float(e) for e in edges))
        if not self.edges:
            raise ValueError("histogram needs at least one bucket edge")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.edges) + 1)   # +1 = inf bucket
        self._sum = 0.0
        self._n = 0

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.edges, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._n += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> dict[float, int]:
        """Sparse cumulative ``{upper_edge: count}`` (inf = overflow) —
        the exact shape the teacher's ``latency_hist_ms`` always had."""
        with self._lock:
            counts = list(self._counts)
        out: dict[float, int] = {}
        for edge, c in zip(self.edges, counts):
            if c:
                out[edge] = c
        if counts[-1]:
            out[_INF] = counts[-1]
        return out

    @staticmethod
    def window(cur: dict, prev: dict) -> dict[float, int]:
        """Exact windowed histogram: per-bucket difference of two
        cumulative snapshots (fixed edges line up by construction).
        Accepts string keys straight off the wire."""
        prev_n = {float(k): int(v) for k, v in (prev or {}).items()}
        out: dict[float, int] = {}
        for k, v in (cur or {}).items():
            d = int(v) - prev_n.get(float(k), 0)
            if d > 0:
                out[float(k)] = d
        return out

    @staticmethod
    def quantile(hist: dict, q: float) -> float | None:
        """q-quantile of a sparse ``{upper_edge: count}`` snapshot
        (keys may be strings off the wire). Answers the bucket's UPPER
        edge — conservative: a p95 read from this never under-reports,
        so an SLO decision made on it never under-provisions. None when
        empty."""
        items = sorted(((float(k), int(v)) for k, v in hist.items()),
                       key=lambda kv: kv[0])
        total = sum(c for _, c in items)
        if total <= 0:
            return None
        target = q * total
        cum = 0
        for edge, count in items:
            cum += count
            if cum >= target:
                return edge
        return items[-1][0]


Metric = Counter | Gauge | Histogram

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(raw: str) -> str:
    name = _SANITIZE.sub("_", str(raw))
    if not name or not _NAME_OK.match(name):
        name = "_" + name
    return name


def _escape_label(v: Any) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == _INF:
        return "+Inf"
    if v == -_INF:
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class Registry:
    """Per-process metric registry + stats-dict source aggregator.

    ``counter``/``gauge``/``histogram`` are get-or-create by name (a
    kind clash raises — two subsystems silently sharing one name under
    different types is exactly the drift this plane exists to stop).
    ``register_stats`` adopts an existing ``stats() -> dict`` surface
    as a collect-time gauge source; the dict API stays the subsystem's
    contract and the registry is the view over it.
    """

    def __init__(self, namespace: str = "edl"):
        self.namespace = namespace
        self._lock = threading.Lock()
        self._metrics: dict[str, Metric] = {}     # guarded-by: _lock
        self._sources: dict[int, tuple[str, Callable[[], dict | None]]] = {}
        self._ids = itertools.count(1)
        self._scrapes = 0                          # guarded-by: _lock

    # -- typed metrics -----------------------------------------------------

    def _get_or_make(self, name: str, factory, kind: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif m.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"not {kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:  # noqa: A002
        return self._get_or_make(
            name, lambda: Counter(name, help), "counter")

    def gauge(self, name: str, help: str = "") -> Gauge:  # noqa: A002
        return self._get_or_make(name, lambda: Gauge(name, help), "gauge")

    def histogram(self, name: str, edges: Iterable[float] = LOG_BUCKETS_MS,
                  help: str = "") -> Histogram:  # noqa: A002
        return self._get_or_make(
            name, lambda: Histogram(edges, name, help), "histogram")

    # -- stats-dict sources ------------------------------------------------

    def register_stats(self, kind: str,
                       fn: Callable[[], dict | None]) -> int:
        """Adopt a ``stats() -> dict`` surface; returns an unregister
        handle. The callable runs at collect time, NEVER under the
        registry lock."""
        handle = next(self._ids)
        with self._lock:
            self._sources[handle] = (kind, fn)
        return handle

    def unregister(self, handle: int) -> None:
        with self._lock:
            self._sources.pop(handle, None)

    # -- collection --------------------------------------------------------

    def _collect_sources(self) -> list[tuple[str, int, dict]]:
        """(kind, instance-id, stats dict) per live source. Callbacks
        run WITHOUT the registry lock; a throwing/closed source is
        skipped, never fatal to a scrape."""
        with self._lock:
            sources = list(self._sources.items())
        out = []
        seen: dict[str, int] = {}
        for _, (kind, fn) in sorted(sources):
            iid = seen.get(kind, 0)
            seen[kind] = iid + 1
            try:
                stats = fn()
            except Exception as exc:  # noqa: BLE001 — a dying subsystem
                # must not take the scrape surface down with it
                log.debug("stats source %s failed: %s", kind, exc)
                continue
            if isinstance(stats, dict):
                out.append((kind, iid, stats))
        return out

    def snapshot(self) -> dict:
        """JSON-safe full snapshot: typed metrics + every source's
        stats dict — what gets published into the coordination store
        so the Collector/scaler read the numbers a human scrapes."""
        with self._lock:
            metrics = list(self._metrics.items())
            scrapes = self._scrapes
        out: dict[str, Any] = {"ts": time.time(), "scrapes": scrapes,
                               "metrics": {}, "sources": {}}
        for name, m in metrics:
            if isinstance(m, Histogram):
                out["metrics"][name] = {
                    "kind": m.kind, "sum": m.sum, "count": m.count,
                    "hist": {str(k): v for k, v in m.snapshot().items()}}
            else:
                out["metrics"][name] = {"kind": m.kind, "value": m.value}
        for kind, iid, stats in self._collect_sources():
            out["sources"][f"{kind}/{iid}"] = stats
        return out

    def publish(self, store, key: str, lease: int = 0) -> None:
        """Best-effort snapshot into the coordination store (the
        Collector/scaler-visible copy of the scrape surface)."""
        try:
            store.put(key, json.dumps(self.snapshot(), sort_keys=True,
                                      default=str), lease=lease)
        except Exception as exc:  # noqa: BLE001 — observability must
            # never take a subsystem down
            log.debug("metrics snapshot publish failed: %s", exc)

    def render(self) -> str:
        """Prometheus text exposition format (v0.0.4)."""
        with self._lock:
            metrics = sorted(self._metrics.items())
            self._scrapes += 1
        ns = self.namespace
        lines: list[str] = []
        for name, m in metrics:
            full = _metric_name(f"{ns}_{name}")
            if m.help:
                lines.append(f"# HELP {full} {m.help}")
            lines.append(f"# TYPE {full} {m.kind}")
            if isinstance(m, Histogram):
                snap = m.snapshot()
                cum = 0
                for edge in m.edges:
                    cum += snap.get(edge, 0)
                    lines.append(f'{full}_bucket{{le="{_fmt_value(edge)}"}}'
                                 f' {cum}')
                cum += snap.get(_INF, 0)
                lines.append(f'{full}_bucket{{le="+Inf"}} {cum}')
                lines.append(f"{full}_sum {_fmt_value(m.sum)}")
                lines.append(f"{full}_count {m.count}")
            else:
                lines.append(f"{full} {_fmt_value(m.value)}")
        for kind, iid, stats in self._collect_sources():
            base = _metric_name(f"{ns}_{kind}")
            for key in sorted(stats):
                value = stats[key]
                mname = _metric_name(f"{base}_{key}")
                if isinstance(value, bool):
                    value = int(value)
                if isinstance(value, (int, float)):
                    lines.append(f"# TYPE {mname} gauge")
                    lines.append(f'{mname}{{iid="{iid}"}} '
                                 f'{_fmt_value(float(value))}')
                elif isinstance(value, dict):
                    # sub-histogram shape ({bucket: count}) -> labeled.
                    # Keys ending "_by_<label>" (e.g. queue_depth_by_class,
                    # rejected_by_tenant) name their OWN label dimension
                    # instead of the generic "bucket", so per-tenant /
                    # per-class serving gauges come out as
                    # edl_teacher_..._by_class{class="high"} — directly
                    # aggregable in PromQL.
                    samples = [(k, v) for k, v in value.items()
                               if isinstance(v, (int, float))
                               and not isinstance(v, bool)]
                    if not samples:
                        continue
                    label = "bucket"
                    _, sep, suffix = key.rpartition("_by_")
                    if sep and suffix.isidentifier():
                        label = suffix
                    lines.append(f"# TYPE {mname} gauge")
                    for k, v in sorted(samples, key=lambda kv: str(kv[0])):
                        lines.append(
                            f'{mname}{{iid="{iid}",'
                            f'{label}="{_escape_label(k)}"}} '
                            f'{_fmt_value(float(v))}')
        return "\n".join(lines) + "\n"


_REGISTRY = Registry()
_serve_once = threading.Lock()
_serve_checked = False
_http = None


def registry() -> Registry:
    """The per-process registry. First use starts the scrape endpoint
    when ``EDL_TPU_METRICS_PORT`` is set (idempotent, best-effort)."""
    global _serve_checked
    if not _serve_checked:
        with _serve_once:
            if not _serve_checked:
                _serve_checked = True
                port = config.env_int("EDL_TPU_METRICS_PORT", 0)
                if port > 0:
                    serve(port)
    return _REGISTRY


def register_stats(kind: str, fn: Callable[[], dict | None]) -> int:
    return registry().register_stats(kind, fn)


def unregister(handle: int) -> None:
    _REGISTRY.unregister(handle)


class MetricsServer:
    """Threaded HTTP scrape endpoint: GET /metrics -> Prometheus text,
    GET /snapshot -> the JSON snapshot. One daemon thread + listening
    socket per process, torn down by close()."""

    def __init__(self, reg: Registry, port: int, host: str = "127.0.0.1"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        registry_ref = reg

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                path = self.path.split("?", 1)[0].rstrip("/") or "/metrics"
                if path == "/metrics":
                    body = registry_ref.render().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/snapshot":
                    body = json.dumps(registry_ref.snapshot(),
                                      default=str).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # route into our logger
                log.debug("metrics http: " + fmt, *args)

        self._srv = ThreadingHTTPServer((host, port), Handler)
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True, name="edl-metrics-http")
        self._thread.start()

    def close(self) -> None:
        self._srv.shutdown()
        self._thread.join(timeout=2.0)
        self._srv.server_close()


def serve(port: int, host: str = "127.0.0.1") -> MetricsServer | None:
    """Start (or return) the process's scrape endpoint. Best-effort: a
    busy port logs and returns None rather than failing the subsystem
    that happened to touch the registry first."""
    global _http
    if _http is not None:
        return _http
    try:
        # lifecycle: long-lived(process-wide scrape endpoint; stop_serving is the teardown)
        _http = MetricsServer(_REGISTRY, port, host)
        log.info("metrics scrape endpoint on %s:%d", host, _http.port)
    except OSError as exc:
        log.warning("metrics endpoint not started on port %d: %s",
                    port, exc)
        _http = None
    return _http


def stop_serving() -> None:
    global _http, _serve_checked
    if _http is not None:
        _http.close()
        _http = None
    _serve_checked = False
