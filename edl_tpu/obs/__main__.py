"""CLI for the observability plane.

    python -m edl_tpu.obs trace <dir> [--chrome out.json] [--json]
        Merge every process's span file into per-trace trees; print
        them (or a machine-readable phase summary with --json) and
        optionally export Chrome-trace/Perfetto JSON.

    python -m edl_tpu.obs selftest
        Sequential CI gate: exercises all three legs end-to-end and
        ASSERTS the plane imported without jax/numpy — the same
        stdlib-only contract the coord/scaler/chaos selftests pin.
"""

from __future__ import annotations

import argparse
import json
import sys


def _fmt_dur(s: float) -> str:
    return f"{s * 1e3:.1f}ms" if s < 1.0 else f"{s:.3f}s"


def run_trace(args) -> int:
    from edl_tpu.obs import trace

    spans = trace.load_spans(args.dir)
    if not spans:
        print(f"no spans under {args.dir}", file=sys.stderr)
        return 1
    traces = trace.group_traces(spans)
    summary = trace.resize_phase_summary(spans)
    if args.chrome:
        with open(args.chrome, "w") as fh:
            json.dump(trace.to_chrome(spans), fh)
        print(f"chrome trace -> {args.chrome} ({len(spans)} spans, "
              f"{len(traces)} traces)", file=sys.stderr)
    if args.json:
        print(json.dumps({"traces": len(traces), "spans": len(spans),
                          "resizes": summary}, sort_keys=True))
        return 0
    for tid, tspans in sorted(traces.items(),
                              key=lambda kv: kv[1][0].get("t0", 0.0)):
        t0 = min(s.get("t0", 0.0) for s in tspans)
        total = max(s.get("t0", 0.0) + s.get("dur", 0.0)
                    for s in tspans) - t0
        print(f"trace {tid}  spans={len(tspans)} "
              f"span={_fmt_dur(total)}")
        for s, depth in trace.span_tree(tspans):
            offset = s.get("t0", 0.0) - t0
            attrs = s.get("attrs") or {}
            extra = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
            print(f"  {'  ' * depth}{s['name']} "
                  f"+{_fmt_dur(offset)} {_fmt_dur(s.get('dur', 0.0))} "
                  f"[pid {s.get('pid')}]" + (f" {extra}" if extra else ""))
    if summary:
        print("resize phase breakdown:")
        for r in summary:
            phases = " ".join(f"{k}={_fmt_dur(v)}"
                              for k, v in r["phases"].items())
            print(f"  {r['trace_id']}: downtime={_fmt_dur(r['downtime_s'])}"
                  f" {phases}")
    return 0


def selftest(verbose: bool = True) -> int:
    import math
    import os
    import tempfile
    import urllib.request

    # the stdlib-only contract: the obs plane must not pull the
    # accelerator stack in. From the CLI nothing is preloaded so this
    # is absolute; in-process callers (pytest) may already carry
    # jax/numpy, so the check is "we didn't ADD them".
    pre_jax = "jax" in sys.modules
    pre_np = "numpy" in sys.modules
    assert pre_jax or "jax" not in sys.modules
    assert pre_np or "numpy" not in sys.modules

    from edl_tpu.obs import metrics, recorder, trace

    def check(name: str, ok: bool) -> bool:
        if verbose:
            print(f"  {'ok' if ok else 'FAIL'}  {name}")
        return ok

    ok = True

    # -- metrics leg -------------------------------------------------------
    reg = metrics.Registry()
    c = reg.counter("selftest_ops", "ops")
    c.inc()
    c.inc(2)
    g = reg.gauge("selftest_depth")
    g.set(7)
    h = reg.histogram("selftest_latency_ms", metrics.LOG_BUCKETS_MS)
    for v in (0.5, 3.0, 3.0, 40.0, 99999.0):
        h.observe(v)
    snap1 = h.snapshot()
    h.observe(3.0)
    win = metrics.Histogram.window(h.snapshot(), snap1)
    ok &= check("histogram windows difference exactly",
                win == {5.0: 1})
    ok &= check("conservative quantile answers the upper edge",
                metrics.Histogram.quantile(snap1, 0.5) == 5.0
                and metrics.Histogram.quantile({}, 0.5) is None)
    reg.register_stats("selftest_src", lambda: {"queue_depth": 3,
                                                "hist": {"8": 2}})
    text = reg.render()
    ok &= check("prometheus text: counter/gauge lines",
                "edl_selftest_ops 3" in text
                and "edl_selftest_depth 7" in text)
    ok &= check("prometheus text: cumulative buckets + +Inf",
                'edl_selftest_latency_ms_bucket{le="+Inf"} 6' in text
                and "edl_selftest_latency_ms_count 6" in text)
    ok &= check("stats dict rendered as gauges",
                'edl_selftest_src_queue_depth{iid="0"} 3' in text
                and 'bucket="8"' in text)
    srv = metrics.MetricsServer(reg, port=0)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=5).read()
        ok &= check("scrape endpoint serves the same text",
                    b"edl_selftest_ops 3" in body)
        snap = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/snapshot", timeout=5).read())
        ok &= check("snapshot endpoint carries sources",
                    snap["sources"]["selftest_src/0"]["queue_depth"] == 3)
    finally:
        srv.close()

    # -- trace leg ---------------------------------------------------------
    with tempfile.TemporaryDirectory(prefix="edl-obs-selftest-") as tmp:
        os.environ["EDL_TPU_TRACE"] = tmp
        trace.reconfigure()
        try:
            with trace.span("resize.request", attrs={"desired": 2}) as root:
                msg = trace.attach({"op": "put"})
                ctx = trace.extract(dict(msg))
                with trace.adopt(ctx):
                    with trace.span("resize.actuate"):
                        pass
                root.attrs["note"] = "selftest"
            spans = trace.load_spans(tmp)
            ok &= check("wire attach/extract keeps one trace",
                        len({s["tid"] for s in spans}) == 1
                        and len(spans) == 2)
            tree = trace.span_tree(spans)
            ok &= check("child parents onto the propagated span",
                        [(s["name"], d) for s, d in tree]
                        == [("resize.request", 0), ("resize.actuate", 1)])
            ok &= check("garbled context degrades to None",
                        trace.parse_context(["x"]) is None
                        and trace.parse_context("junk") is None
                        and trace.parse_context([1, 2]) is None)
            chrome = trace.to_chrome(spans)
            ok &= check("chrome export shape",
                        len(chrome["traceEvents"]) == 2
                        and all(e["ph"] == "X"
                                for e in chrome["traceEvents"]))
            summary = trace.resize_phase_summary(spans)
            ok &= check("resize phase summary sees the trace",
                        len(summary) == 1
                        and "actuation" in summary[0]["phases"])
        finally:
            os.environ.pop("EDL_TPU_TRACE", None)
            trace.reconfigure()

    # -- recorder leg ------------------------------------------------------
    rec = recorder.FlightRecorder(capacity=8)
    for i in range(20):
        rec.record("resize", to=i)
    ok &= check("ring bounded with dropped accounting",
                len(rec.events()) == 8 and rec.dropped == 12
                and rec.events("resize")[-1]["to"] == 19)
    with tempfile.TemporaryDirectory(prefix="edl-obs-selftest-") as tmp:
        path = rec.dump(os.path.join(tmp, "flight.json"))
        doc = json.load(open(path))
        ok &= check("dump round-trips the ring",
                    len(doc["events"]) == 8 and doc["dropped"] == 12)
    off = recorder.FlightRecorder(capacity=0)
    off.record("resize", to=1)
    ok &= check("capacity 0 disables recording", off.events() == [])

    ok &= check("no accelerator import crept in",
                ("jax" in sys.modules) == pre_jax
                and ("numpy" in sys.modules) == pre_np
                and math.isfinite(1.0))
    print("obs selftest:", "OK" if ok else "FAILED")
    return 0 if ok else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m edl_tpu.obs")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_trace = sub.add_parser("trace", help="merge + view span files")
    p_trace.add_argument("dir", nargs="?", default="edl_trace",
                         help="span sink directory (EDL_TPU_TRACE)")
    p_trace.add_argument("--chrome", help="write Chrome-trace JSON here")
    p_trace.add_argument("--json", action="store_true",
                         help="machine-readable phase summary")
    sub.add_parser("selftest", help="stdlib-only CI gate")
    args = parser.parse_args(argv)
    if args.cmd == "trace":
        return run_trace(args)
    return selftest()


if __name__ == "__main__":
    raise SystemExit(main())
