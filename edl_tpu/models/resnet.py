"""ResNet family (ResNet50/101/152 and the *_vd variants) in flax.

Capability of the reference model zoo
(`example/collective/resnet50/models/resnet.py` and
`example/distill/resnet/models/resnet_vd.py`): bottleneck ResNets for
ImageNet, plus the "vd" tweaks — deep 3x3x3 stem, stride moved to the 3x3
conv, and avg-pool-then-1x1 downsample shortcuts.

TPU-first design, not a translation of the Paddle static-graph builders:
NHWC layout (XLA's native conv layout on TPU), bf16 activations with fp32
parameters and batch-norm statistics, and a `flax.linen` module tree so
parameters are a pytree shardable by `edl_tpu.parallel` rules. All shapes
are static; the whole forward lowers to MXU convolutions.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut.

    `vd`: stride lives on the 3x3 conv and the downsampling shortcut is
    avg_pool + stride-1 1x1 conv (reference resnet_vd.py shortcut branch).
    """

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    vd: bool = False

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides,) * 2)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale: identity-ish residual at init
        # (standard ResNet recipe; keeps early training stable at large
        # global batch, the elastic-DP regime).
        y = self.norm(scale_init=nn.initializers.zeros)(y)

        if residual.shape != y.shape:
            if self.vd and self.strides > 1:
                residual = nn.avg_pool(
                    residual, (2, 2), strides=(2, 2), padding="SAME")
                residual = self.conv(
                    self.filters * 4, (1, 1), name="conv_shortcut")(residual)
            else:
                residual = self.conv(
                    self.filters * 4, (1, 1),
                    strides=(self.strides,) * 2, name="conv_shortcut")(residual)
            residual = self.norm(name="norm_shortcut")(residual)

        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Bottleneck ResNet for ImageNet classification.

    Attributes:
      stage_sizes: blocks per stage, e.g. (3, 4, 6, 3) for ResNet50.
      num_classes: classifier width.
      vd: enable the ResNet-vd tweaks (deep stem + avgpool shortcuts).
      dtype: activation dtype (bf16 on TPU; params/BN stats stay fp32).
    """

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    vd: bool = False
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       kernel_init=nn.initializers.variance_scaling(
                           2.0, "fan_out", "normal"))
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)

        x = x.astype(self.dtype)
        if self.vd:
            # Deep stem: three 3x3 convs (32, 32, 64) instead of one 7x7.
            for i, width in enumerate((32, 32, 64)):
                x = conv(width, (3, 3),
                         strides=(2, 2) if i == 0 else (1, 1),
                         name=f"stem_conv{i}")(x)
                x = norm(name=f"stem_norm{i}")(x)
                x = nn.relu(x)
        else:
            x = conv(64, (7, 7), strides=(2, 2), name="stem_conv")(x)
            x = norm(name="stem_norm")(x)
            x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                x = BottleneckBlock(
                    filters=self.num_filters * 2 ** stage,
                    strides=2 if stage > 0 and block == 0 else 1,
                    conv=conv, norm=norm, vd=self.vd,
                )(x)

        x = jnp.mean(x, axis=(1, 2))  # global average pool
        # Classifier in fp32: the logits feed softmax-CE, where bf16
        # rounding hurts; this matmul is negligible FLOPs.
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     kernel_init=nn.initializers.variance_scaling(
                         1.0, "fan_in", "uniform"))(x)
        return x


ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3))
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3))
ResNet152 = partial(ResNet, stage_sizes=(3, 8, 36, 3))
ResNet50_vd = partial(ResNet, stage_sizes=(3, 4, 6, 3), vd=True)
ResNet101_vd = partial(ResNet, stage_sizes=(3, 4, 23, 3), vd=True)
ResNet152_vd = partial(ResNet, stage_sizes=(3, 8, 36, 3), vd=True)

# Tiny config for tests/dryruns: 1 block/stage, 8 base filters.
ResNetTiny = partial(ResNet, stage_sizes=(1, 1, 1, 1), num_filters=8)
