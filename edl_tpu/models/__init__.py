from edl_tpu.models.linear import LinearRegression

__all__ = ["LinearRegression"]

_LAZY = {
    "resnet": ("ResNet", "ResNet50", "ResNet101", "ResNet152",
               "ResNet50_vd", "ResNet101_vd", "ResNet152_vd", "ResNetTiny",
               "BottleneckBlock"),
    "vgg": ("VGG", "VGG11", "VGG13", "VGG16", "VGG19"),
    "transformer": ("Transformer", "TransformerConfig"),
    "mlp": ("MLP", "mlp"),
    "bow": ("BOWClassifier",),
    "deepfm": ("DeepFM",),
}


def __getattr__(name):
    # Heavier model families load lazily to keep import cost low.
    for module, names in _LAZY.items():
        if name in names:
            import importlib
            try:
                mod = importlib.import_module(f"edl_tpu.models.{module}")
            except ModuleNotFoundError as exc:
                raise AttributeError(name) from exc
            return getattr(mod, name)
    raise AttributeError(name)
