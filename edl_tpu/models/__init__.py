from edl_tpu.models.linear import LinearRegression

__all__ = ["LinearRegression"]

_LAZY = {
    "resnet": ("ResNet", "ResNet50", "ResNet101", "ResNet152",
               "ResNet50_vd", "ResNet101_vd", "ResNet152_vd", "ResNetTiny",
               "BottleneckBlock"),
    "vgg": ("VGG", "VGG11", "VGG13", "VGG16", "VGG19"),
    "transformer": ("Transformer", "TransformerConfig"),
    "mlp": ("MLP", "mlp"),
    "bow": ("BOWClassifier", "CNNClassifier"),
    "deepfm": ("DeepFM",),
}


def get_model(name):
    """Resolve a zoo factory by name, immune to submodule shadowing.

    `getattr(models, "mlp")` can return the *submodule* once
    `edl_tpu.models.mlp` has been imported anywhere (the import machinery
    binds the submodule attribute on the package, which wins over
    __getattr__) — so name-based consumers (teacher_server --model) must
    resolve through this helper.
    """
    import importlib
    for module, names in _LAZY.items():
        if name in names:
            mod = importlib.import_module(f"edl_tpu.models.{module}")
            return getattr(mod, name)
    if name in __all__:
        return globals()[name]
    raise AttributeError(f"unknown model {name!r}")


def __getattr__(name):
    # Heavier model families load lazily to keep import cost low.
    for module, names in _LAZY.items():
        if name in names:
            try:
                return get_model(name)
            except ModuleNotFoundError as exc:
                raise AttributeError(name) from exc
    raise AttributeError(name)
