from edl_tpu.models.linear import LinearRegression

__all__ = ["LinearRegression"]


def __getattr__(name):
    # Heavier model families load lazily to keep import cost low.
    if name in ("ResNet", "resnet50", "resnet50_vd", "resnet18", "resnet101"):
        from edl_tpu.models import resnet
        return getattr(resnet, name)
    if name in ("VGG", "vgg16"):
        from edl_tpu.models import vgg
        return getattr(vgg, name)
    raise AttributeError(name)
