"""Linear regression — the fit_a_line-equivalent smoke model.

Capability parity: reference example/fit_a_line/train_ft.py (uci-housing
linear regression used as the fault-tolerant smoke job; BASELINE config 1).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LinearRegression(nn.Module):
    features: int = 1

    @nn.compact
    def __call__(self, x):
        return nn.Dense(self.features)(x)


def mse_loss(pred, target):
    return jnp.mean((pred - target) ** 2)
