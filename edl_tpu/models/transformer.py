"""Decoder-only transformer LM — the long-context / multi-axis flagship.

Net-new relative to the reference (its models are CNNs + BOW/ERNIE-distill,
SURVEY.md §5): a causal LM whose parameters carry flax *logical axis names*
(`vocab/embed/heads/kv/mlp`) so `edl_tpu.parallel.sharding` rules shard them
over any `dp x fsdp x tp x sp` mesh, and whose attention switches to
`edl_tpu.parallel.ring_attention` when the mesh has a real `sp` axis —
sequence/context parallelism with k/v blocks rotating over ICI.

Everything is static-shaped and jit-traceable; remat is applied per block
(`jax.checkpoint`) to trade FLOPs for HBM when configured.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dfield
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh

from edl_tpu.parallel import ring_attention as ra
from edl_tpu.parallel import sharding as shd


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_len: int = 2048
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    remat: bool = False
    # attention kernel: "auto" = ring when the mesh has sp>1, else the
    # Pallas flash kernel on TPU (ops/flash_attention.py), else XLA
    # dense; "flash"/"dense" force a single-device kernel choice.
    attention: str = "auto"
    # mesh: when set (and it has sp>1) attention runs the ring kernel and
    # activations get logical sharding constraints. None = single-device.
    mesh: Mesh | None = dfield(default=None, hash=False, compare=False)
    rules: tuple = shd.DEFAULT_RULES
    # -- mixture of experts (dense fallback: moe=False leaves every
    # existing config byte-identical — blocks keep the plain MLP).
    # moe=True swaps each block's MLP for MoEMLP: a top-k
    # capacity-factor router over n_experts expert FFNs whose tables
    # carry the ("expert", ...) logical axis — sharded over ep by
    # sharding.DEFAULT_RULES, so they enter the checkpoint index as
    # ep-sharded leaves and re-shard on resize like any sharded state.
    moe: bool = False
    n_experts: int = 8
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # moe_wire: transport for expert dispatch/combine. None = dense
    # einsum dispatch (single device, or XLA-partitioned over an ep
    # mesh). Inside a manual shard_map region, train/comm injects its
    # hierarchical all-to-all wire here (an object with
    # dispatch/combine/local_slice — see comm.MoEWire).
    moe_wire: Any = dfield(default=None, hash=False, compare=False)

    def __post_init__(self):
        if self.moe:
            if self.n_experts < 2:
                raise ValueError(
                    f"moe needs n_experts >= 2, got {self.n_experts}")
            if not 1 <= self.moe_top_k <= self.n_experts:
                raise ValueError(
                    f"moe_top_k must be in [1, n_experts="
                    f"{self.n_experts}], got {self.moe_top_k}")
            if self.moe_capacity_factor <= 0:
                raise ValueError(
                    f"moe_capacity_factor must be > 0, got "
                    f"{self.moe_capacity_factor}")

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    def constrain(self, x, logical):
        return shd.constrain(x, logical, self.mesh, self.rules)

    @property
    def use_ring(self) -> bool:
        return (self.mesh is not None and "sp" in self.mesh.axis_names
                and self.mesh.shape["sp"] > 1)

    def use_flash(self, seq_len: int) -> bool:
        if self.attention not in ("auto", "flash", "dense"):
            raise ValueError(f"unknown attention={self.attention!r} "
                             "(auto|flash|dense)")
        if self.attention == "flash":
            return True
        if self.attention != "auto":
            return False
        # auto: the Pallas kernel needs a TPU backend (interpret mode is
        # for tests), a 128-divisible sequence, and a mesh without model
        # sharding on heads (tp shards heads; flash is per-head so it
        # composes, but XLA partitions the dense path equally well — keep
        # flash for the unsharded-attention case where it clearly wins).
        return (jax.default_backend() == "tpu" and seq_len % 128 == 0
                and (self.mesh is None
                     or all(self.mesh.shape.get(a, 1) == 1
                            for a in ("tp", "sp"))))

    def flash(self, q, k, v):
        """Flash attention, shard_mapped over the mesh's batch axes —
        a pallas_call is opaque to the XLA partitioner, so without this
        a dp-sharded input would be gathered to every device."""
        from edl_tpu.ops.flash_attention import flash_attention
        if self.mesh is None or all(s == 1 for s in
                                    self.mesh.shape.values()):
            return flash_attention(q, k, v, causal=True)
        from jax.sharding import PartitionSpec as P
        batch = tuple(a for a in ("dp", "fsdp")
                      if self.mesh.shape.get(a, 1) > 1) or None
        spec = P(batch)
        fn = partial(flash_attention, causal=True)
        from edl_tpu.parallel.compat import shard_map
        return shard_map(fn, mesh=self.mesh,
                         in_specs=(spec, spec, spec), out_specs=spec,
                         check_vma=False)(q, k, v)


def _dense(features, names, cfg, name=None):
    return nn.DenseGeneral(
        features, axis=-1, dtype=cfg.dtype, name=name, use_bias=False,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.variance_scaling(1.0, "fan_in", "normal"),
            names))


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, train: bool = True):
        cfg = self.cfg
        b, s, _ = x.shape
        proj = partial(nn.DenseGeneral, axis=-1, dtype=cfg.dtype,
                       use_bias=False)
        qkv_init = nn.with_logical_partitioning(
            nn.initializers.variance_scaling(1.0, "fan_in", "normal"),
            ("embed", "heads", "kv"))
        q = proj((cfg.n_heads, cfg.head_dim), kernel_init=qkv_init,
                 name="query")(x)
        k = proj((cfg.n_heads, cfg.head_dim), kernel_init=qkv_init,
                 name="key")(x)
        v = proj((cfg.n_heads, cfg.head_dim), kernel_init=qkv_init,
                 name="value")(x)
        q = cfg.constrain(q, ("batch", "seq", "heads", "kv"))
        k = cfg.constrain(k, ("batch", "seq", "heads", "kv"))
        v = cfg.constrain(v, ("batch", "seq", "heads", "kv"))

        if cfg.use_ring:
            o = ra.ring_attention(q, k, v, mesh=cfg.mesh, causal=True)
        elif cfg.use_flash(s):
            o = cfg.flash(q, k, v)
        else:
            o = ra.dense_attention(q, k, v, causal=True)
        o = cfg.constrain(o, ("batch", "seq", "heads", "kv"))

        out_init = nn.with_logical_partitioning(
            nn.initializers.variance_scaling(1.0, "fan_in", "normal"),
            ("heads", "kv", "embed"))
        o = nn.DenseGeneral(cfg.d_model, axis=(-2, -1), dtype=cfg.dtype,
                            use_bias=False, kernel_init=out_init,
                            name="out")(o)
        return cfg.constrain(o, ("batch", "seq", "embed"))


def moe_capacity(n_tokens: int, n_experts: int, top_k: int,
                 capacity_factor: float) -> int:
    """Per-expert token buffer size: ceil(cf * T * k / E), at least 1.

    Static (T is a trace-time constant), so every dispatch buffer —
    and therefore the all-to-all wire — has a fixed shape regardless
    of where the router actually sends tokens."""
    import math
    return max(1, math.ceil(capacity_factor * n_tokens * top_k
                            / n_experts))


def router_topk(logits: jax.Array, top_k: int, capacity: int
                ) -> tuple[jax.Array, jax.Array, dict]:
    """Top-k capacity-factor routing (Switch/GShard style), pure dense
    math so it jits on any backend and tests can hit the capacity
    edges without flax.

    logits: (T, E) router scores. Each token picks its top_k experts by
    softmax probability; within each expert, slots are granted in
    CHOICE-MAJOR order (every token's first choice is placed before any
    second choice), and assignments past ``capacity`` are dropped —
    the token's output falls through the residual connection, the
    standard capacity-factor contract.

    Returns ``(combine, dispatch, aux)``: combine (T, E, C) fp32 gate
    weights (renormalized over the kept top-k), dispatch (T, E, C)
    bool one-hot slot assignment, and aux = {load_balance (the Shazeer
    f·p loss, 1.0 at perfect balance), dropped_frac (fraction of the
    T*k assignments dropped by capacity — the accounting the tests
    pin)}.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate, idx = jax.lax.top_k(probs, top_k)                 # (T, k)
    gate = gate / jnp.maximum(jnp.sum(gate, -1, keepdims=True), 1e-9)
    oh = jax.nn.one_hot(idx, e, dtype=jnp.float32)          # (T, k, E)
    # position of each assignment inside its expert's buffer,
    # choice-major: flatten to (k*T, E) with choice as the slow dim
    flat = oh.transpose(1, 0, 2).reshape(top_k * t, e)
    pos_flat = jnp.cumsum(flat, axis=0) - flat
    pos = jnp.sum(pos_flat * flat, axis=-1).reshape(top_k, t).T
    pos = pos.astype(jnp.int32)                             # (T, k)
    kept = pos < capacity
    # one_hot of `capacity` (out of range) is the all-zero row, so a
    # dropped assignment vanishes from dispatch AND combine
    pos_oh = jax.nn.one_hot(jnp.where(kept, pos, capacity), capacity,
                            dtype=jnp.float32)              # (T, k, C)
    dispatch = jnp.einsum("tke,tkc->tec", oh, pos_oh) > 0
    combine = jnp.einsum("tk,tke,tkc->tec", gate, oh, pos_oh)
    f = jnp.mean(jnp.sum(oh, axis=1), axis=0) / top_k       # (E,)
    p = jnp.mean(probs, axis=0)
    aux = {"load_balance": e * jnp.sum(f * p),
           "dropped_frac": 1.0 - jnp.mean(kept.astype(jnp.float32))}
    return combine, dispatch, aux


class MoEMLP(nn.Module):
    """Expert-parallel MLP: top-k capacity-factor router + n_experts
    gelu FFNs whose (E, ...) tables carry the "expert" logical axis
    (sharded over ep by sharding.DEFAULT_RULES — the leaves the
    checkpoint index stores ep-sharded and re-shards on resize).

    Two transports, one set of router/expert math:
    - cfg.moe_wire=None (default): dense einsum dispatch. On a single
      device this is the whole layer; on an ep mesh XLA's partitioner
      turns the (E, cap, d) einsums into its own all-to-all.
    - cfg.moe_wire set (inside train/comm's manual shard_map region):
      the wire object transports the per-chip dispatch buffer to the
      experts' owner chips (hierarchical ICI/DCN all-to-all, optionally
      int8 on the DCN leg) and back; each chip computes only its
      local expert slice.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, s, d = x.shape
        e, k = cfg.n_experts, cfg.moe_top_k
        t = b * s
        cap = moe_capacity(t, e, k, cfg.moe_capacity_factor)
        router = self.param(
            "router",
            nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                         ("embed", "expert_router")),
            (cfg.d_model, e))
        table_init = nn.initializers.variance_scaling(
            1.0, "fan_in", "normal", in_axis=-2, out_axis=-1,
            batch_axis=(0,))
        w_in = self.param(
            "w_in", nn.with_logical_partitioning(
                table_init, ("expert", "embed", "mlp")),
            (e, cfg.d_model, cfg.d_ff))
        w_out = self.param(
            "w_out", nn.with_logical_partitioning(
                table_init, ("expert", "mlp", "embed")),
            (e, cfg.d_ff, cfg.d_model))

        xf = x.reshape(t, d)
        logits = jnp.einsum("td,de->te", xf.astype(jnp.float32),
                            router.astype(jnp.float32))
        combine, dispatch, aux = router_topk(logits, k, cap)
        self.sow("intermediates", "moe_aux", aux["load_balance"])
        self.sow("intermediates", "moe_dropped", aux["dropped_frac"])

        buf = jnp.einsum("tec,td->ecd", dispatch.astype(cfg.dtype), xf)
        wire = cfg.moe_wire
        if wire is None:
            h = jnp.einsum("ecd,edf->ecf", buf,
                           w_in.astype(cfg.dtype))
            h = nn.gelu(h)
            out = jnp.einsum("ecf,efd->ecd", h,
                             w_out.astype(cfg.dtype))
        else:
            recv = wire.dispatch(buf)           # (E/W, W*cap, d)
            h = jnp.einsum("ecd,edf->ecf", recv,
                           wire.local_slice(w_in).astype(cfg.dtype))
            h = nn.gelu(h)
            out = jnp.einsum("ecf,efd->ecd", h,
                             wire.local_slice(w_out).astype(cfg.dtype))
            out = wire.combine(out)             # back to (E, cap, d)
        y = jnp.einsum("tec,ecd->td", combine.astype(cfg.dtype), out)
        return y.reshape(b, s, d)


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, train: bool = True):
        cfg = self.cfg
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln_attn")(x)
        h = Attention(cfg, name="attn")(h, train)
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout, deterministic=not train)(h)
        x = x + h
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln_mlp")(x)
        if cfg.moe:
            h = MoEMLP(cfg, name="moe_mlp")(h)
        else:
            h = _dense(cfg.d_ff, ("embed", "mlp"), cfg, name="mlp_in")(h)
            h = nn.gelu(h)
            h = cfg.constrain(h, ("batch", "seq", "mlp"))
            h = _dense(cfg.d_model, ("mlp", "embed"), cfg,
                       name="mlp_out")(h)
        if cfg.dropout > 0:
            h = nn.Dropout(cfg.dropout, deterministic=not train)(h)
        return x + h


class Transformer(nn.Module):
    """Causal LM: tokens (B, S) int32 -> logits (B, S, vocab) fp32."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, train: bool = True,
                 return_hidden: bool = False):
        """return_hidden=True skips the lm_head and yields the final-LN
        hidden states (B, S, d) — the input of the streamed-vocab fused
        CE (ops/fused_xent.py), which reads the head kernel straight
        from the param tree. Init must use the default path so the
        lm_head params exist."""
        cfg = self.cfg
        # Table axes use the dedicated (vocab_table, embed_table) logical
        # names: vocab stays unsharded so the token gather partitions
        # trivially (no involuntary table rematerialization), embed splits
        # over tp. See sharding.DEFAULT_RULES.
        embed = nn.Embed(
            cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab_table", "embed_table")),
            name="tok_embed")
        pos_embed = self.param(
            "pos_embed",
            nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                         ("seq", "embed")),
            (cfg.max_len, cfg.d_model))
        x = embed(tokens)
        x = x + pos_embed[None, :tokens.shape[1]].astype(cfg.dtype)
        x = cfg.constrain(x, ("batch", "seq", "embed"))
        block = Block
        if cfg.remat:
            block = nn.remat(Block, static_argnums=(2,))
        for i in range(cfg.n_layers):
            x = block(cfg, name=f"block{i}")(x, train)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_final")(x)
        if return_hidden:
            return x
        # Tied-untied head: separate projection, fp32 logits for stable CE.
        logits = nn.DenseGeneral(
            cfg.vocab_size, axis=-1, dtype=jnp.float32, use_bias=False,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.variance_scaling(1.0, "fan_in", "normal"),
                ("embed", "vocab")),
            name="lm_head")(x)
        return logits


def lm_loss_fn(state, params, batch):
    """Causal LM loss for {'tokens': (B,S)} batches (next-token CE)."""
    logits = state.apply_fn({"params": params}, batch["tokens"], train=True)
    targets = batch["tokens"][:, 1:]
    logits = logits[:, :-1]
    logp = jax.nn.log_softmax(logits)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    loss = -jnp.mean(ll)
    return loss, {"ppl": jnp.exp(loss)}


def lm_loss_fused(state, params, batch, *, chunk: int = 8192):
    """lm_loss_fn without the (B,S,V) logits tensor: hidden states feed
    the streamed-vocab CE (ops/fused_xent.py), which reads the lm_head
    kernel from the param tree. Numerically equivalent to lm_loss_fn;
    use for large-vocab models where the logits dominate memory.

    Mesh note: intended for dp/fsdp worlds (kernel replicated or sharded
    on the embed dim — the contraction reduces it with a psum). Under
    tp the head kernel is sharded on the VOCAB dim, and the chunked
    dynamic_slice would make XLA gather the full table — use the dense
    lm_loss_fn there (its vocab-parallel softmax partitions cleanly)."""
    from edl_tpu.ops.fused_xent import streamed_lm_xent

    hidden = state.apply_fn({"params": params}, batch["tokens"],
                            train=True, return_hidden=True)
    b, s, d = hidden.shape
    hidden = hidden[:, :-1].reshape(b * (s - 1), d)
    targets = batch["tokens"][:, 1:].reshape(-1)
    kernel = params["lm_head"]["kernel"]
    loss = streamed_lm_xent(hidden, kernel, targets, chunk)
    return loss, {"ppl": jnp.exp(loss)}


def _sown(intermediates, name: str) -> list:
    """Collect every `self.sow`-ed value called ``name`` in a
    variables['intermediates'] tree (one per MoE block)."""
    from jax.tree_util import tree_flatten_with_path

    leaves, _ = tree_flatten_with_path(intermediates)
    return [v for path, v in leaves
            if any(getattr(kk, "key", None) == name for kk in path)]


def lm_loss_moe(state, params, batch, *, aux_weight: float = 0.01,
                apply_fn=None):
    """lm_loss_fn for moe=True configs: next-token CE plus the routers'
    load-balance auxiliary (aux_weight * mean over MoE blocks), with
    the capacity-drop fraction reported in the metrics. ``apply_fn``
    overrides state.apply_fn when the loss must run a DIFFERENT model
    binding than the state was built with (the manual-dispatch path
    rebinds cfg.moe_wire without touching the params)."""
    fn = apply_fn or state.apply_fn
    logits, mutated = fn({"params": params}, batch["tokens"],
                         train=True, mutable=["intermediates"])
    targets = batch["tokens"][:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1])
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    ce = -jnp.mean(ll)
    inter = mutated.get("intermediates", {})
    aux = _sown(inter, "moe_aux")
    dropped = _sown(inter, "moe_dropped")
    balance = (jnp.mean(jnp.stack(aux)) if aux
               else jnp.zeros((), jnp.float32))
    loss = ce + jnp.asarray(aux_weight, ce.dtype) * balance.astype(
        ce.dtype)
    return loss, {"ppl": jnp.exp(ce),
                  "moe_balance": balance,
                  "moe_dropped": (jnp.mean(jnp.stack(dropped)) if dropped
                                  else jnp.zeros((), jnp.float32))}


def choose_remat(cfg: TransformerConfig, batch_size: int,
                 seq_len: int | None = None,
                 hbm_bytes: int | None = None,
                 budget_frac: float = 0.6) -> bool:
    """Autotuned remat knob: does the backward's activation footprint
    fit, or should blocks be checkpointed?

    Pure arithmetic over the config (deterministic, testable): the
    no-remat backward keeps every block's saved activations live at
    once — roughly 12 d_model-wide tensors per block (embeddings, qkv,
    attn out, both mlp halves), plus the (heads, S, S) score matrix
    when attention is dense — while remat keeps ONE block's worth and
    recomputes the rest. If the no-remat estimate exceeds
    ``budget_frac`` of what is left after params + fp32 moments, remat
    pays its ~30% recompute FLOPs. ``hbm_bytes`` defaults to the
    backend device's reported memory, or a 16 GiB TPU-core default
    when the backend (CPU harness) reports none.
    """
    seq = seq_len or cfg.max_len
    itemsize = jnp.dtype(cfg.dtype).itemsize
    per_block = 12 * batch_size * seq * cfg.d_model * itemsize
    if cfg.attention == "dense" or (
            cfg.attention == "auto" and not cfg.use_ring
            and jax.default_backend() != "tpu"):
        per_block += batch_size * cfg.n_heads * seq * seq * itemsize
    activations = cfg.n_layers * per_block
    n_params = (cfg.vocab_size * cfg.d_model * 2          # embed + head
                + cfg.max_len * cfg.d_model
                + cfg.n_layers * (4 * cfg.d_model ** 2
                                  + 2 * cfg.d_model * cfg.d_ff))
    resident = n_params * (4 + 8)                          # fp32 + adam
    if hbm_bytes is None:
        stats = getattr(jax.devices()[0], "memory_stats", lambda: None)()
        hbm_bytes = (stats or {}).get("bytes_limit", 16 * (1 << 30))
    return activations > budget_frac * max(hbm_bytes - resident,
                                           hbm_bytes // 8)


def auto_remat(cfg: TransformerConfig, batch_size: int,
               seq_len: int | None = None,
               hbm_bytes: int | None = None) -> TransformerConfig:
    """cfg with ``remat`` set by :func:`choose_remat` (no-op when the
    estimate says activations fit)."""
    import dataclasses

    return dataclasses.replace(
        cfg, remat=choose_remat(cfg, batch_size, seq_len, hbm_bytes))
