"""Text-classification students for NLP distillation.

Capability of the reference's NLP distill students (example/distill/nlp/
model.py:84-135 — a BOW model: padding-masked embedding sum -> softsign
-> linear head; and a CNN variant: embedding -> width-3 conv -> pool ->
masked softsign sum -> head), re-designed for TPU: fixed-length padded id
batches (static shapes for XLA), bf16-friendly ops, and the head sized
by `num_classes` so the same students serve the sentiment demo (2) and
larger label sets.

These are the *students* of the ERNIE->BOW pipeline: the teacher is any
served model producing logits over the same classes (a transformer LM
head here — the ERNIE stand-in), consumed through `DistillReader`.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def _masked_sum(embedded: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    """Sum token vectors where id != 0 (0 is the pad id)."""
    mask = (ids != 0).astype(embedded.dtype)[..., None]
    return jnp.sum(embedded * mask, axis=1)


class BOWClassifier(nn.Module):
    """Bag-of-words: embed -> masked sum -> softsign -> dense head."""

    vocab_size: int = 30000
    embed_dim: int = 128
    num_classes: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, ids, train: bool = False):
        emb = nn.Embed(self.vocab_size, self.embed_dim,
                       dtype=self.dtype, name="embed")(ids)
        pooled = nn.soft_sign(_masked_sum(emb, ids))
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        name="head")(pooled)


class CNNClassifier(nn.Module):
    """Embed -> width-3 conv (relu) -> masked softsign sum -> head."""

    vocab_size: int = 30000
    embed_dim: int = 128
    num_filters: int = 128
    num_classes: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, ids, train: bool = False):
        emb = nn.Embed(self.vocab_size, self.embed_dim,
                       dtype=self.dtype, name="embed")(ids)
        # NWC conv over the token axis — XLA maps this onto the MXU as a
        # batched matmul; no NCHW transpose dance needed on TPU.
        hidden = nn.relu(nn.Conv(self.num_filters, kernel_size=(3,),
                                 padding="SAME", dtype=self.dtype,
                                 name="conv")(emb))
        pooled = nn.soft_sign(_masked_sum(hidden, ids))
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        name="head")(pooled)
