"""DeepFM CTR model over Criteo-style dense + hashed sparse features.

Capability of the reference's CTR example (example/ctr/ctr/train.py —
the classic Criteo ctr_dnn_model: 13 dense values + 26 categorical ids
hashed into a `sparse_feature_dim` space, per-feature embeddings, MLP
tower, sigmoid CTR head, AUC metric), upgraded to DeepFM (the model the
reference names in its CTR deployment docs): a first-order linear term +
second-order factorization-machine interaction term + deep tower share
one embedding table.

TPU notes: all sparse ids arrive pre-hashed as int32 in [0, vocab) with
a STATIC number of fields, so the whole model is gather + matmul —
there's no dynamic-shape sparse op anywhere, and one `nn.Embed` table
serves the 26 fields batched as a single (B, F) gather. The FM
second-order term uses the sum-square/square-sum identity, which is two
elementwise ops + reductions XLA fuses into the surrounding matmuls.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

NUM_DENSE = 13
NUM_SPARSE = 26


class DeepFM(nn.Module):
    """CTR logit over (dense float features, hashed sparse id fields)."""

    vocab_size: int = 1000 * 1000
    embed_dim: int = 10
    num_dense: int = NUM_DENSE
    num_sparse: int = NUM_SPARSE
    hidden: Sequence[int] = (400, 400, 400)
    num_classes: int = 1  # CTR logit
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, dense, sparse_ids, train: bool = False):
        """dense: (B, num_dense) float; sparse_ids: (B, num_sparse) int32."""
        B = sparse_ids.shape[0]
        # one shared table: (B, F) -> (B, F, D) in a single gather
        emb = nn.Embed(self.vocab_size, self.embed_dim, dtype=self.dtype,
                       name="sparse_embed")(sparse_ids)
        # first order: per-id scalar weight + linear on dense
        w1 = nn.Embed(self.vocab_size, 1, dtype=self.dtype,
                      name="sparse_linear")(sparse_ids)
        first = jnp.sum(w1[..., 0], axis=1, keepdims=True) + nn.Dense(
            1, dtype=self.dtype, name="dense_linear")(dense)
        # second order (FM): 0.5 * (sum^2 - sum-of-squares) over fields
        s = jnp.sum(emb, axis=1)
        second = 0.5 * jnp.sum(s * s - jnp.sum(emb * emb, axis=1),
                               axis=-1, keepdims=True)
        # deep tower over [flattened embeddings ; dense]
        deep = jnp.concatenate(
            [emb.reshape(B, self.num_sparse * self.embed_dim), dense], -1)
        for i, width in enumerate(self.hidden):
            deep = nn.relu(nn.Dense(width, dtype=self.dtype,
                                    name=f"deep_{i}")(deep))
        deep = nn.Dense(self.num_classes, dtype=self.dtype,
                        name="deep_out")(deep)
        return (first + second + deep).astype(jnp.float32)


def bce_with_logits(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean sigmoid cross-entropy; labels in {0,1}, logits (B, 1) or (B,)."""
    logits = logits.reshape(-1)
    labels = labels.reshape(-1).astype(logits.dtype)
    return jnp.mean(jnp.maximum(logits, 0) - logits * labels
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def auc(scores, labels) -> float:
    """Rank-based AUC (exact; ties get midranks) — the reference CTR
    job's tracked metric (train.py auc_var). Host-side numpy."""
    import numpy as np

    scores = np.asarray(scores).reshape(-1)
    labels = np.asarray(labels).reshape(-1)
    pos = labels > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty_like(scores)
    ranks[order] = np.arange(1, len(scores) + 1)
    # midranks for tied scores
    sorted_scores = scores[order]
    i = 0
    while i < len(scores):
        j = i
        while j + 1 < len(scores) and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = (i + j) / 2.0 + 1
        i = j + 1
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0)
                 / (n_pos * n_neg))
