"""Small MLP classifier — the mnist-scale teacher/student model.

Capability parity: the reference's mnist distill recipe uses a tiny
teacher served to students (example/distill/mnist_distill/
train_with_fleet.py:134-145); this is the CPU-testable model both sides of
our distill pipeline use in tests and demos.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn


class MLP(nn.Module):
    num_classes: int = 10
    hidden: Sequence[int] = (256, 128)

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        for width in self.hidden:
            x = nn.relu(nn.Dense(width)(x))
        return nn.Dense(self.num_classes)(x)


def mlp(num_classes: int = 10, **kw) -> MLP:
    return MLP(num_classes=num_classes, **kw)
