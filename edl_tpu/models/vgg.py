"""VGG (11/13/16/19) in flax.

Capability of the reference `example/collective/resnet50/models/vgg.py`
(conv-block builder with per-stage conv counts + 3 FC layers). NHWC, bf16
activations, fp32 classifier head — see resnet.py for the layout rationale.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn


class VGG(nn.Module):
    stage_convs: Sequence[int]          # convs per stage, 5 stages
    num_classes: int = 1000
    fc_dim: int = 4096
    dropout: float = 0.5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, kernel_size=(3, 3), dtype=self.dtype,
                       kernel_init=nn.initializers.variance_scaling(
                           2.0, "fan_out", "normal"))
        x = x.astype(self.dtype)
        widths = (64, 128, 256, 512, 512)
        for stage, (n_convs, width) in enumerate(
                zip(self.stage_convs, widths)):
            for i in range(n_convs):
                x = conv(width, name=f"conv{stage}_{i}")(x)
                x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                 epsilon=1e-5, dtype=self.dtype,
                                 name=f"norm{stage}_{i}")(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        for i in range(2):
            x = nn.Dense(self.fc_dim, dtype=self.dtype, name=f"fc{i}")(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


VGG11 = partial(VGG, stage_convs=(1, 1, 2, 2, 2))
VGG13 = partial(VGG, stage_convs=(2, 2, 2, 2, 2))
VGG16 = partial(VGG, stage_convs=(2, 2, 3, 3, 3))
VGG19 = partial(VGG, stage_convs=(2, 2, 4, 4, 4))
