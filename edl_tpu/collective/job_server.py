"""JobServer/JobClient — elastic demo pair with timed-resize fault injection.

The reference's flagship demo drives elasticity with an ABSENT package
(`paddle_edl.demo.collective.job_server_demo` / `job_client_demo`,
start_job_server.sh:11: `--time_interval_to_change 900` changes the pod set
every 15 min — "resize is the tested fault", SURVEY.md §5). This is that
pair, working: a small HTTP/JSON control server publishing the *desired
node count*, and a client that keeps that many launcher processes running
on this host.

  python -m edl_tpu.collective.job_server --port 8180 \
      --nodes-range 2:4 --time-interval-to-change 900
  python -m edl_tpu.collective.job_server client --server :8180 -- \
      python -m my_trainer ...
"""

from __future__ import annotations

import argparse
import json
import random
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from edl_tpu.obs import recorder as flight
from edl_tpu.obs import trace
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.collective.job_server")


class JobState:
    def __init__(self, job_id: str, min_nodes: int, max_nodes: int,
                 desired: int | None = None, seed: int = 0,
                 store=None):
        self.job_id = job_id
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        if desired is None:
            desired = max_nodes
        self.desired = max(min_nodes, min(max_nodes, desired))
        self._rng = random.Random(seed)
        # every desired_nodes change this server actually served, in
        # order — the audit trail demos/tests cross-check against the
        # scaler's decision journal (a resize NOT in the journal is a
        # scaler acting outside its own observability surface)
        self.resize_log: list[dict] = []
        # RLock: resize()/random_resize() return snapshot() while holding it.
        self._lock = threading.RLock()
        # Migration plane: with a coordination store attached, every
        # served resize publishes a monotonic migration epoch + the
        # donor roster alive at the decision instant (the fencing/audit
        # record peers and the --resize-p2p demo key on).
        self.store = store
        self._migration_epoch = 0

    def attach_store(self, store) -> None:
        with self._lock:
            self.store = store

    def _publish_migration_epoch(self, prev: int) -> None:
        # caller holds self._lock (epoch ordering must match resize_log)
        if self.store is None:
            return
        from edl_tpu.collective import migration as mig
        self._migration_epoch += 1
        try:
            mig.publish_resize_epoch(self.store, self.job_id,
                                     epoch=self._migration_epoch,
                                     desired=self.desired, prev=prev)
        except Exception as exc:  # noqa: BLE001 — best-effort: the
            # resize itself must be served even if the store hiccups
            log.warning("migration epoch publish failed: %s", exc)

    def snapshot(self) -> dict:
        with self._lock:
            return {"job_id": self.job_id, "desired_nodes": self.desired,
                    "min_nodes": self.min_nodes,
                    "max_nodes": self.max_nodes}

    def resize(self, desired: int) -> dict:
        with self._lock:
            clamped = not (self.min_nodes <= desired <= self.max_nodes)
            prev = self.desired
            self.desired = max(self.min_nodes,
                               min(self.max_nodes, desired))
            self.resize_log.append({"from": prev, "to": self.desired,
                                    "requested": desired,
                                    "clamped": clamped,
                                    "source": "resize"})
            # flight-recorder witness: one event per SERVED resize, in
            # log order — the chaos auditor cross-checks this ring
            # against resize_log and the scaler journal (I2's third
            # witness)
            flight.record("resize", plane="job", job_id=self.job_id,
                          frm=prev, to=self.desired, source="resize",
                          epoch=self._migration_epoch
                          + (1 if self.desired != prev else 0))
            if self.desired != prev:
                self._publish_migration_epoch(prev)
            if clamped:
                # loud, not silent: the scaler journals the response, so
                # a clamp must be visible there and in this log
                log.warning("resize request %d clamped to %d "
                            "(range [%d, %d])", desired, self.desired,
                            self.min_nodes, self.max_nodes)
            else:
                log.info("desired_nodes -> %d", self.desired)
            snap = self.snapshot()
            snap["clamped"] = clamped
            if clamped:
                snap["requested"] = desired
            return snap

    def random_resize(self) -> dict:
        """Fault injection: pick a different node count in [min, max]."""
        with self._lock:
            choices = [n for n in range(self.min_nodes, self.max_nodes + 1)
                       if n != self.desired] or [self.desired]
            prev = self.desired
            self.desired = self._rng.choice(choices)
            self.resize_log.append({"from": prev, "to": self.desired,
                                    "requested": self.desired,
                                    "clamped": False, "source": "fault"})
            flight.record("resize", plane="job", job_id=self.job_id,
                          frm=prev, to=self.desired, source="fault",
                          epoch=self._migration_epoch
                          + (1 if self.desired != prev else 0))
            if self.desired != prev:
                self._publish_migration_epoch(prev)
            log.info("fault injection: desired_nodes -> %d", self.desired)
            return self.snapshot()


def _make_handler(state: JobState):
    class Handler(BaseHTTPRequestHandler):
        def _reply(self, obj: dict, code: int = 200) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path.rstrip("/") in ("", "/job"):
                self._reply(state.snapshot())
            else:
                self._reply({"error": "not found"}, 404)

        def do_POST(self):
            if self.path.rstrip("/") != "/resize":
                self._reply({"error": "not found"}, 404)
                return
            # Validate the payload explicitly: every malformed request —
            # bad JSON, non-object body, missing/non-integer `desired` —
            # is a 400 with an error body, never a handler 500.
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
            except (json.JSONDecodeError, UnicodeDecodeError) as exc:
                self._reply({"error": f"malformed JSON: {exc}"}, 400)
                return
            if not isinstance(payload, dict):
                self._reply({"error": "payload must be a JSON object"},
                            400)
                return
            if "desired" not in payload:
                self._reply({"error": "missing field 'desired'"}, 400)
                return
            desired = payload["desired"]
            if isinstance(desired, bool) \
                    or isinstance(desired, float) \
                    and not desired.is_integer():
                self._reply({"error": f"'desired' must be an integer, "
                                      f"got {desired!r}"}, 400)
                return
            try:
                desired = int(desired)
            except (TypeError, ValueError):
                self._reply({"error": f"'desired' must be an integer, "
                                      f"got {desired!r}"}, 400)
                return
            # Trace seam (HTTP hop): a caller's span context arrives in
            # the X-EDL-Trace header; the served resize — including the
            # epoch publication inside it, which embeds the context for
            # the trainers — becomes a child span of the decision.
            ctx = trace.parse_context(
                (self.headers.get("X-EDL-Trace") or "").split(":")
                if self.headers.get("X-EDL-Trace") else None)
            with trace.adopt(ctx):
                with trace.span("resize.actuate",
                                attrs={"job": state.job_id,
                                       "desired": desired}):
                    self._reply(state.resize(desired))

        def log_message(self, fmt, *args):  # route into our logger
            log.debug("http: " + fmt, *args)

    return Handler


class JobServer:
    def __init__(self, state: JobState, port: int = 8180,
                 host: str = "127.0.0.1",
                 time_interval_to_change: float = 0.0):
        # /resize is unauthenticated, so external binding ("0.0.0.0") is an
        # explicit operator opt-in (--host), never the default.
        self.state = state
        self.httpd = ThreadingHTTPServer((host, port), _make_handler(state))
        self.port = self.httpd.server_address[1]
        self.interval = time_interval_to_change
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def start(self) -> "JobServer":
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True,
                             name="job-server-http")
        t.start()
        self._threads.append(t)
        if self.interval > 0:
            f = threading.Thread(target=self._fault_loop, daemon=True,
                                 name="job-server-faults")
            f.start()
            self._threads.append(f)
        log.info("JobServer on :%d desired=%d", self.port,
                 self.state.desired)
        return self

    def _fault_loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.state.random_resize()

    def stop(self) -> None:
        self._stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()


def get_job(server: str, timeout: float = 5.0) -> dict:
    if server.startswith(":"):
        server = "127.0.0.1" + server
    if not server.startswith("http"):
        server = "http://" + server
    with urllib.request.urlopen(server + "/job", timeout=timeout) as r:
        return json.loads(r.read())


def request_resize(server: str, desired: int, timeout: float = 5.0) -> dict:
    if server.startswith(":"):
        server = "127.0.0.1" + server
    if not server.startswith("http"):
        server = "http://" + server
    # Trace root of a resize (unless the caller — e.g. the scaler's
    # decide span — already has one): the context rides the HTTP hop as
    # X-EDL-Trace, so actuation/adoption/restore all join this trace.
    with trace.span("resize.request", attrs={"desired": desired}):
        headers = {"Content-Type": "application/json"}
        ctx = trace.inject()
        if ctx is not None:
            headers["X-EDL-Trace"] = ":".join(ctx)
        req = urllib.request.Request(
            server + "/resize", method="POST",
            data=json.dumps({"desired": desired}).encode(),
            headers=headers)
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())


class JobClient:
    """Keeps `desired_nodes` launcher processes running on this host.

    The single-host demo topology (reference demo README: 8 pods on one
    node): each launcher it spawns is one elastic pod; shrinking kills the
    newest launchers and the survivors stop-resume onto the smaller world.
    """

    def __init__(self, server: str, launcher_cmd: list[str],
                 poll: float = 2.0):
        self.server = server
        self.launcher_cmd = launcher_cmd
        self.poll = poll
        self.procs: list[subprocess.Popen] = []
        self._stop = threading.Event()

    def _reap(self) -> None:
        self.procs = [p for p in self.procs if p.poll() is None]

    def reconcile(self, desired: int) -> None:
        self._reap()
        while len(self.procs) < desired:
            p = subprocess.Popen(self.launcher_cmd,
                                 start_new_session=True)
            log.info("spawned launcher pid=%d (%d/%d)", p.pid,
                     len(self.procs) + 1, desired)
            self.procs.append(p)
        while len(self.procs) > desired:
            p = self.procs.pop()
            log.info("stopping launcher pid=%d", p.pid)
            p.terminate()

    def run(self) -> int:
        try:
            while not self._stop.is_set():
                try:
                    job = get_job(self.server)
                except OSError as exc:
                    log.warning("job server unreachable: %s", exc)
                    time.sleep(self.poll)
                    continue
                self.reconcile(int(job["desired_nodes"]))
                self._reap()
                if not self.procs and int(job["desired_nodes"]) == 0:
                    return 0
                time.sleep(self.poll)
        finally:
            for p in self.procs:
                p.terminate()
        return 0

    def stop(self) -> None:
        self._stop.set()


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "client":
        parser = argparse.ArgumentParser(prog="edl_tpu job_server client")
        parser.add_argument("--server", default=":8180")
        parser.add_argument("--poll", type=float, default=2.0)
        parser.add_argument("cmd", nargs=argparse.REMAINDER)
        args = parser.parse_args(argv[1:])
        cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
        if not cmd:
            parser.error("missing launcher command (after --)")
        return JobClient(args.server, cmd, poll=args.poll).run()

    parser = argparse.ArgumentParser(prog="edl_tpu.collective.job_server")
    parser.add_argument("--job-id", default="default_job")
    parser.add_argument("--host", default="127.0.0.1",
                        help="bind address (0.0.0.0 exposes the "
                             "unauthenticated /resize endpoint)")
    parser.add_argument("--port", type=int, default=8180)
    parser.add_argument("--nodes-range", default="1:4")
    parser.add_argument("--desired", type=int, default=None)
    parser.add_argument("--time-interval-to-change", type=float, default=0.0,
                        help="fault injection: random resize every S seconds")
    parser.add_argument("--seed", type=int, default=0)
    # scaler-driven mode: resizes come from the utilization-driven
    # decision plane (edl_tpu/scaler) instead of the fault injector
    parser.add_argument("--scaler", action="store_true",
                        help="drive desired_nodes from the autoscaler "
                             "(requires --store)")
    parser.add_argument("--store", default=None,
                        help="coordination store endpoint (required by "
                             "--scaler; with or without it, /resize "
                             "publishes migration epochs + donor "
                             "rosters for p2p state migration)")
    parser.add_argument("--scaler-interval", type=float, default=None,
                        help="decision interval s "
                             "(EDL_TPU_SCALER_INTERVAL)")
    parser.add_argument("--scaler-journal", default=None,
                        help="JSON-lines decision journal file")
    parser.add_argument("--dry-run", action="store_true",
                        help="scaler journals decisions without resizing")
    args = parser.parse_args(argv)
    if args.scaler and not args.store:
        parser.error("--scaler requires --store")
    lo, hi = (int(x) for x in args.nodes_range.split(":"))
    state = JobState(args.job_id, lo, hi, desired=args.desired,
                     seed=args.seed)
    server = JobServer(state, port=args.port, host=args.host,
                       time_interval_to_change=args.time_interval_to_change)
    server.start()
    controller = store = None
    if args.store:
        from edl_tpu.coord.redis_store import connect_store
        store = connect_store(args.store)
        state.attach_store(store)
    if args.scaler:
        from edl_tpu.scaler.controller import (ScalerConfig,
                                               ScalerController)
        from edl_tpu.scaler.policy import ThroughputPolicy
        from edl_tpu.utils.config import from_env
        overrides = ({"interval": args.scaler_interval}
                     if args.scaler_interval is not None else {})
        config = from_env(ScalerConfig, **overrides)
        config.min_nodes, config.max_nodes = lo, hi
        # in-process actuation: no HTTP hop for limits or /resize
        controller = ScalerController(
            store, [args.job_id],
            ThroughputPolicy(gain_threshold=config.gain_threshold,
                             cooldown_s=config.cooldown_s),
            config=config, dry_run=args.dry_run,
            journal_path=args.scaler_journal,
            actuate=lambda _job, desired: state.resize(desired)).start()
        log.info("scaler-driven mode: decisions every %.1fs",
                 config.interval)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        if controller is not None:
            controller.stop()
        if store is not None:
            store.close()
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
