"""Pod rank claim: smallest-free-slot CAS race with lease keepalive.

Capability of the reference's PodRegister (utils/register.py:60-88: claim
the smallest free rank via etcd put_if_not_exists, 1s lease refresher
thread, master = rank 0) on our coordination store.

Key layout:
    /{job}/ranks/{i}   -> Pod JSON, leased (ephemeral)   — the claim
    /{job}/cluster     -> Cluster JSON, permanent        — leader-published
    /{job}/complete    -> "1", permanent                 — job done marker

The state-migration plane (collective/migration.py) hangs its donor
adverts, resize epochs, and restore/adoption acks off the same job
scope under /{job}/migration/ — a released claim is what lets a
lingering donor's `_linger` see that nobody is left to serve, so
`release()` must keep revoking the lease eagerly (never TTL-drain) on
the graceful paths.
"""

from __future__ import annotations

import socket
import threading
import time

from edl_tpu.collective.cluster import Pod
from edl_tpu.coord.client import HostLeaseCoalescer, LeaseKeeper, \
    host_coalescer
from edl_tpu.coord.store import Store
from edl_tpu.utils import config
from edl_tpu.utils.exceptions import EdlRegisterError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.collective.register")


def default_coalescer(store: Store, ttl: float) -> HostLeaseCoalescer | None:
    """The host-shared lease coalescer when EDL_TPU_LEASE_COALESCE=1
    (default off: per-pod leases, the pre-r24 behavior). One lease per
    host carries every pod registration with a single batched keepalive
    write — per-host heartbeats instead of per-pod ones."""
    if not config.env_flag("EDL_TPU_LEASE_COALESCE", False):
        return None
    return host_coalescer(store, socket.gethostname(), ttl)


def ranks_prefix(job_id: str) -> str:
    return f"/{job_id}/ranks/"

def rank_key(job_id: str, rank: int) -> str:
    return f"/{job_id}/ranks/{rank:06d}"

def cluster_key(job_id: str) -> str:
    return f"/{job_id}/cluster"

def complete_key(job_id: str) -> str:
    return f"/{job_id}/complete"


def live_pods(store: Store, job_id: str) -> tuple[list[Pod], int]:
    """Snapshot of currently-claimed pods (sorted by claimed rank)."""
    records, revision = store.get_prefix(ranks_prefix(job_id))
    pods = [Pod.from_json(r.value) for r in records]
    return sorted(pods, key=lambda p: p.claimed_rank), revision


class PodRegister:
    """Claim + keep a rank slot for this pod.

    The claim is leased: if this process dies, the slot frees after TTL and
    the watcher on every other pod sees the membership change (the
    reference's ~15s etcd-TTL drain, collective/launch.py:180-183).
    """

    def __init__(self, store: Store, job_id: str, pod: Pod,
                 max_nodes: int = 1024, ttl: float = 10.0,
                 coalescer: HostLeaseCoalescer | None = None):
        self.store = store
        self.job_id = job_id
        self.pod = pod
        self.max_nodes = max_nodes
        self.ttl = ttl
        self.lease: int | None = None
        self.lost = threading.Event()
        self._keeper: LeaseKeeper | None = None
        # Lease coalescing (doc/design_coord.md): with a coalescer the
        # claim rides the HOST lease (one keepalive writer per host, not
        # per pod) and release detaches just this pod's key — siblings
        # on the shared lease are untouched.
        self._coalescer = coalescer if coalescer is not None \
            else default_coalescer(store, ttl)
        self._claimed_key: str | None = None

    def claim(self, timeout: float = 60.0) -> int:
        """Race for the smallest free slot. Returns the claimed rank."""
        from edl_tpu.coord.store import try_watch
        deadline = time.monotonic() + timeout
        watch = None
        try:
            while time.monotonic() < deadline:
                lease = self._coalescer.lease() \
                    if self._coalescer is not None \
                    else self.store.lease_grant(self.ttl)
                for i in range(self.max_nodes):
                    self.pod.claimed_rank = i
                    if self.store.put_if_absent(rank_key(self.job_id, i),
                                                self.pod.to_json(),
                                                lease=lease):
                        self.lease = lease
                        self._claimed_key = rank_key(self.job_id, i)
                        if self._coalescer is not None:
                            self._coalescer.attach(self._claimed_key,
                                                   on_lost=self._on_lost)
                        else:
                            self._keeper = LeaseKeeper(
                                self.store, lease, interval=self.ttl / 6.0,
                                on_lost=self._on_lost).start()
                        log.info("pod %s claimed rank %d",
                                 self.pod.pod_id, i)
                        return i
                # Every slot taken: revoke the unused lease and retry when
                # a slot frees (its DELETE event wakes us; the 1s re-poll
                # is the EDL_TPU_COORD_WATCH=0 / in-process fallback).
                # A coalesced host lease is shared — never revoke it here.
                if self._coalescer is None:
                    self.store.lease_revoke(lease)
                if watch is None:
                    watch = try_watch(self.store, ranks_prefix(self.job_id))
                if watch is not None:
                    watch.get(timeout=1.0)
                else:
                    time.sleep(1.0)
            raise EdlRegisterError(
                f"no free rank slot in {self.max_nodes} after {timeout}s")
        finally:
            if watch is not None:
                watch.cancel()

    def _on_lost(self) -> None:
        log.error("pod %s lost its rank lease", self.pod.pod_id)
        self.lost.set()

    def refresh_value(self) -> None:
        """Rewrite our key (e.g. after port change), keeping the lease."""
        if self.lease is not None:
            self.store.put(rank_key(self.job_id, self.pod.claimed_rank),
                           self.pod.to_json(), lease=self.lease)

    def release(self) -> None:
        if self._coalescer is not None and self._claimed_key is not None:
            # per-pod revoke on the shared lease: delete only our key
            self._coalescer.detach(self._claimed_key, delete=True)
            self._claimed_key = None
            self.lease = None
        if self._keeper is not None:
            self._keeper.stop(revoke=True)
            self._keeper = None
            self.lease = None

    def close(self) -> None:
        """Teardown alias for `release` (edl-lint resource-lifecycle:
        the keeper thread's joining close path)."""
        self.release()
