"""Elastic collective job orchestration.

The capability of the reference's L3 layer (SURVEY.md §1): pod rank claim,
cluster watcher, stop-resume barrier, trainer process management, and the
JobServer/JobClient demo pair (ABSENT upstream, re-specified from
collective/launch.py + example/demo/collective/README.md).

TPU-native shape: one launcher process per TPU host ("pod"); the trainer it
spawns is a single JAX process driving all local chips, joined into a
multi-host world via `jax.distributed` + a `jax.sharding.Mesh` — elasticity
is stop-resume: on membership change every launcher kills its trainer and
re-forms the cluster; trainers resume from the latest checkpoint on a fresh
mesh.
"""

from edl_tpu.collective.cluster import Cluster, Pod
from edl_tpu.collective.job_env import JobEnv, TrainerEnv

__all__ = ["Cluster", "Pod", "JobEnv", "TrainerEnv"]
