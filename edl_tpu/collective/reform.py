"""Reform state machine: survive a device-world change without a restart.

The r12 migration plane adopts a resize in place only when the pod's
device set is unchanged; any true device-world change still stop-resumes
every process (the ROADMAP item 2 gap). This module is the explicit,
fenced protocol that closes it: a surviving trainer keeps its OS
process, walks the phase ladder

    quiesce -> mesh-reform -> peer-restore -> re-jit -> first-step

and every phase carries a **deadline**, a **typed failure**
(`ReformError`) and a **defined downgrade**:

    phase         failure means                     downgrade
    ------------  --------------------------------  -------------------
    quiesce       step/ckpt drain stalled           stop-resume
    mesh-reform   topology re-formation timed out   stop-resume
    peer-restore  donor died / peer stalled         disk restore
    disk-restore  local disk also unusable          stop-resume
    re-jit        recompile failed                  stop-resume
    first-step    new generation never stepped      stop-resume (via the
                                                    launcher's adopt
                                                    timeout)

"stop-resume" is the CLEAN downgrade, never a wedge: the survivor seals
its live state, exits 143 and lingers as a donor, and the launcher's
existing `wait_adopted` timeout respawns the world exactly as a classic
stop-resume would — with the old generation's state served from memory.
A half-reformed survivor can never ack adoption: acks are generation-
fenced against the leader-published cluster/epoch docs (see
`MigrationService.ack`), so a stale ack bounces instead of convincing
the launcher a torn world is healthy.

The machine itself is pure stdlib (no jax/numpy): the TrainLoop drives
it with jax-side executors (train/loop.py), the chaos pod workers drive
it with their numpy checkpoint rig (chaos/worker.py), and both report
the same phase/outcome shape the I6 invariant audits.

Deadlines are the ``EDL_TPU_REFORM_*`` knobs; enforcement is
cooperative (executors receive the phase deadline) plus post-hoc: an
executor that returns after its budget is still a typed phase failure,
so a stall can slip the deadline by one blocking call but never
silently succeed late. True wedges (a phase that never returns) are
bounded by the launcher's ``EDL_TPU_ADOPT_TIMEOUT`` fallback.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable

from edl_tpu.obs import recorder as flight
from edl_tpu.obs import trace
from edl_tpu.utils.config import field, from_env
from edl_tpu.utils.exceptions import EdlError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.collective.reform")

# canonical phase order (doc/design_elastic_collective.md table)
PHASES = ("quiesce", "mesh-reform", "peer-restore", "disk-restore",
          "re-jit", "first-step")

#: outcome of a completed ladder
IN_PLACE = "in-place"
STOP_RESUME = "stop-resume"

#: phase -> downgrade when it fails (the ladder's one retry is
#: peer-restore -> disk-restore; everything else degrades to a clean
#: stop-resume)
DOWNGRADE = {
    "quiesce": STOP_RESUME,
    "mesh-reform": STOP_RESUME,
    "peer-restore": "disk",
    "disk-restore": STOP_RESUME,
    "re-jit": STOP_RESUME,
    "first-step": STOP_RESUME,
}


@dataclass
class ReformConfig:
    """Per-phase deadlines (seconds). Generous defaults: the budgets
    exist to convert a wedge into a typed downgrade, not to race the
    happy path."""

    quiesce_s: float = field(10.0, env="EDL_TPU_REFORM_QUIESCE_S")
    mesh_s: float = field(30.0, env="EDL_TPU_REFORM_MESH_S")
    restore_s: float = field(60.0, env="EDL_TPU_REFORM_RESTORE_S")
    rejit_s: float = field(300.0, env="EDL_TPU_REFORM_REJIT_S")

    def budget(self, phase: str) -> float:
        return {"quiesce": self.quiesce_s,
                "mesh-reform": self.mesh_s,
                "peer-restore": self.restore_s,
                "disk-restore": self.restore_s,
                "re-jit": self.rejit_s,
                "first-step": self.rejit_s}[phase]

    @classmethod
    def from_environ(cls, **overrides) -> "ReformConfig":
        return from_env(cls, **overrides)


class ReformError(EdlError):
    """Typed phase failure; carries the phase and its downgrade."""

    def __init__(self, phase: str, reason: str, downgrade: str):
        super().__init__(f"reform {phase} failed ({downgrade} downgrade):"
                         f" {reason}")
        self.phase = phase
        self.reason = reason
        self.downgrade = downgrade


class ReformMachine:
    """One generation change, walked phase by phase.

    Drive it either with `run_ladder` (the canonical order, with the
    peer->disk restore downgrade built in) or phase-at-a-time with
    `run_phase`. Phases the caller cannot run inside the ladder (the
    loop's re-jit/first-step happen at the next training step) are
    recorded afterwards with `note_deferred`; `finish()` seals the
    outcome into the flight recorder exactly once.
    """

    def __init__(self, generation: int, config: ReformConfig | None = None,
                 *, trace_parent: tuple[str, str] | None = None,
                 who: str = ""):
        self.generation = generation
        self.config = config or ReformConfig.from_environ()
        self.phases: list[dict] = []   # [{phase, s, ok, error?, overrun?}]
        self.result: str | None = None
        self.restore: str | None = None   # None | "peers" | "disk"
        self.error: str | None = None
        self.who = who
        self._parent = trace_parent
        self._finished = False

    # -- low level -----------------------------------------------------------

    def run_phase(self, name: str, fn: Callable[[float], Any]) -> Any:
        """Run one phase: `fn(deadline)` under a `reform.<name>` span.

        Raises `ReformError` on any exception (typed with the phase's
        downgrade) and on post-hoc deadline overrun — a phase that
        *returns* late still failed its budget."""
        budget = self.config.budget(name)
        t0 = time.monotonic()
        deadline = t0 + budget
        try:
            with trace.span(f"reform.{name}", parent=self._parent,
                            attrs={"generation": self.generation}):
                out = fn(deadline)
        except ReformError as exc:
            self.phases.append({"phase": name,
                                "s": round(time.monotonic() - t0, 4),
                                "ok": False, "error": str(exc)})
            raise
        except Exception as exc:  # noqa: BLE001 — every phase failure
            # becomes the TYPED error its downgrade is keyed on
            self.phases.append({"phase": name,
                                "s": round(time.monotonic() - t0, 4),
                                "ok": False, "error": str(exc)})
            raise ReformError(name, str(exc), DOWNGRADE[name]) from exc
        elapsed = time.monotonic() - t0
        if elapsed > budget:
            self.phases.append({"phase": name, "s": round(elapsed, 4),
                                "ok": False, "overrun": True,
                                "error": f"deadline exceeded "
                                         f"({elapsed:.3f}s > {budget}s)"})
            raise ReformError(
                name, f"deadline exceeded ({elapsed:.3f}s > {budget}s)",
                DOWNGRADE[name])
        self.phases.append({"phase": name, "s": round(elapsed, 4),
                            "ok": True})
        return out

    # -- the canonical ladder -------------------------------------------------

    def run_ladder(self, *, quiesce: Callable | None = None,
                   mesh_reform: Callable | None = None,
                   restore_peers: Callable | None = None,
                   restore_disk: Callable | None = None,
                   rejit: Callable | None = None) -> "ReformMachine":
        """Walk the phases in order. A `None` executor skips its phase
        (recorded as skipped-by-construction, e.g. no restore needed
        when the device set is unchanged). peer-restore failure retries
        as disk-restore; any other failure — or a disk failure — lands
        the outcome on the clean stop-resume downgrade. Never raises."""
        try:
            if quiesce is not None:
                self.run_phase("quiesce", quiesce)
            if mesh_reform is not None:
                self.run_phase("mesh-reform", mesh_reform)
            if restore_peers is not None:
                try:
                    self.run_phase("peer-restore", restore_peers)
                    self.restore = "peers"
                except ReformError as exc:
                    if exc.downgrade != "disk" or restore_disk is None:
                        raise
                    log.warning("reform gen=%d: %s — disk-restore "
                                "downgrade", self.generation, exc)
                    self.run_phase("disk-restore", restore_disk)
                    self.restore = "disk"
            if rejit is not None:
                self.run_phase("re-jit", rejit)
            self.result = IN_PLACE
        except ReformError as exc:
            self.result = STOP_RESUME
            self.error = str(exc)
            log.warning("reform gen=%d degraded to stop-resume: %s",
                        self.generation, exc)
        return self

    # -- deferred phases (loop-side re-jit / first-step) ----------------------

    def note_deferred(self, name: str, seconds: float,
                      ok: bool = True, error: str | None = None) -> None:
        """Record a phase measured outside the ladder (the loop's first
        post-reform step IS re-jit + first-step). Deadline overruns are
        flagged but do not retro-downgrade — the step already ran; the
        launcher's adopt timeout is the hard bound on this tail."""
        budget = self.config.budget(name)
        rec = {"phase": name, "s": round(seconds, 4), "ok": ok}
        if error:
            rec["error"] = error
        if seconds > budget:
            rec["overrun"] = True
        self.phases.append(rec)

    def finish(self) -> dict:
        """Seal the outcome (idempotent): one flight-recorder event per
        reform, and the dict the adoption ack / worker report carries."""
        doc = self.to_dict()
        if not self._finished:
            self._finished = True
            flight.record("reform", who=self.who,
                          generation=self.generation,
                          result=self.result, restore=self.restore,
                          error=self.error,
                          phases={p["phase"]: p["s"] for p in self.phases})
        return doc

    def phase_seconds(self) -> dict[str, float]:
        return {p["phase"]: p["s"] for p in self.phases}

    def to_dict(self) -> dict:
        return {"generation": self.generation, "result": self.result,
                "restore": self.restore, "error": self.error,
                "phases": self.phases}


def wait_until(pred: Callable[[], bool], deadline: float,
               interval: float = 0.05) -> bool:
    """Cooperative-deadline poll helper for phase executors: True when
    `pred` held before `deadline` (monotonic), False on timeout."""
    while True:
        if pred():
            return True
        if time.monotonic() >= deadline:
            return False
        time.sleep(min(interval, max(0.0, deadline - time.monotonic())))
