"""Trainer subprocess management: spawn, log redirect, kill-tree, watch.

Capability of the reference's edl_process (utils/edl_process.py:36-152:
spawn per-trainer subprocess with env, `workerlog.N` redirect, psutil
kill-tree, poll-based liveness). TPU difference: ONE trainer process per
host (it drives all local chips through JAX), not one per accelerator — so
this manages a single child, started in its own process group so the whole
tree dies together.
"""

from __future__ import annotations

import os
import signal
import subprocess
import time
from dataclasses import dataclass, field

from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.collective.process")


@dataclass
class TrainerProc:
    proc: subprocess.Popen
    log_path: str
    cmd: list[str] = field(default_factory=list)

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    @property
    def returncode(self) -> int | None:
        return self.proc.poll()


def start_trainer(cmd: list[str], env: dict, log_dir: str,
                  rank: int = 0) -> TrainerProc:
    """Spawn the trainer with stdout+stderr -> {log_dir}/workerlog.{rank}."""
    os.makedirs(log_dir, exist_ok=True)
    log_path = os.path.join(log_dir, f"workerlog.{rank}")
    fout = open(log_path, "ab", buffering=0)
    fout.write(f"==== start rank={rank} cmd={cmd} ====\n".encode())
    proc = subprocess.Popen(cmd, env=env, stdout=fout, stderr=fout,
                            start_new_session=True)  # own process group
    log.info("started trainer rank=%d pid=%d log=%s", rank, proc.pid,
             log_path)
    return TrainerProc(proc=proc, log_path=log_path, cmd=list(cmd))


def release_trainer(tp: TrainerProc) -> None:
    """SIGTERM the trainer group and return immediately — the donor
    path of the state-migration plane: a migration-enabled trainer
    converts SIGTERM into a graceful stop plus a bounded donor linger
    (it keeps serving its sealed snapshot to the re-formed world), so
    the caller must neither block on it nor escalate to SIGKILL the way
    `terminate_trainer` does. The caller owns the eventual force-kill
    deadline (launch.py's lingering reap)."""
    if not tp.alive():
        return
    try:
        os.killpg(os.getpgid(tp.pid), signal.SIGTERM)
        log.info("released trainer pid=%d (graceful stop + donor linger)",
                 tp.pid)
    except (ProcessLookupError, PermissionError):
        pass


def _signal_group(tp: TrainerProc, sig: signal.Signals) -> bool:
    """Deliver ``sig`` to the trainer's whole process group; False when
    the group is already gone. The chaos plane's process injector uses
    this for its SIGKILL/SIGSTOP/SIGCONT faults — the group, not the
    pid, so a paused trainer cannot keep live grandchildren serving."""
    if not tp.alive():
        return False
    try:
        os.killpg(os.getpgid(tp.pid), sig)
        return True
    except (ProcessLookupError, PermissionError):
        return False


def kill_trainer(tp: TrainerProc) -> bool:
    """SIGKILL, no grace — the crash fault (vs `terminate_trainer`'s
    graceful escalation)."""
    ok = _signal_group(tp, signal.SIGKILL)
    if ok:
        try:
            tp.proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            log.error("trainer pid=%d survived SIGKILL", tp.pid)
    return ok


def pause_trainer(tp: TrainerProc) -> bool:
    """SIGSTOP the group (the grey-failure fault: alive to the OS, dead
    to every deadline)."""
    return _signal_group(tp, signal.SIGSTOP)


def resume_trainer(tp: TrainerProc) -> bool:
    """SIGCONT a paused group."""
    return _signal_group(tp, signal.SIGCONT)


def terminate_trainer(tp: TrainerProc, grace: float = 10.0) -> None:
    """SIGTERM the process group, escalate to SIGKILL after `grace`."""
    if not tp.alive():
        return
    pgid = None
    try:
        pgid = os.getpgid(tp.pid)
        os.killpg(pgid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        pass
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        if not tp.alive():
            break
        time.sleep(0.1)
    if tp.alive() and pgid is not None:
        log.warning("trainer pid=%d ignored SIGTERM; killing group", tp.pid)
        try:
            os.killpg(pgid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
    try:
        tp.proc.wait(timeout=5.0)
    except subprocess.TimeoutExpired:
        log.error("trainer pid=%d unkillable", tp.pid)
    log.info("trainer pid=%d terminated rc=%s", tp.pid, tp.returncode)
