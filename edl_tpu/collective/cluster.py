"""Pod/Cluster model: membership snapshot with JSON round-trip + equality.

Capability of the reference's cluster model (utils/cluster.py: Pod/Trainer/
Cluster with rank, endpoints, gpus, JSON round-trip, equality used for
change detection — WIP-SKELETON upstream, re-specified here).

A `Pod` is one launcher = one TPU host. A `Cluster` is the leader-published
membership snapshot: pods ordered by their *claimed* registry rank, each
assigned a dense `rank` (= jax.distributed process_id). Equality of the
pod-id set (not object identity) is the elastic change detector.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field


@dataclass
class Pod:
    pod_id: str                 # unique per launcher process
    addr: str                   # host ip
    port: int = 0               # trainer coordinator port (rank 0's is used)
    n_devices: int = 1          # local accelerator count
    claimed_rank: int = -1      # registry slot claimed via CAS
    rank: int = -1              # dense rank assigned at cluster formation

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "Pod":
        return cls(**json.loads(s))

    @property
    def endpoint(self) -> str:
        return f"{self.addr}:{self.port}"


@dataclass
class Cluster:
    job_id: str
    version: int = 0
    pods: list[Pod] = field(default_factory=list)

    def __post_init__(self):
        self.pods = [Pod(**p) if isinstance(p, dict) else p
                     for p in self.pods]

    @property
    def world_size(self) -> int:
        return len(self.pods)

    @property
    def n_devices(self) -> int:
        return sum(p.n_devices for p in self.pods)

    @property
    def coordinator(self) -> str:
        """rank-0 pod endpoint — jax.distributed coordinator address."""
        return self.pods[0].endpoint if self.pods else ""

    def pod_ids(self) -> set[str]:
        return {p.pod_id for p in self.pods}

    def rank_of(self, pod_id: str) -> int:
        for p in self.pods:
            if p.pod_id == pod_id:
                return p.rank
        return -1

    def same_membership(self, other: "Cluster | set[str]") -> bool:
        ids = other if isinstance(other, set) else other.pod_ids()
        return self.pod_ids() == ids

    def to_json(self) -> str:
        return json.dumps({"job_id": self.job_id, "version": self.version,
                           "pods": [asdict(p) for p in self.pods]},
                          sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "Cluster":
        return cls(**json.loads(s))


def form_cluster(job_id: str, version: int, pods: list[Pod]) -> Cluster:
    """Order pods by claimed rank and assign dense ranks 0..N-1."""
    ordered = sorted(pods, key=lambda p: p.claimed_rank)
    out = []
    for i, p in enumerate(ordered):
        q = Pod(**asdict(p))
        q.rank = i
        out.append(q)
    return Cluster(job_id=job_id, version=version, pods=out)
