"""Stop-resume cluster barrier: stability-gated leader-published snapshots.

Capability of the reference's edl_barrier (collective/launch.py:111-150:
pods register, rank-0 runs the barrier, everyone blocks until the world is
formed) re-designed store-native: the *leader* (live pod with the smallest
claimed rank) waits until membership has been stable for
`stable_secs`, then CAS-publishes a versioned Cluster snapshot; followers
poll until a snapshot appears that (a) has a version above the one they
last trained under and (b) contains them. No extra RPC service — the
coordination store is the only dependency, so the barrier inherits its
fault tolerance.
"""

from __future__ import annotations

import time

from edl_tpu.collective.cluster import Cluster, form_cluster
from edl_tpu.collective import register as reg
from edl_tpu.coord.store import Store
from edl_tpu.utils.exceptions import EdlBarrierError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.collective.barrier")


def read_cluster(store: Store, job_id: str) -> Cluster | None:
    rec = store.get(reg.cluster_key(job_id))
    return Cluster.from_json(rec.value) if rec else None


def cluster_barrier(store: Store, job_id: str, pod_id: str, *,
                    after_version: int = 0, min_nodes: int = 1,
                    stable_secs: float = 2.0, timeout: float = 300.0,
                    poll: float = 0.2) -> Cluster:
    """Block until a fresh Cluster containing `pod_id` is published.

    Any participant may act as leader the moment it observes itself as the
    smallest live claimed rank — leadership needs no election because the
    publish is a CAS keyed on the previous snapshot version (losers simply
    observe the winner's snapshot).
    """
    deadline = time.monotonic() + timeout
    stable_since: float | None = None
    last_membership: frozenset[str] | None = None

    while time.monotonic() < deadline:
        current = read_cluster(store, job_id)
        if (current is not None and current.version > after_version
                and pod_id in current.pod_ids()):
            live, _ = reg.live_pods(store, job_id)
            if current.same_membership({p.pod_id for p in live}):
                return current
            # Snapshot already stale (member died since publish) — keep
            # waiting; the leader will publish a successor.

        pods, _ = reg.live_pods(store, job_id)
        membership = frozenset(p.pod_id for p in pods)
        now = time.monotonic()
        if membership != last_membership:
            last_membership = membership
            stable_since = now
        i_am_leader = bool(pods) and pods[0].pod_id == pod_id
        enough = len(pods) >= min_nodes and pod_id in membership
        stable = stable_since is not None and now - stable_since >= stable_secs
        if i_am_leader and enough and stable:
            base_version = (current.version if current else 0)
            if base_version < after_version:
                base_version = after_version
            nxt = form_cluster(job_id, base_version + 1, pods)
            expect = current.to_json() if current else None
            if store.compare_and_swap(reg.cluster_key(job_id), expect,
                                      nxt.to_json()):
                log.info("leader %s published cluster v%d (%d pods)",
                         pod_id, nxt.version, nxt.world_size)
                return nxt
            # CAS lost: someone else published; loop re-reads it.
        time.sleep(poll)

    raise EdlBarrierError(
        f"barrier timeout after {timeout}s (job={job_id} pod={pod_id})")
