"""Elastic launcher: register -> barrier -> spawn trainer -> watch -> loop.

The working replacement for the reference's WIP launcher
(collective/launch.py:111-194 intent: JobEnv -> pod register/watch ->
barrier -> start_local_trainers -> on cluster change kill + re-loop) and the
ABSENT demo JobClient pair. One launcher per TPU host.

Lifecycle per generation:
  1. claim a rank slot (CAS, leased)                       register.py
  2. barrier until leader publishes a Cluster snapshot     barrier.py
  3. spawn ONE trainer process with the EDL_TPU_* env       process.py
  4. watch: membership change | lease lost | trainer exit  watcher.py
  5. stop-resume: kill trainer, go to 2 (or 1); trainer
     resumes from the latest checkpoint on the new mesh

CLI:
  python -m edl_tpu.collective.launch --store 127.0.0.1:2379 \
      --nodes-range 1:4 -- python -m my_trainer --epochs 10
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time

from edl_tpu.collective import barrier as bar
from edl_tpu.collective import migration as mig
from edl_tpu.collective import register as reg
from edl_tpu.collective.cluster import Pod
from edl_tpu.collective.job_env import (JobEnv, local_addr, trainer_environ)
from edl_tpu.collective.process import (start_trainer, release_trainer,
                                        terminate_trainer)
from edl_tpu.collective.watcher import ClusterWatcher
from edl_tpu.coord.client import StoreClient
from edl_tpu.coord.store import Store
from edl_tpu.utils import net
from edl_tpu.utils.config import describe
from edl_tpu.utils.exceptions import EdlError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.collective.launch")


def _job_complete(store: Store, job_id: str) -> bool:
    return store.get(reg.complete_key(job_id)) is not None


def launch(job: JobEnv, trainer_cmd: list[str], *, store: Store | None = None,
           max_consecutive_crashes: int = 5, poll: float = 0.5,
           n_devices: int | None = None,
           healthy_generation_secs: float = 60.0) -> int:
    """Run the elastic loop until the job completes. Returns exit code."""
    owns_store = store is None
    if store is None:
        store = StoreClient(job.store_endpoints)  # closed in the finally
    if n_devices is None:
        n_devices = max(1, job.nproc_per_node)
    # The coordinator port is stable across membership restarts (published
    # cluster snapshots embed it, so silently changing it would invalidate
    # every snapshot) and is re-picked ONLY on the release+re-claim path,
    # where the membership blip forces peers into a new generation built
    # from live records anyway.
    pod = Pod(pod_id=job.pod_id, addr=local_addr(), port=net.free_port(),
              n_devices=n_devices)
    log.info("launcher starting:\n%s", describe(job))

    register = reg.PodRegister(store, job.job_id, pod,
                               max_nodes=job.max_nodes, ttl=job.lease_ttl)
    register.claim()
    last_version = 0
    crashes = 0
    trainer = None
    watcher = None
    cluster = None
    # Donors released into their linger window (state-migration plane):
    # SIGTERM'd trainers that keep serving their sealed snapshot to the
    # re-formed world. Reaped each poll; force-killed past the deadline.
    lingering: list[list] = []  # [TrainerProc, kill_deadline]

    def _reap_lingering() -> None:
        now = time.monotonic()
        for item in list(lingering):
            tp, deadline = item
            if not tp.alive():
                lingering.remove(item)
            elif now > deadline:
                log.warning("donor pid=%d outlived its linger window; "
                            "killing group", tp.pid)
                try:
                    os.killpg(os.getpgid(tp.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                lingering.remove(item)

    try:
        while True:
            if _job_complete(store, job.job_id):
                log.info("job %s complete", job.job_id)
                return 0
            if cluster is None:
                cluster = bar.cluster_barrier(
                    store, job.job_id, pod.pod_id,
                    after_version=last_version, min_nodes=job.min_nodes,
                    stable_secs=job.barrier_stable_secs,
                    timeout=job.barrier_timeout)
                last_version = cluster.version
            if trainer is None:
                rank = cluster.rank_of(pod.pod_id)
                env = trainer_environ(cluster, pod.pod_id, job)
                trainer = start_trainer(trainer_cmd, env, job.log_dir,
                                        rank=rank)
            watcher = ClusterWatcher(store, cluster).start()
            generation_start = time.monotonic()

            restart_reason = None
            while restart_reason is None:
                time.sleep(poll)
                _reap_lingering()
                if _job_complete(store, job.job_id):
                    restart_reason = "complete"
                elif register.lost.is_set():
                    # Checked before `changed`: when our own lease expires
                    # the watcher also sees the membership blip, but the
                    # right recovery is release + re-claim, not a plain
                    # rejoin of the (stale-lease) barrier.
                    restart_reason = "lease_lost"
                elif watcher.changed.is_set():
                    restart_reason = "membership"
                elif not trainer.alive():
                    rc = trainer.returncode
                    if rc == 0:
                        # Training finished: publish completion for the
                        # other pods (idempotent put).
                        store.put(reg.complete_key(job.job_id), "1")
                        restart_reason = "complete"
                    else:
                        # A generation that trained healthily for a while
                        # breaks the "consecutive" chain: without this,
                        # isolated crashes days apart would accumulate into
                        # a spurious crash_loop abort.
                        if time.monotonic() - generation_start \
                                > healthy_generation_secs:
                            crashes = 0
                        crashes += 1
                        log.warning("trainer crashed rc=%s (%d/%d)", rc,
                                    crashes, max_consecutive_crashes)
                        if crashes >= max_consecutive_crashes:
                            restart_reason = "crash_loop"
                        else:
                            restart_reason = "crash"

            watcher.stop()
            if restart_reason == "membership" and job.resize_p2p \
                    and trainer.alive():
                # Live migration path: re-form the world FIRST (our rank
                # claim is still held, the trainer keeps training), then
                # let the running trainer adopt the new generation in
                # place — no respawn, no re-import, no restore. Its
                # reform watcher follows the leader-published cluster;
                # we only wait for the "adopted" ack.
                cluster = bar.cluster_barrier(
                    store, job.job_id, pod.pod_id,
                    after_version=last_version, min_nodes=job.min_nodes,
                    stable_secs=job.barrier_stable_secs,
                    timeout=job.barrier_timeout)
                last_version = cluster.version
                if cluster.rank_of(pod.pod_id) >= 0 and mig.wait_adopted(
                        store, job.job_id, pod.pod_id, cluster.version,
                        timeout=job.adopt_timeout_secs,
                        is_alive=trainer.alive):
                    log.info("trainer pid=%d adopted cluster v%d in "
                             "place", trainer.pid, cluster.version)
                    crashes = 0
                    continue  # same trainer; fresh watcher at loop top
                # Adoption unavailable (trainer without the migration
                # service, or it stalled): stop-resume — but keep the
                # old trainer alive as a DONOR so the replacement can
                # restore its state from memory instead of disk.
                log.info("in-place adoption unavailable — stop-resume "
                         "with donor linger (pid=%d)", trainer.pid)
                release_trainer(trainer)
                lingering.append([trainer,
                                  time.monotonic()
                                  + job.donor_linger_secs + 5.0])
                trainer = None
                crashes = 0
                continue  # cluster already re-formed: respawn directly
            terminate_trainer(trainer)
            trainer = None
            cluster = None
            if restart_reason == "complete":
                return 0
            if restart_reason == "crash_loop":
                log.error("aborting after %d consecutive crashes", crashes)
                return 1
            if restart_reason == "membership":
                crashes = 0
            if restart_reason in ("lease_lost", "crash"):
                # Re-form the world without us first: drop our claim so the
                # surviving pods' watchers fire, then re-claim. This is how
                # a local trainer failure propagates into a global
                # stop-resume (reference: pod exit -> etcd TTL drain, with a
                # deliberate 15s sleep > TTL before rejoin). The gap must
                # stay open longer than the peers' watch poll interval or
                # they miss the blip; peers that still miss it catch the new
                # generation via the watcher's cluster-version check.
                register.release()
                time.sleep(job.rejoin_delay_secs)
                # Safe point to re-pick the coordinator port (it may still
                # be held by the dying trainer): we are absent from the
                # registry, so no snapshot can embed the old value, and the
                # blip forces a new generation from live records.
                pod.port = net.free_port()
                register = reg.PodRegister(store, job.job_id, pod,
                                           max_nodes=job.max_nodes,
                                           ttl=job.lease_ttl)
                register.claim()
    except EdlError as exc:
        log.error("launcher failed: %s", exc)
        return 2
    finally:
        if watcher is not None:
            watcher.stop()
        if trainer is not None:
            if job.resize_p2p:
                # Shrink/shutdown: the trainer converts SIGTERM into a
                # graceful stop and lingers as a donor (own session, so
                # it survives this launcher) — exactly how a shrink
                # victim's shards outlive its own eviction. Its linger
                # is self-bounded; releasing the claim below lets it
                # exit early when nobody is left to serve.
                release_trainer(trainer)
            else:
                terminate_trainer(trainer)
        register.release()
        if owns_store:
            try:
                store.close()
            except Exception:  # noqa: BLE001 — teardown
                pass
    return 0


def parse_args(argv=None) -> tuple[JobEnv, list[str]]:
    parser = argparse.ArgumentParser(
        prog="edl_tpu.collective.launch",
        description="Elastic TPU job launcher (flag else EDL_TPU_* env)")
    parser.add_argument("--job-id", default=None)
    parser.add_argument("--pod-id", default=None)
    parser.add_argument("--store", dest="store_endpoints", default=None,
                        help="coordination store endpoint host:port")
    parser.add_argument("--nodes-range", default=None, help="min:max")
    parser.add_argument("--nproc-per-node", type=int, default=None)
    parser.add_argument("--slices", dest="slices", type=int, default=None,
                        help="TPU slice count for hybrid ICIxDCN meshes "
                             "(0 = auto-detect from the hardware; >1 "
                             "partitions pods rank-contiguously and "
                             "trainers place dp across DCN)")
    parser.add_argument("--checkpoint-path", default=None)
    parser.add_argument("--log-dir", default=None)
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="-- trainer command line")
    args = parser.parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("missing trainer command (after --)")
    overrides = {k: v for k, v in vars(args).items()
                 if k != "cmd" and v is not None}
    return JobEnv.from_environ(**overrides), cmd


def _raise_exit(signum, frame):
    raise SystemExit(128 + signum)


def main(argv=None) -> int:
    # A JobClient shrink (or operator Ctrl-C on a remote shell) delivers
    # SIGTERM to the launcher only — the trainer runs in its own session.
    # Convert it to SystemExit so launch()'s finally block kills the trainer
    # tree and releases the rank claim instead of orphaning a trainer that
    # keeps writing checkpoints against a stale world.
    signal.signal(signal.SIGTERM, _raise_exit)
    job, cmd = parse_args(argv)
    return launch(job, cmd)


if __name__ == "__main__":
    sys.exit(main())
