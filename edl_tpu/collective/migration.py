"""Peer-to-peer live state migration: the resize path without the disk.

The stop-resume recipe (checkpoint -> kill world -> re-form -> restore
from disk) pays the full respawn + deserialize price on every membership
change. This plane converts the checkpoint plane from the hot path into
the safety net:

- every trainer under the elastic launcher runs a **donor server**: the
  newest SEALED checkpoint snapshot (the async-checkpoint plane's
  retained host-side copy — no extra device->host transfer) is served
  chunk-by-chunk over the zero-copy binary tensor wire
  (data/tensor_wire.py gather-send);
- a (re)starting trainer **restores from peers**: donor manifests are
  merged into the same self-describing chunk index the on-disk sharded
  format uses, and the cross-mesh resharding planner
  (train/sharded_checkpoint.restore_from_index) assembles the target
  state from parallel region fetches — saved-world and restore-world
  shapes stay independent;
- **surviving** trainers never restart at all: a reform watcher follows
  the leader-published cluster generation, and on a resize that keeps
  this pod the TrainLoop adopts the new (rank, world) in place — no
  respawn, no re-import, no re-jit, no restore. Downtime collapses to
  one step boundary;
- **disk remains the fallback** whenever peers cannot serve: no live
  donors (total-world kill), donors staler than the local disk (epoch
  fencing), or a donor dying mid-transfer all raise `PeerRestoreError`
  and the caller falls back to `CheckpointManager.restore`.

Store key layout (all under the job scope):

    /{job}/migration/donors/{pod_id}  donor advert JSON, leased
                                      {pod_id, addr, port, version, step,
                                       generation, nbytes}
    /{job}/migration/epoch            resize epoch doc, published by the
                                      JobServer's /resize (fencing +
                                      audit): {epoch, ts, from, desired,
                                      donors}
    /{job}/migration/ack/{pod_id}     restore/adoption ack {ts, mode:
                                      peers|disk|adopted, version,
                                      generation, downtime_s, bytes}

``EDL_TPU_RESIZE_P2P=0`` is the escape hatch back to pure stop-resume.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Callable

import numpy as np

from edl_tpu.coord.store import Store
from edl_tpu.obs import recorder as flight
from edl_tpu.obs import trace
from edl_tpu.train.ckpt_io import chunk_crc32, verify_enabled
from edl_tpu.utils import config
from edl_tpu.data.tensor_wire import (TensorWireError, recv_tensors,
                                         send_tensors)
from edl_tpu.utils.exceptions import EdlError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.collective.migration")


class PeerRestoreError(EdlError):
    """Peer restore is unavailable/failed — caller falls back to disk."""


# -- key layout -------------------------------------------------------------

def donors_prefix(job_id: str) -> str:
    return f"/{job_id}/migration/donors/"


def donor_key(job_id: str, pod_id: str) -> str:
    return f"/{job_id}/migration/donors/{pod_id}"


def epoch_key(job_id: str) -> str:
    return f"/{job_id}/migration/epoch"


def ack_prefix(job_id: str) -> str:
    return f"/{job_id}/migration/ack/"


def ack_key(job_id: str, pod_id: str) -> str:
    return f"/{job_id}/migration/ack/{pod_id}"


def p2p_enabled(environ=None) -> bool:
    if environ is None:
        return config.env_flag("EDL_TPU_RESIZE_P2P", True)
    return environ.get("EDL_TPU_RESIZE_P2P", "1") != "0"


def live_donors(store: Store, job_id: str) -> list[dict]:
    """Parsed donor adverts currently alive (leased keys)."""
    records, _ = store.get_prefix(donors_prefix(job_id))
    out = []
    for rec in records:
        try:
            out.append(json.loads(rec.value))
        except json.JSONDecodeError:
            continue
    return out


# -- donor server -----------------------------------------------------------

class MigrationServer:
    """Serve the retained sealed snapshot to peers over the tensor wire.

    Protocol (one framed request -> one framed reply, pipelined per
    connection):

      {op: "manifest"} -> meta {version, status, process_index, leaves}
      {op: "fetch", files: [...]} -> tensors {fname: chunk}, meta
                                     {version}

    Requests against a donor that holds no snapshot (or an unknown
    chunk) get an ``error`` meta instead of a dropped connection, so the
    restorer can distinguish "donor not ready" from "donor died".
    """

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.port = self._sock.getsockname()[1]
        self._lock = threading.Lock()
        self._snap: dict | None = None            # guarded-by: _lock
        self._stop = threading.Event()
        self._conns: set[socket.socket] = set()   # guarded-by: _lock
        self._accept = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="edl-migrate-srv")
        self._accept.start()

    def publish(self, snapshot: dict) -> None:
        """Swap in a newer sealed snapshot (serve-ready view from
        CheckpointManager.sealed_snapshot). In-flight fetches keep their
        reference to the old one — snapshots are immutable once
        published, so a swap can never tear a transfer."""
        with self._lock:
            self._snap = snapshot

    def snapshot(self) -> dict | None:
        with self._lock:
            return self._snap

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            with self._lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="edl-migrate-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not self._stop.is_set():
                meta, _ = recv_tensors(conn)
                # trace seam: a fetch sent under a restore span carries
                # its context in the meta — the donor-side serve work
                # shows up inside the SAME resize trace
                ctx = trace.extract(meta)
                if ctx is not None:
                    with trace.span(f"migrate.serve_{meta.get('op')}",
                                    parent=ctx):
                        self._handle(conn, meta)
                else:
                    self._handle(conn, meta)
        except (TensorWireError, OSError):
            pass  # peer done / donor stopping
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, conn: socket.socket, meta: dict) -> None:
        # overridable seam: tests subclass this to model a donor dying
        # mid-transfer (manifest served, fetch drops the connection)
        snap = self.snapshot()
        op = meta.get("op")
        if snap is None:
            send_tensors(conn, {"error": "donor holds no sealed snapshot"})
            return
        if op == "manifest":
            send_tensors(conn, {"op": "manifest",
                                "version": snap["version"],
                                "status": snap["status"],
                                "process_index": snap["process_index"],
                                "leaves": snap["leaves"]})
        elif op == "fetch":
            names = meta.get("files") or []
            missing = [n for n in names if n not in snap["chunks"]]
            if missing:
                send_tensors(conn, {"error": f"unknown chunks {missing}"})
                return
            send_tensors(conn, {"op": "fetch", "version": snap["version"]},
                         {n: snap["chunks"][n] for n in names})
        else:
            send_tensors(conn, {"error": f"unknown op {op!r}"})

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


# -- peer restore -----------------------------------------------------------

def _connect(advert: dict, timeout: float) -> socket.socket:
    sock = socket.create_connection((advert["addr"], int(advert["port"])),
                                    timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def _fetch_manifest(advert: dict, timeout: float) -> dict:
    with _connect(advert, timeout) as sock:
        send_tensors(sock, {"op": "manifest"})
        meta, _ = recv_tensors(sock)
    if "error" in meta:
        raise TensorWireError(meta["error"])
    return meta


class _PeerChunks:
    """Chunk source for `restore_from_index` backed by donor fetches.

    One connection per (donor, reader thread); each chunk is fetched
    exactly once per restore and cached, mirroring the on-disk
    `_ChunkFiles` handle cache."""

    def __init__(self, owners: dict[str, dict], timeout: float,
                 expect_version: int | None = None,
                 crcs: dict[str, int] | None = None):
        self.owners = owners            # chunk fname -> donor advert
        self.timeout = timeout
        # version fence: a donor sealing a NEWER snapshot mid-restore
        # must not mix steps into the assembled state
        self.expect_version = expect_version
        # integrity fence: chunk crc32s from the donor manifests — a
        # chunk garbled on the wire (or served torn) fails here and the
        # whole peer restore falls back instead of assembling garbage
        self.crcs = crcs or {}
        self._verify = verify_enabled()
        self._cache: dict[str, np.ndarray] = {}
        self._cache_lock = threading.Lock()
        self._inflight: dict[str, threading.Lock] = {}
        self._local = threading.local()
        self._all_socks: list[socket.socket] = []
        self._socks_lock = threading.Lock()
        self.bytes_fetched = 0
        # fetches run on restore_from_index's reader POOL threads —
        # thread-local trace context does not cross, so the restore
        # span is captured here and passed as each fetch's explicit
        # parent (and rides the tensor-wire meta to the donor)
        self._trace_parent = trace.current()

    def _sock_for(self, advert: dict) -> socket.socket:
        pool = getattr(self._local, "socks", None)
        if pool is None:
            pool = self._local.socks = {}
        key = (advert["addr"], advert["port"])
        sock = pool.get(key)
        if sock is None:
            sock = pool[key] = _connect(advert, self.timeout)
            with self._socks_lock:
                self._all_socks.append(sock)
        return sock

    def load(self, fname: str) -> np.ndarray:
        # per-chunk single-flight: two reader threads planning regions
        # that intersect the same chunk must not both pull it over the
        # wire (each chunk crosses once, like the mmap handle cache)
        with self._cache_lock:
            arr = self._cache.get(fname)
            if arr is not None:
                return arr
            flight = self._inflight.setdefault(fname, threading.Lock())
        with flight:
            with self._cache_lock:
                arr = self._cache.get(fname)
            if arr is not None:
                return arr
            return self._fetch(fname)

    def _fetch(self, fname: str) -> np.ndarray:
        advert = self.owners.get(fname)
        if advert is None:
            raise PeerRestoreError(f"no donor owns chunk {fname}")
        sock = self._sock_for(advert)
        with trace.span("migrate.fetch", parent=self._trace_parent,
                        attrs={"file": fname,
                               "donor": advert.get("pod_id")}) as sp:
            send_tensors(sock, {"op": "fetch", "files": [fname]})
            meta, tensors = recv_tensors(sock)
            if sp is not None and fname in tensors:
                sp.attrs["bytes"] = int(tensors[fname].nbytes)
        if "error" in meta or fname not in tensors:
            raise PeerRestoreError(
                f"donor {advert.get('pod_id')} failed serving {fname}: "
                f"{meta.get('error', 'chunk missing from reply')}")
        if self.expect_version is not None \
                and int(meta.get("version", -1)) != self.expect_version:
            raise PeerRestoreError(
                f"donor {advert.get('pod_id')} moved to version "
                f"{meta.get('version')} mid-restore (wanted "
                f"{self.expect_version})")
        arr = tensors[fname]
        expect = self.crcs.get(fname)
        if self._verify and expect is not None:
            got = chunk_crc32(arr)
            if got != expect:
                raise PeerRestoreError(
                    f"chunk {fname} from donor {advert.get('pod_id')} "
                    f"failed integrity check (crc32 {got:#010x} != "
                    f"manifest {expect:#010x})")
        with self._cache_lock:
            self._cache[fname] = arr
            self.bytes_fetched += arr.nbytes
        return arr

    def close(self) -> None:
        with self._socks_lock:
            socks, self._all_socks = self._all_socks, []
        for sock in socks:
            try:
                sock.close()
            except OSError:
                pass


def resize_trace_ctx(store: Store, job_id: str) -> tuple[str, str] | None:
    """The span context the last served resize embedded in its epoch
    doc (publish_resize_epoch) — how a trainer that learns of a resize
    asynchronously joins the decision's trace. None when tracing is
    off, there is no epoch doc, or it carries no context."""
    if not trace.enabled():
        return None
    try:
        rec = store.get(epoch_key(job_id))
        if rec is None:
            return None
        return trace.parse_context(json.loads(rec.value).get("trace"))
    except Exception:  # noqa: BLE001 — observability only
        return None


def restore_from_peers(store: Store, job_id: str, target: Any, *,
                       local_version: int | None = None,
                       threads: int | None = None,
                       timeout: float = 5.0,
                       pods: list[str] | None = None
                       ) -> tuple[Any, Any, dict]:
    """Assemble ``target``'s state from live donor snapshots (traced:
    the restore runs as a ``resize.restore_peers`` span parented onto
    the resize that caused it, with per-chunk fetch child spans).
    ``pods`` restricts the donor set — the reform state machine's
    survivor restores its OWN just-sealed shards this way (per-pod
    checkpoint version counters are not comparable across pods, so an
    unfiltered merge could interleave states from different steps)."""
    with trace.span("resize.restore_peers",
                    parent=resize_trace_ctx(store, job_id),
                    attrs={"job": job_id}) as sp:
        state, status, stats = _restore_from_peers(
            store, job_id, target, local_version=local_version,
            threads=threads, timeout=timeout, pods=pods)
        if sp is not None:
            sp.attrs.update({k: stats[k] for k in
                             ("version", "bytes_from_peers", "restore_s")})
        flight.record("peer_restore", job_id=job_id,
                      version=stats["version"],
                      bytes_from_peers=stats["bytes_from_peers"],
                      restore_s=stats["restore_s"])
        return state, status, stats


def _restore_from_peers(store: Store, job_id: str, target: Any, *,
                        local_version: int | None = None,
                        threads: int | None = None,
                        timeout: float = 5.0,
                        pods: list[str] | None = None
                        ) -> tuple[Any, Any, dict]:
    """Assemble ``target``'s state from live donor snapshots.

    Donor adverts are read from the store, the newest advertised version
    wins, and manifests are merged into one chunk index — exactly the
    cross-mesh resharding plan a disk restore builds from index files,
    so peer- and disk-restored states are bitwise identical. ``local_
    version`` is the epoch fence: when this pod's own disk already holds
    a NEWER sealed version than any donor (e.g. every donor died and
    came back stale), peers are refused and the caller restores from
    disk instead.

    Returns ``(state, TrainStatus, stats)``; raises `PeerRestoreError`
    on any condition where disk is the right path.
    """
    from edl_tpu.train import sharded_checkpoint as sc
    from edl_tpu.train.state import TrainStatus

    adverts = live_donors(store, job_id)
    if pods is not None:
        adverts = [a for a in adverts if a.get("pod_id") in pods]
    if not adverts:
        raise PeerRestoreError(
            "no live donors advertised" if pods is None else
            f"no live donors among {pods}")
    # The advert is DISCOVERY only — the manifest carries the live
    # sealed version (adverts refresh off-thread and may lag a seal).
    manifests: dict[str, dict] = {}
    owners: dict[str, dict] = {}
    by_version: dict[int, list[tuple[dict, dict]]] = {}
    for advert in adverts:
        try:
            man = _fetch_manifest(advert, timeout)
        except (OSError, TensorWireError) as exc:
            log.warning("donor %s unreachable for manifest: %s",
                        advert.get("pod_id"), exc)
            continue
        by_version.setdefault(int(man["version"]), []).append((advert, man))
    if not by_version:
        raise PeerRestoreError("all donors unreachable")
    # Donors may straddle a seal; the newest consistent group wins
    # (mixing versions would interleave states from different steps).
    chosen = max(by_version)
    if local_version is not None and local_version > chosen:
        # Epoch fence: a stale donor never beats this pod's own newer
        # sealed checkpoint (e.g. the whole world died and one donor
        # came back serving an old snapshot).
        raise PeerRestoreError(
            f"donors stale: best peer version {chosen} < local disk "
            f"version {local_version}")
    for advert, man in by_version[chosen]:
        manifests[advert.get("pod_id", advert["addr"])] = man
        for leaf in man["leaves"]:
            for chunk in leaf["chunks"]:
                owners.setdefault(chunk["file"], advert)
    merged = sc.merge_leaf_tables([m["leaves"] for m in manifests.values()])
    source = _PeerChunks(owners, timeout, expect_version=chosen,
                         crcs=sc.checksum_map(merged))
    t0 = time.perf_counter()
    try:
        state = sc.restore_from_index(merged, source.load, target, threads)
    except PeerRestoreError:
        raise
    except Exception as exc:  # noqa: BLE001 — donor death mid-transfer,
        # coverage holes, wire errors: all mean "go restore from disk"
        raise PeerRestoreError(f"peer fetch failed: {exc}") from exc
    finally:
        source.close()
    status = TrainStatus.from_dict(
        next(iter(manifests.values()))["status"])
    stats = {"version": chosen,
             "bytes_from_peers": source.bytes_fetched,
             "donors": sorted(manifests),
             "restore_s": round(time.perf_counter() - t0, 4)}
    log.info("restored v%d from %d peer(s) in %.3fs (%.1f MB over the "
             "wire)", chosen, len(manifests), stats["restore_s"],
             source.bytes_fetched / 2**20)
    return state, status, stats


# -- trainer-side service ---------------------------------------------------

class Reform:
    """A pending in-place adoption: the new cluster still contains us."""

    def __init__(self, cluster, rank: int, world_size: int):
        self.cluster = cluster
        self.rank = rank
        self.world_size = world_size
        self.generation = cluster.version


class MigrationService:
    """Everything a trainer process contributes to the migration plane.

    - serves its retained sealed snapshot (attach() wires a
      CheckpointManager's retention hook to the donor server + a leased
      store advert, refreshed off-thread);
    - watches the leader-published cluster generation so the TrainLoop
      can adopt a resize in place (`poll_reform`);
    - converts SIGTERM into a *graceful* stop (`stop_requested`) and, on
      shutdown, lingers as a donor until the re-formed world has acked
      its restores (or a bounded deadline) — how a shrink victim's
      shards survive its own eviction.
    """

    def __init__(self, store: Store, job_id: str, pod_id: str, *,
                 generation: int = 0, ttl: float = 15.0,
                 linger_s: float = 10.0, addr: str | None = None,
                 owns_store: bool = False):
        from edl_tpu.collective.job_env import local_addr
        self.store = store
        self.job_id = job_id
        self.pod_id = pod_id
        self.ttl = ttl
        self.linger_s = linger_s
        self.addr = addr or local_addr()
        self.generation = generation
        self._owns_store = owns_store
        self.server = MigrationServer()
        self.stop_requested = threading.Event()
        self._stop_ts: float | None = None
        self._lease: int | None = None
        self._keeper = None
        self._advert_dirty = threading.Event()
        self._advert_doc: dict | None = None  # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._advert_thread: threading.Thread | None = None
        # reform watch
        self._reform: Reform | None = None    # guarded-by: _lock
        self._watch_thread: threading.Thread | None = None
        self._reform_watch = None
        self._ckpt = None

    # -- construction ------------------------------------------------------

    @classmethod
    def from_env(cls, ckpt=None) -> "MigrationService | None":
        """Build from the launcher's trainer env; None when p2p is
        disabled, the trainer runs standalone, or the store is down."""
        if not p2p_enabled():
            return None
        if not config.env_present("EDL_TPU_RANK"):
            return None  # not under the elastic launcher
        endpoints = config.env_str("EDL_TPU_STORE_ENDPOINTS", "") or ""
        job_id = config.env_str("EDL_TPU_JOB_ID", "") or ""
        pod_id = config.env_str("EDL_TPU_POD_ID", "") or ""
        if not (endpoints and job_id and pod_id):
            return None
        from edl_tpu.coord.redis_store import connect_store
        try:
            store = connect_store(endpoints.split(",")[0])
        except Exception as exc:  # noqa: BLE001 — plane is optional
            log.warning("migration service disabled (store unreachable: "
                        "%s)", exc)
            return None
        svc = cls(store, job_id, pod_id,
                  generation=config.env_int("EDL_TPU_CLUSTER_VERSION", 0),
                  linger_s=config.env_float("EDL_TPU_DONOR_LINGER", 10.0),
                  owns_store=True)
        if ckpt is not None:
            svc.attach(ckpt)
        svc.start_reform_watch()
        svc.install_sigterm()
        return svc

    def attach(self, ckpt) -> None:
        """Wire a CheckpointManager's sealed-snapshot retention into the
        donor server: every sealed save republishes the serve-ready view
        and refreshes the leased advert (off the saving thread)."""
        self._ckpt = ckpt
        ckpt.retain_sealed = True
        ckpt.on_sealed = self._on_sealed
        existing = ckpt.sealed_snapshot()
        if existing is not None:
            self._on_sealed()

    # -- donor advertising -------------------------------------------------

    def _on_sealed(self) -> None:
        snap = self._ckpt.sealed_snapshot() if self._ckpt else None
        if snap is None:
            return
        self.server.publish(snap)
        from edl_tpu.train.sharded_checkpoint import snapshot_nbytes
        # as-stored bytes: a state with quantized resident moments
        # (train/fused_opt.py) adverts — and serves — the int8 planes,
        # so joiners budget the real wire cost, ~2x under the fp32 one
        doc = {"pod_id": self.pod_id, "addr": self.addr,
               "port": self.server.port,
               "version": snap["version"],
               "step": (snap["status"] or {}).get("step"),
               "generation": self.generation,
               "nbytes": snapshot_nbytes(snap),
               "ts": time.time()}
        with self._lock:
            self._advert_doc = doc
            if self._advert_thread is None:
                self._advert_thread = threading.Thread(
                    target=self._advert_loop, daemon=True,
                    name="edl-migrate-advert")
                self._advert_thread.start()
        self._advert_dirty.set()

    def _advert_loop(self) -> None:
        while not self._stop.is_set():
            if not self._advert_dirty.wait(timeout=0.2):
                continue
            self._advert_dirty.clear()
            with self._lock:
                doc = self._advert_doc
            if doc is None:
                continue
            try:
                self.store.put(donor_key(self.job_id, self.pod_id),
                               json.dumps(doc, sort_keys=True),
                               lease=self._ensure_lease())
            except Exception as exc:  # noqa: BLE001 — best-effort: a
                # failed advert only hides this donor from peers
                log.warning("donor advert publish failed: %s", exc)
                self._lease = None

    def flush_advert(self) -> bool:
        """Publish the donor advert for the current sealed snapshot NOW,
        on the calling thread (the off-thread advert loop's cadence is
        fine for steady-state serving but the reform quiesce phase needs
        its fresh seal discoverable before peer-restore starts). False
        when there is nothing to advertise or the put failed."""
        self._on_sealed()
        with self._lock:
            doc = self._advert_doc
        if doc is None:
            return False
        try:
            self.store.put(donor_key(self.job_id, self.pod_id),
                           json.dumps(doc, sort_keys=True),
                           lease=self._ensure_lease())
            return True
        except Exception as exc:  # noqa: BLE001 — best-effort, the
            # advert loop retries; the caller falls back to disk
            log.warning("synchronous donor advert failed: %s", exc)
            return False

    def _ensure_lease(self) -> int:
        if self._lease is not None and self._keeper is not None \
                and not self._keeper.lost.is_set():
            return self._lease
        from edl_tpu.coord.client import LeaseKeeper
        if self._keeper is not None:
            self._keeper.stop(revoke=False)
        self._lease = self.store.lease_grant(self.ttl)
        self._keeper = LeaseKeeper(self.store, self._lease,
                                   interval=self.ttl / 6.0).start()
        return self._lease

    # -- reform watch (in-place adoption) ----------------------------------

    def start_reform_watch(self, interval: float = 0.3) -> None:
        if self._watch_thread is not None:
            return
        self._watch_thread = threading.Thread(
            target=self._watch_loop, args=(interval,), daemon=True,
            name="edl-migrate-reform")
        self._watch_thread.start()

    def _watch_loop(self, interval: float) -> None:
        from edl_tpu.collective import register as reg
        from edl_tpu.collective.cluster import Cluster
        from edl_tpu.coord.store import try_watch, watch_resync_interval
        # Event-driven: wake on the leader's cluster-snapshot PUT so an
        # in-place adoption starts at event latency (the 0.061s p2p
        # resize path stops waiting out a poll tick); the fixed poll
        # survives as the resync net / EDL_TPU_COORD_WATCH=0 fallback.
        key = reg.cluster_key(self.job_id)
        watch = try_watch(self.store, key)
        with self._lock:
            self._reform_watch = watch
        wait = interval if watch is None \
            else watch_resync_interval(default=max(interval * 10, 10.0))
        parsed_revision = -1
        first = True
        while not self._stop.is_set():
            if first:
                first = False  # a reform published BEFORE the watch
                # existed has no event: check once immediately
            elif watch is not None:
                watch.get(timeout=wait)  # event or resync tick
                if self._stop.is_set():
                    return
            elif self._stop.wait(interval):
                return
            try:
                rec = self.store.get(key)
            except Exception as exc:  # noqa: BLE001 — transient store
                log.debug("reform watch poll failed: %s", exc)
                continue
            if rec is None or rec.revision == parsed_revision:
                continue
            parsed_revision = rec.revision
            try:
                cluster = Cluster.from_json(rec.value)
            except (ValueError, TypeError):
                continue
            if cluster.version <= self.generation:
                continue
            rank = cluster.rank_of(self.pod_id)
            if rank < 0:
                # evicted from the new world: nothing to adopt — the
                # launcher's SIGTERM drives the graceful donor path
                continue
            with self._lock:
                self._reform = Reform(cluster, rank, cluster.world_size)

    def poll_reform(self) -> Reform | None:
        """The newest pending adoption (cleared by `adopted`)."""
        with self._lock:
            return self._reform

    def adopted(self, reform: Reform) -> None:
        """Mark `reform` consumed and re-stamp this donor's generation
        (newer pending reforms survive the clear)."""
        with self._lock:
            self.generation = reform.generation
            if self._reform is not None \
                    and self._reform.generation <= reform.generation:
                self._reform = None
        # refresh the advert's generation so peers can correlate
        self._advert_dirty.set()

    # -- acks --------------------------------------------------------------

    def live_generation(self) -> int | None:
        """The cluster generation the leader has published (the epoch
        authority adoption acks are fenced against); None when the doc
        is unreadable — fencing then degrades open, the launcher-side
        `wait_adopted` generation check is the second fence."""
        from edl_tpu.collective import register as reg
        from edl_tpu.collective.cluster import Cluster
        try:
            rec = self.store.get(reg.cluster_key(self.job_id))
            if rec is None:
                return None
            return Cluster.from_json(rec.value).version
        except Exception:  # noqa: BLE001 — transient store error
            return None

    def ack(self, mode: str, *, version: int | None = None,
            downtime_s: float | None = None, bytes_from_peers: int = 0,
            restore_s: float | None = None, generation: int | None = None,
            reform: dict | None = None) -> bool:
        """Record that this pod is trained-and-running in the current
        generation (written AFTER the first post-restore/post-adoption
        step): what lingering donors key their early exit on, and what
        the demo/bench read the measured downtime from.

        Adoption acks are **generation-fenced**: a survivor that
        finished reforming into generation G while the leader has
        already published G' > G is half-reformed against a dead world
        — its ack BOUNCES (False, nothing written, flight-recorded)
        instead of convincing the launcher that a torn world is
        healthy. `wait_adopted` independently requires generation >=
        the awaited one, so both halves of the fence must agree before
        an adoption counts."""
        gen = self.generation if generation is None else generation
        if mode == "adopted":
            live = self.live_generation()
            if live is not None and live > gen:
                log.warning("stale adoption ack bounced: generation %d "
                            "< live cluster generation %d", gen, live)
                flight.record("reform", who=self.pod_id, stale_ack=True,
                              generation=gen, live_generation=live)
                return False
        doc = {"pod_id": self.pod_id, "mode": mode, "ts": time.time(),
               "pid": os.getpid(),
               "generation": gen, "version": version,
               "downtime_s": downtime_s,
               "bytes_from_peers": int(bytes_from_peers),
               "restore_s": restore_s}
        if reform is not None:
            doc["reform"] = reform
        try:
            self.store.put(ack_key(self.job_id, self.pod_id),
                           json.dumps(doc, sort_keys=True))
            return True
        except Exception as exc:  # noqa: BLE001 — observability only
            log.warning("migration ack failed: %s", exc)
            return False

    # -- restore (consumer side) -------------------------------------------

    def restore_from_peers(self, target: Any, *,
                           local_version: int | None = None,
                           threads: int | None = None,
                           pods: list[str] | None = None):
        return restore_from_peers(self.store, self.job_id, target,
                                  local_version=local_version,
                                  threads=threads, pods=pods)

    # -- lifecycle ---------------------------------------------------------

    def install_sigterm(self) -> None:
        """Convert SIGTERM into a graceful stop: the TrainLoop finishes
        its step, drains the last snapshot, then lingers as a donor.
        No-op off the main thread (signal API restriction)."""
        import signal as _signal

        def _handler(signum, frame):
            self._stop_ts = time.time()
            self.stop_requested.set()
        try:
            _signal.signal(_signal.SIGTERM, _handler)
        except ValueError:  # not the main thread
            log.debug("SIGTERM handler not installed (non-main thread)")

    def _linger(self) -> None:
        """Serve until the re-formed world acked or the deadline passes.

        Early exits: every live rank claim has a fresh ack (the new
        world is fully up), or there are no live claims at all (nobody
        left to serve — e.g. the whole job is shutting down)."""
        from edl_tpu.collective import register as reg
        since = self._stop_ts or time.time()
        deadline = time.monotonic() + self.linger_s
        log.info("donor linger: serving peers up to %.1fs", self.linger_s)
        while time.monotonic() < deadline:
            try:
                claims, _ = self.store.get_prefix(
                    reg.ranks_prefix(self.job_id))
                acks, _ = self.store.get_prefix(ack_prefix(self.job_id))
            except Exception:  # noqa: BLE001 — store gone: stop serving
                return
            fresh = 0
            for rec in acks:
                try:
                    if float(json.loads(rec.value).get("ts", 0)) >= since:
                        fresh += 1
                except (ValueError, TypeError):
                    continue
            if not claims:
                return
            if fresh >= len(claims):
                log.info("donor linger: %d/%d fresh acks — done", fresh,
                         len(claims))
                return
            time.sleep(0.3)

    def shutdown(self, linger: bool | None = None) -> None:
        """Stop serving. ``linger`` defaults to 'only when a graceful
        stop was requested and we hold something worth serving'."""
        if linger is None:
            linger = (self.stop_requested.is_set()
                      and self.server.snapshot() is not None)
        if linger:
            try:
                self._linger()
            except Exception:  # noqa: BLE001 — teardown must finish
                log.exception("donor linger failed")
        self._stop.set()
        self.server.stop()
        with self._lock:
            reform_watch = self._reform_watch
            self._reform_watch = None
        if reform_watch is not None:
            reform_watch.cancel()  # wakes the blocked event wait
        for t in (self._advert_thread, self._watch_thread):
            if t is not None:
                t.join(timeout=2.0)
        self._advert_thread = self._watch_thread = None
        if self._ckpt is not None:
            self._ckpt.on_sealed = None
        if self._keeper is not None:
            self._keeper.stop(revoke=True)
            self._keeper = None
            self._lease = None
        if self._owns_store:
            self._owns_store = False
            try:
                self.store.close()
            except Exception:  # noqa: BLE001 — teardown
                pass


# -- launcher-side helpers --------------------------------------------------

def wait_adopted(store: Store, job_id: str, pod_id: str, generation: int,
                 timeout: float, poll: float = 0.2,
                 is_alive: Callable[[], bool] | None = None) -> bool:
    """Launcher side of in-place adoption: block until this pod's
    trainer acked generation >= `generation` (True), the trainer died,
    or the timeout passed (False -> fall back to stop-resume). Wakes on
    the ack key's PUT event when the store serves watches (the check
    itself stays poll-shaped so EDL_TPU_COORD_WATCH=0 is identical)."""
    from edl_tpu.coord.store import try_watch
    watch = try_watch(store, ack_key(job_id, pod_id))
    deadline = time.monotonic() + timeout
    try:
        while time.monotonic() < deadline:
            if is_alive is not None and not is_alive():
                return False
            try:
                rec = store.get(ack_key(job_id, pod_id))
            except Exception:  # noqa: BLE001 — transient store error
                rec = None
            if rec is not None:
                try:
                    doc = json.loads(rec.value)
                    if doc.get("mode") == "adopted" \
                            and int(doc.get("generation") or 0) >= generation:
                        return True
                except (ValueError, TypeError):
                    pass
            remaining = deadline - time.monotonic()
            if watch is not None:
                # the ack PUT wakes us instantly; the bounded timeout
                # keeps the is_alive check fresh
                watch.get(timeout=max(0.0, min(0.5, remaining)))
            else:
                time.sleep(max(0.0, min(poll, remaining)))
        return False
    finally:
        if watch is not None:
            watch.cancel()


def publish_resize_epoch(store: Store, job_id: str, *, epoch: int,
                         desired: int, prev: int | None = None) -> dict:
    """JobServer /resize hook: stamp a monotonic migration epoch with
    the donor roster alive at the decision instant — the fencing +
    audit record the demo and docs key on."""
    with trace.span("resize.publish_epoch",
                    attrs={"job": job_id, "epoch": int(epoch),
                           "desired": int(desired)}):
        roster = [{k: d.get(k) for k in ("pod_id", "addr", "port",
                                         "version", "generation")}
                  for d in live_donors(store, job_id)]
        doc = {"epoch": int(epoch), "ts": time.time(), "from": prev,
               "desired": int(desired), "donors": roster}
        # Trace hop: the epoch doc carries the publication span's
        # context, so trainers that adopt/restore off this resize join
        # its trace even though they learn of it asynchronously
        # through the store.
        ctx = trace.inject()
        if ctx is not None:
            doc["trace"] = ctx
        store.put(epoch_key(job_id), json.dumps(doc, sort_keys=True))
        return doc
