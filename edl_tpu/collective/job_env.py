"""Job + trainer environment contracts.

The EDL_TPU_* env contract replacing the reference's PADDLE_* one
(utils/edl_env.py:86-126: JOB_ID, POD_ID, ETCD_ENPOINTS, NODES_RANGE
"min:max", NPROC_PERNODE, checkpoint/HDFS vars; utils/edl_process.py:51-59:
per-trainer PADDLE_TRAINER_ID/ENDPOINTS env). `JobEnv` is read by the
launcher; `TrainerEnv` is what the spawned trainer process reads back.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from edl_tpu.collective.cluster import Cluster
from edl_tpu.utils.config import field, from_env
from edl_tpu.utils import net, unique_name


@dataclass
class JobEnv:
    job_id: str = field("default_job", env="EDL_TPU_JOB_ID")
    pod_id: str = field("", env="EDL_TPU_POD_ID")
    store_endpoints: str = field("127.0.0.1:2379",
                                 env="EDL_TPU_STORE_ENDPOINTS")
    nodes_range: str = field("1:16", env="EDL_TPU_NODES_RANGE")  # "min:max"
    nproc_per_node: int = field(0, env="EDL_TPU_NPROC_PERNODE")  # 0 = auto
    # Multi-slice (hybrid ICI x DCN) topology: how many TPU slices the
    # job spans. 0 = auto (trainers detect from jax.devices()
    # slice_index; flat single-slice world when the hardware reports
    # none). >1 partitions the pods rank-contiguously into slices and
    # the trainers build hybrid meshes with dp crossing DCN
    # (parallel/mesh.make_hybrid_mesh).
    slices: int = field(0, env="EDL_TPU_SLICES")
    up_limit_nodes: int = field(1024, env="EDL_TPU_UP_LIMIT_NODES")
    checkpoint_path: str = field("", env="EDL_TPU_CHECKPOINT_PATH")
    job_server: str = field("", env="EDL_TPU_JOBSERVER")
    log_dir: str = field("./log", env="EDL_TPU_LOG_DIR")
    lease_ttl: float = field(10.0, env="EDL_TPU_LEASE_TTL")
    barrier_stable_secs: float = field(2.0, env="EDL_TPU_BARRIER_STABLE")
    barrier_timeout: float = field(300.0, env="EDL_TPU_BARRIER_TIMEOUT")
    # After a local crash/lease loss, how long to stay unregistered before
    # re-claiming (must exceed peers' watcher poll interval so the blip is
    # observed; the reference sleeps 15s > etcd TTL for the same reason).
    rejoin_delay_secs: float = field(3.0, env="EDL_TPU_REJOIN_DELAY")
    # Peer-to-peer live state migration (collective/migration.py): on a
    # membership change, surviving trainers adopt the new world IN PLACE
    # (no respawn/restore) and every trainer serves its sealed snapshot
    # to (re)starting peers, with disk as the fallback. 0 restores the
    # pure stop-resume-from-disk recipe.
    resize_p2p: bool = field(True, env="EDL_TPU_RESIZE_P2P")
    # How long a SIGTERM'd trainer keeps serving shards to the re-formed
    # world before exiting (early-exits once every live pod has acked).
    donor_linger_secs: float = field(10.0, env="EDL_TPU_DONOR_LINGER")
    # How long the launcher waits for its trainer to ack an in-place
    # adoption before falling back to stop-resume with a donor linger.
    adopt_timeout_secs: float = field(10.0, env="EDL_TPU_ADOPT_TIMEOUT")

    def __post_init__(self):
        if not self.pod_id:
            self.pod_id = unique_name.client_id()

    @property
    def min_nodes(self) -> int:
        return int(self.nodes_range.split(":")[0])

    @property
    def max_nodes(self) -> int:
        parts = self.nodes_range.split(":")
        return min(int(parts[-1]), self.up_limit_nodes)

    @classmethod
    def from_environ(cls, **overrides) -> "JobEnv":
        return from_env(cls, **overrides)


TRAINER_ENV_VARS = ("EDL_TPU_RANK", "EDL_TPU_WORLD_SIZE",
                    "EDL_TPU_COORDINATOR", "EDL_TPU_CLUSTER_JSON",
                    "EDL_TPU_JOB_ID", "EDL_TPU_POD_ID",
                    "EDL_TPU_CHECKPOINT_PATH", "EDL_TPU_STORE_ENDPOINTS",
                    "EDL_TPU_CLUSTER_VERSION", "EDL_TPU_SLICES",
                    "EDL_TPU_SLICE_ID")


@dataclass
class TrainerEnv:
    """What a spawned trainer process sees (reference TrainerEnv,
    utils/edl_env.py:149)."""

    rank: int = field(0, env="EDL_TPU_RANK")
    world_size: int = field(1, env="EDL_TPU_WORLD_SIZE")
    coordinator: str = field("", env="EDL_TPU_COORDINATOR")
    cluster_json: str = field("", env="EDL_TPU_CLUSTER_JSON")
    job_id: str = field("", env="EDL_TPU_JOB_ID")
    pod_id: str = field("", env="EDL_TPU_POD_ID")
    checkpoint_path: str = field("", env="EDL_TPU_CHECKPOINT_PATH")
    store_endpoints: str = field("", env="EDL_TPU_STORE_ENDPOINTS")
    cluster_version: int = field(0, env="EDL_TPU_CLUSTER_VERSION")
    # slice topology (hybrid ICI x DCN meshes): 0/-1 = auto-detect from
    # the devices; set by the launcher when the operator pins
    # EDL_TPU_SLICES on the job
    n_slices: int = field(0, env="EDL_TPU_SLICES")
    slice_id: int = field(-1, env="EDL_TPU_SLICE_ID")

    @classmethod
    def from_environ(cls, **overrides) -> "TrainerEnv":
        return from_env(cls, **overrides)

    @property
    def cluster(self) -> Cluster | None:
        return Cluster.from_json(self.cluster_json) \
            if self.cluster_json else None

    @property
    def is_leader(self) -> bool:
        return self.rank == 0


def slice_of_rank(rank: int, world_size: int, n_slices: int) -> int:
    """Rank-contiguous slice assignment: ranks [0, w/s) -> slice 0, etc.

    Contiguity matters: the barrier orders pods by claimed rank, and GKE
    multi-slice JobSets hand out completion indices slice-by-slice, so
    contiguous rank blocks are the physical slices. When each POD spans
    multiple slices (n_slices a multiple of world_size — one launcher
    driving all local devices, the CPU-emulation shape) no single slice
    id applies: return -1 (auto) and let the trainer's slice_topology
    split its local devices. Anything else is a misconfiguration the
    hybrid mesh would reject anyway — fail here with the better message.
    """
    if n_slices <= 1:
        return 0
    if world_size % n_slices == 0:
        return rank // (world_size // n_slices)
    if n_slices % world_size == 0:
        return -1  # pod-local multi-slice: id is per-device, not per-pod
    raise ValueError(
        f"world_size={world_size} not divisible by "
        f"EDL_TPU_SLICES={n_slices} (nor vice versa)")


def trainer_environ(cluster: Cluster, pod_id: str, job: JobEnv) -> dict:
    """Env block for the trainer subprocess (reference edl_process.py:51-59)."""
    env = dict(os.environ)
    rank = cluster.rank_of(pod_id)
    env.update({
        "EDL_TPU_RANK": str(rank),
        "EDL_TPU_WORLD_SIZE": str(cluster.world_size),
        "EDL_TPU_COORDINATOR": cluster.coordinator,
        "EDL_TPU_CLUSTER_JSON": cluster.to_json(),
        "EDL_TPU_JOB_ID": job.job_id,
        "EDL_TPU_POD_ID": pod_id,
        "EDL_TPU_CHECKPOINT_PATH": job.checkpoint_path,
        "EDL_TPU_STORE_ENDPOINTS": job.store_endpoints,
        "EDL_TPU_CLUSTER_VERSION": str(cluster.version),
        "EDL_TPU_SLICES": str(job.slices),
        "EDL_TPU_SLICE_ID": str(
            slice_of_rank(rank, cluster.world_size, job.slices)
            if job.slices > 1 else -1),
    })
    return env


def local_addr() -> str:
    return net.host_ip()
