"""Cluster membership watcher: sets a flag when the live pod set diverges.

Capability of the reference's Watcher (utils/watcher.py:39-77: thread polls
the etcd pod service each second, diffs pod JSON, sets `changed`).
"""

from __future__ import annotations

import threading

from edl_tpu.collective.cluster import Cluster
from edl_tpu.collective import register as reg
from edl_tpu.coord.store import Store
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.collective.watcher")


class ClusterWatcher:
    """Watch the rank-claim prefix AND the published cluster version.

    `changed` fires when (a) live membership differs from the baseline
    Cluster this trainer generation was formed with, or (b) a cluster
    snapshot with a *newer version* appears. (b) matters because a pod that
    crashes and rejoins within one poll interval produces no membership
    diff — but its barrier publishes a new generation, which every peer
    must join or the collectives deadlock.
    """

    def __init__(self, store: Store, baseline: Cluster,
                 interval: float = 1.0):
        self.store = store
        self.baseline = baseline
        self.interval = interval
        self.changed = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"cluster-watch-{baseline.job_id}")

    def start(self) -> "ClusterWatcher":
        self._thread.start()
        return self

    def _run(self) -> None:
        base = self.baseline.pod_ids()
        version = self.baseline.version
        parsed_revision = -1
        while not self._stop.wait(self.interval):
            try:
                pods, _ = reg.live_pods(self.store, self.baseline.job_id)
                rec = self.store.get(reg.cluster_key(self.baseline.job_id))
                # Parse the snapshot only when its store revision moved —
                # this poll runs every second on every pod.
                if rec is not None and rec.revision != parsed_revision:
                    version = Cluster.from_json(rec.value).version
                    parsed_revision = rec.revision
            except Exception as exc:
                log.warning("cluster watch poll failed: %s", exc)
                continue
            now = {p.pod_id for p in pods}
            if now != base:
                log.info("cluster change: %s -> %s",
                         sorted(base), sorted(now))
                self.changed.set()
                return
            if version > self.baseline.version:
                log.info("cluster generation advanced: v%d -> v%d",
                         self.baseline.version, version)
                self.changed.set()
                return

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
