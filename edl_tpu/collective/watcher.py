"""Cluster membership watcher: sets a flag when the live pod set diverges.

Capability of the reference's Watcher (utils/watcher.py:39-77: thread polls
the etcd pod service each second, diffs pod JSON, sets `changed`) — now fed
by store watch events (rank-claim prefix + published cluster snapshot), so a
membership change or generation bump is seen at event latency instead of up
to one poll period later; the periodic re-check survives as a resync safety
net (and as the whole mechanism when EDL_TPU_COORD_WATCH=0).
"""

from __future__ import annotations

import threading

from edl_tpu.collective.cluster import Cluster
from edl_tpu.collective import register as reg
from edl_tpu.coord.store import Store
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.collective.watcher")


class ClusterWatcher:
    """Watch the rank-claim prefix AND the published cluster version.

    `changed` fires when (a) live membership differs from the baseline
    Cluster this trainer generation was formed with, or (b) a cluster
    snapshot with a *newer version* appears. (b) matters because a pod that
    crashes and rejoins within one poll interval produces no membership
    diff — but its barrier publishes a new generation, which every peer
    must join or the collectives deadlock.
    """

    def __init__(self, store: Store, baseline: Cluster,
                 interval: float = 1.0):
        self.store = store
        self.baseline = baseline
        self.interval = interval
        self.changed = threading.Event()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._watches: list = []
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"cluster-watch-{baseline.job_id}")

    def start(self) -> "ClusterWatcher":
        # Event-driven: a mutation on the rank-claim prefix or the
        # published cluster snapshot wakes the checker immediately, so
        # membership changes are seen at event latency; the fixed-period
        # poll is demoted to a resync safety net. try_watch -> None
        # (EDL_TPU_COORD_WATCH=0 / redis) keeps the original poll loop.
        from edl_tpu.coord.store import try_watch
        job_id = self.baseline.job_id
        for prefix in (reg.ranks_prefix(job_id), reg.cluster_key(job_id)):
            watch = try_watch(self.store, prefix)
            if watch is not None:
                thread = threading.Thread(target=self._pump, args=(watch,),
                                          daemon=True,
                                          name=f"cluster-watch-pump-{job_id}")
                thread.start()
                self._watches.append((watch, thread))
        self._thread.start()
        return self

    def _pump(self, watch) -> None:
        while not self._stop.is_set():
            batch = watch.get(timeout=5.0)
            if batch is None:
                if watch.cancelled:
                    return
                continue
            if batch.events or batch.compacted:
                self._wake.set()

    def _run(self) -> None:
        from edl_tpu.coord.store import watch_resync_interval
        base = self.baseline.pod_ids()
        version = self.baseline.version
        parsed_revision = -1
        # with watches the periodic re-check is only a safety net
        wait = self.interval if not self._watches \
            else watch_resync_interval(default=max(self.interval * 10, 10.0))
        first = True
        while not self._stop.is_set():
            if first:
                first = False  # a change between the baseline snapshot
                # and watch creation has no event: check immediately
            else:
                self._wake.wait(timeout=wait)
                self._wake.clear()
            if self._stop.is_set():
                return
            try:
                pods, _ = reg.live_pods(self.store, self.baseline.job_id)
                rec = self.store.get(reg.cluster_key(self.baseline.job_id))
                # Parse the snapshot only when its store revision moved.
                if rec is not None and rec.revision != parsed_revision:
                    version = Cluster.from_json(rec.value).version
                    parsed_revision = rec.revision
            except Exception as exc:
                log.warning("cluster watch poll failed: %s", exc)
                continue
            now = {p.pod_id for p in pods}
            if now != base:
                log.info("cluster change: %s -> %s",
                         sorted(base), sorted(now))
                self.changed.set()
                return
            if version > self.baseline.version:
                log.info("cluster generation advanced: v%d -> v%d",
                         self.baseline.version, version)
                self.changed.set()
                return

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        for watch, _ in self._watches:
            watch.cancel()
        for _, thread in self._watches:
            thread.join(timeout=2.0)
        self._watches = []
        self._thread.join(timeout=2.0)
