"""SLO-driven serving elasticity: autoscale the distill teacher pool.

The reference's second pillar — EDL distill — is an *elastic* pool of
inference servers, but until now only trainer worlds were autoscaled;
the teacher pool was manually sized. This module closes that last loop
(ROADMAP item 2, the millions-of-users story) from signals that already
flow: teachers publish ``busy_s``/``queue_depth``/``latency_ms_p95``
through `TeacherRegistrar` → `Collector.service_rollup`, and the
balancer's keep-then-fill already handles endpoint departure — so a
pool can grow and shrink under live traffic without a client ever
seeing a hard error.

Two halves, mirroring `policy.py`/`controller.py` for trainers:

- `ServingPolicy` — the decision plane. Pure state machine over
  `ServingView` observations (no store, no wall clock: the caller
  supplies ``now``), targeting a latency / queue-depth / shed-rate SLO
  with **asymmetric hysteresis**: grow fast on *sustained* breach
  (``breach_ticks`` consecutive observations over the p95 target,
  queue high-water mark, or shed-rate ceiling — an admission-controlled
  pool rejects its way back into the latency SLO, so sustained
  shedding must count as overload — multiplicative step bounded by
  ``grow_max_factor``), shrink slowly on *sustained* idleness
  (``idle_ticks`` consecutive observations under the utilization
  low-water mark with an empty queue and p95 comfortably inside the
  SLO), one teacher at a time. The dead zone between the two
  conditions is the anti-oscillation margin, the serving analogue of
  `ThroughputPolicy`'s eps/2eps band. A breach whose backlog is
  already paying down under existing capacity holds instead of growing
  (``backlog-draining``) — more teachers cannot drain a queue faster
  than the arrival deficit does.

- `TeacherPoolActuator` — the actuation plane. Owns the teacher
  handles for one service on one host: grows by spawning (in-process
  `TeacherServer`s or real subprocesses via `collective/process.py`),
  shrinks by **draining**: deregister from discovery first (the
  balancer's keep-then-fill reassigns the readers), wait until the
  server's own stats report an empty intake queue and zero in-flight
  groups, and only then stop it. A teacher that never quiets (a client
  pinned past the deregistration) is hard-killed at
  ``drain_deadline_s`` — the fallback, never the path.

`ScalerController` runs this policy side by side with the trainer
policies under one leader election (``services=`` / ``serving_policy=``
/ ``serving_actuate=``), and `FairSharePolicy.decide_mixed` water-fills
one node budget across trainer worlds and teacher pools. The loop is
grounded in `simulator.SimServingPool` (open-loop arrival traces,
SLO-attainment oracles); ``python -m edl_tpu.scaler.serving selftest``
is the jax-free CI smoke, and ``elastic_demo --serve-scaler`` runs the
whole thing live on one host.
"""

from __future__ import annotations

import argparse
import math
import sys
import threading
import time
from dataclasses import dataclass
from typing import Callable, Protocol, runtime_checkable

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import recorder as flight
from edl_tpu.obs import trace
from edl_tpu.scaler.policy import Proposal
from edl_tpu.utils.config import field
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.scaler.serving")


@dataclass
class ServingView:
    """One teacher pool's state at one decision instant (a
    `Collector.service_rollup` digest)."""

    service: str
    n_teachers: int            # live registered teachers
    rows_per_sec: float = 0.0  # aggregate serving rate across the pool
    util: float = 0.0          # mean busy fraction across teachers
    queue_depth: int = 0       # total intake backlog (requests)
    latency_ms_p50: float | None = None
    latency_ms_p95: float | None = None   # worst reporting teacher
    # admission-control signals (r23 rollups; zero/empty from older
    # registrars). Shedding is the policy's anti-blindness input: a
    # pool under admission control holds its p95 in-SLO by REJECTING,
    # so sustained shed_per_sec must count as a breach even while the
    # latency numbers look healthy.
    shed_per_sec: float = 0.0
    queue_depth_by_class: dict | None = None   # {"high": 3, ...}
    latency_ms_p95_by_class: dict | None = None
    draining: int = 0          # teachers mid-drain (not real capacity)
    slo_p95_ms: float = 250.0  # the SLO contract travels with the view
    min_teachers: int = 1
    max_teachers: int = 8
    desired: int | None = None  # actuator target (None = n_teachers)
    fresh: bool = True         # False: pool up but no reporting teacher

    @property
    def effective_desired(self) -> int:
        return self.n_teachers if self.desired is None else self.desired


@dataclass
class ServingConfig:
    """The SLO contract + hysteresis knobs (`EDL_TPU_SERVE_*`)."""

    # the target: pool p95 request latency (submit -> results ready)
    slo_p95_ms: float = field(250.0, env="EDL_TPU_SERVE_SLO_P95_MS")
    # breach also when the backlog exceeds this many queued requests
    # PER teacher — queue growth leads the latency it will become
    queue_high: float = field(4.0, env="EDL_TPU_SERVE_QUEUE_HIGH")
    # breach also when the pool sheds more than this many requests/sec:
    # admission control keeps p95 in-SLO by rejecting, so a latency-only
    # breach test goes blind exactly when the pool is most overloaded
    shed_high: float = field(0.5, env="EDL_TPU_SERVE_SHED_HIGH")
    # shrink only under this mean busy fraction (low-water mark) ...
    util_low: float = field(0.3, env="EDL_TPU_SERVE_UTIL_LOW")
    # ... and only while p95 sits under this fraction of the SLO: the
    # asymmetric dead zone between shrink and grow conditions
    shrink_headroom: float = field(0.5, env="EDL_TPU_SERVE_SHRINK_HEADROOM")
    # sustained-signal filters: consecutive observations required
    breach_ticks: int = field(2, env="EDL_TPU_SERVE_BREACH_TICKS")
    idle_ticks: int = field(5, env="EDL_TPU_SERVE_IDLE_TICKS")
    # per-pool seconds between actuated resizes
    cooldown_s: float = field(15.0, env="EDL_TPU_SERVE_COOLDOWN")
    # a grow multiplies the pool by at most this per decision (and by
    # at least +1 teacher): a 4x load step recovers in ~2 grows without
    # a single bad sample quadrupling the pool
    grow_max_factor: float = field(2.0, env="EDL_TPU_SERVE_GROW_FACTOR")
    min_teachers: int = field(1, env="EDL_TPU_SERVE_MIN_TEACHERS")
    max_teachers: int = field(8, env="EDL_TPU_SERVE_MAX_TEACHERS")
    # graceful-drain budget before the hard-kill fallback
    drain_deadline_s: float = field(30.0, env="EDL_TPU_SERVE_DRAIN_DEADLINE")


class ServingPolicy:
    """Latency/queue-SLO autoscaling for teacher pools.

    Per decision (per pool): freshness and resize-in-flight gates, then
    classify the observation (breach / idle / in-band), run the streak
    counters, and act only outside the cooldown — streaks keep
    accumulating *during* cooldown, so the first post-cooldown decision
    reacts immediately instead of re-waiting ``breach_ticks``.

    Same protocol shape as `ScalingPolicy` (decide / notify_resized /
    restore), so the controller and simulator drive both identically;
    the id field of a `Proposal` carries the service name.
    """

    def __init__(self, config: ServingConfig | None = None):
        self.config = config or ServingConfig()
        self._breach: dict[str, int] = {}
        self._idle: dict[str, int] = {}
        self._prev_queue: dict[str, int] = {}
        self._resized_at: dict[str, float] = {}

    def decide(self, views: list[ServingView], now: float) -> list[Proposal]:
        return [self._decide_one(v, now) for v in views]

    def _classify(self, view: ServingView) -> tuple[bool, bool, bool]:
        """(breach, draining, idle) for one observation."""
        cfg = self.config
        slo = view.slo_p95_ms or cfg.slo_p95_ms
        n = max(1, view.n_teachers)
        breach = ((view.latency_ms_p95 is not None
                   and view.latency_ms_p95 > slo)
                  or view.queue_depth > cfg.queue_high * n
                  # shed-blinded breach: under admission control the
                  # pool REJECTS its way back into the latency SLO, so
                  # sustained shedding is overload even at healthy p95
                  or view.shed_per_sec > cfg.shed_high)
        # Backlog already paying down under existing capacity: arrivals
        # run below service rate (util off the ceiling) and the queue
        # shrank since the last look — growing now would buy teachers
        # for a deficit that no longer exists.
        prev = self._prev_queue.get(view.service)
        draining = (breach and view.queue_depth > 0 and prev is not None
                    and view.queue_depth < prev and view.util < 0.95)
        idle = (not breach and view.util < cfg.util_low
                and view.queue_depth == 0
                and (view.latency_ms_p95 is None
                     or view.latency_ms_p95 < cfg.shrink_headroom * slo))
        return breach, draining, idle

    def _decide_one(self, view: ServingView, now: float) -> Proposal:
        svc, cur = view.service, view.n_teachers
        cfg = self.config
        if not view.fresh or cur < 1:
            return Proposal(svc, cur, cur, "no-fresh-serving-stats")
        if view.effective_desired != cur:
            return Proposal(svc, cur, cur, "resize-in-flight")
        breach, draining, idle = self._classify(view)
        self._prev_queue[svc] = view.queue_depth
        self._breach[svc] = (self._breach.get(svc, 0) + 1
                             if breach and not draining else 0)
        self._idle[svc] = self._idle.get(svc, 0) + 1 if idle else 0
        resized_at = self._resized_at.get(svc)
        if resized_at is not None and now - resized_at < cfg.cooldown_s:
            return Proposal(svc, cur, cur, "cooldown")
        if draining:
            return Proposal(svc, cur, cur, "backlog-draining")
        if self._breach[svc] >= cfg.breach_ticks:
            if cur >= view.max_teachers:
                return Proposal(svc, cur, cur, "slo-breach-at-max")
            slo = view.slo_p95_ms or cfg.slo_p95_ms
            factor = 1.0
            if view.latency_ms_p95 is not None and slo > 0:
                factor = view.latency_ms_p95 / slo
            if cfg.queue_high > 0:
                factor = max(factor,
                             view.queue_depth / (cfg.queue_high * cur))
            if view.shed_per_sec > cfg.shed_high:
                # offered / served: capacity for the load the pool is
                # turning away, not just the load it admitted (shed is
                # requests/s vs rows/s — an UNDER-estimate of pressure
                # when requests batch rows, safe under the max())
                factor = max(factor,
                             (view.rows_per_sec + view.shed_per_sec)
                             / max(view.rows_per_sec, 1.0))
            desired = min(view.max_teachers,
                          max(cur + 1,
                              math.ceil(cur * min(factor,
                                                  cfg.grow_max_factor))))
            return Proposal(svc, cur, desired, "slo-breach-grow")
        if self._idle[svc] >= cfg.idle_ticks and cur > view.min_teachers:
            return Proposal(svc, cur, cur - 1, "idle-shrink")
        return Proposal(svc, cur, cur, "in-band")

    def notify_resized(self, service: str, desired: int,
                       now: float) -> None:
        self._resized_at[service] = now
        self._breach[service] = 0
        self._idle[service] = 0
        self._prev_queue.pop(service, None)

    def restore(self, entries: list[dict]) -> None:
        """Journal replay (leader takeover): resume the cooldown clocks
        of serving-kind resize entries. Streaks restart from zero — a
        sustained condition re-proves itself within ``breach_ticks``
        observations, which is exactly the filter's job."""
        for e in entries:
            if e.get("kind") != "serving" or not e.get("service"):
                continue
            if e.get("action") == "resize":
                self._resized_at[e["service"]] = float(e.get("ts", 0.0))


# -- actuation ---------------------------------------------------------------


@runtime_checkable
class TeacherHandle(Protocol):
    """What the actuator needs from one live teacher."""

    endpoint: str

    def stats(self) -> dict | None:
        """Live serving counters, or None when the server is gone."""
        ...

    def deregister(self) -> None:
        """Leave discovery NOW (the drain's first step)."""
        ...

    def stop(self) -> None:
        """Graceful stop after a completed drain."""
        ...

    def kill(self) -> None:
        """Hard stop (the drain-deadline fallback)."""
        ...


class LocalTeacher:
    """In-process `TeacherServer` + registrar — the one-host pool unit
    (tests, `elastic_demo --serve-scaler`)."""

    def __init__(self, server, registrar):
        self.server = server
        self.registrar = registrar
        self.endpoint = registrar.server

    def stats(self) -> dict | None:
        try:
            return self.server.batcher.stats()
        except Exception:  # noqa: BLE001 — torn down under us
            return None

    def deregister(self) -> None:
        self.registrar.stop(deregister=True)

    def drain(self) -> None:
        """Stop admitting: pinned clients get reject-with-retry-after
        (and re-resolve via discovery) instead of queueing forever."""
        self.server.drain()

    def stop(self) -> None:
        self.server.stop()

    def kill(self) -> None:
        self.server.stop()  # in-process: same teardown path

    def close(self) -> None:
        self.registrar.stop(deregister=True)
        self.server.stop()


class ProcessTeacher:
    """Subprocess teacher (spawned via `collective/process.py`) with
    the registrar run actuator-side — the same sidecar split as the
    production CLI pair (`teacher_server` + `registrar`)."""

    def __init__(self, proc, registrar, endpoint: str):
        self.proc = proc           # collective.process.TrainerProc
        self.registrar = registrar
        self.endpoint = endpoint

    def stats(self) -> dict | None:
        from edl_tpu.distill.teacher_server import TeacherClient
        try:
            client = TeacherClient(self.endpoint, timeout=2.0)
        except OSError:
            return None
        try:
            return client.stats()
        except Exception:  # noqa: BLE001 — dying server: treat as gone
            return None
        finally:
            client.close()

    def deregister(self) -> None:
        self.registrar.stop(deregister=True)

    def drain(self) -> None:
        """Flip the remote server into drain mode over the wire."""
        from edl_tpu.distill.teacher_server import TeacherClient
        client = TeacherClient(self.endpoint, timeout=2.0)
        try:
            client.drain()
        finally:
            client.close()

    def stop(self) -> None:
        from edl_tpu.collective.process import terminate_trainer
        terminate_trainer(self.proc, grace=5.0)

    def kill(self) -> None:
        from edl_tpu.collective.process import terminate_trainer
        terminate_trainer(self.proc, grace=0.0)


def spawn_process_teacher(store, service: str, cmd: list[str],
                          endpoint: str, log_dir: str, index: int, *,
                          env: dict | None = None, ttl: float = 10.0,
                          stats_interval: float = 1.0,
                          probe_timeout: float = 60.0) -> ProcessTeacher:
    """Spawn ``cmd`` as a real teacher process (own process group,
    ``workerlog.N`` redirect — `collective/process.py`) and register
    ``endpoint`` once it answers TCP. The returned handle plugs into
    `TeacherPoolActuator`."""
    import os

    from edl_tpu.collective.process import start_trainer
    from edl_tpu.distill.registrar import TeacherRegistrar
    proc = start_trainer(cmd, dict(env or os.environ), log_dir, rank=index)
    registrar = TeacherRegistrar(store, service, endpoint, ttl=ttl,
                                 stats_interval=stats_interval,
                                 probe_timeout=probe_timeout)
    try:
        registrar.start()
    except Exception:
        from edl_tpu.collective.process import terminate_trainer
        terminate_trainer(proc, grace=2.0)
        raise
    return ProcessTeacher(proc, registrar, endpoint)


class TeacherPoolActuator:
    """Grow by spawning, shrink by draining — never hard-kill a busy
    teacher.

    ``spawn(index) -> TeacherHandle`` is the only pool-specific piece;
    everything else (victim choice, the drain protocol, the resize and
    drain audit logs) is shared between in-process pools and real
    process pools.

    Drain protocol (per retired teacher, in a background thread so the
    control loop never blocks on it):

      1. **deregister** from discovery — the balancer's keep-then-fill
         reassigns the teacher's readers on its next tick, so new work
         stops arriving — and, when the handle supports it (duck-typed
         ``drain()``), flip the server itself into drain mode: further
         submits get reject-with-retry-after, so a client pinned past
         the deregistration re-resolves instead of re-arming the queue
         forever (the pre-r23 hard-kill trigger);
      2. **wait for in-flight work** via the server's own stats: the
         intake queue empty AND zero in-flight groups for
         ``drain_quiet_polls`` consecutive polls;
      3. **stop** gracefully — or, when the deadline expires first
         (a client pinned past the deregistration), **hard-kill** and
         record it (``drain_log[i]["hard_killed"]``).
    """

    def __init__(self, spawn: Callable[[int], TeacherHandle], *,
                 min_teachers: int = 1, max_teachers: int = 8,
                 drain_deadline_s: float = 30.0,
                 drain_poll_s: float = 0.1, drain_quiet_polls: int = 2,
                 service: str = "teacher"):
        self.spawn = spawn
        self.min_teachers = min_teachers
        self.max_teachers = max_teachers
        self.drain_deadline_s = drain_deadline_s
        self.drain_poll_s = drain_poll_s
        self.drain_quiet_polls = drain_quiet_polls
        self.service = service
        # control loop, drain threads, and test scrapes all touch the
        # pool state — guarded-by annotations checked by edl-lint
        self._lock = threading.Lock()
        self._teachers: list[TeacherHandle] = []  # guarded-by: _lock
        self._spawned = 0                         # guarded-by: _lock
        self._drains: list[threading.Thread] = []  # guarded-by: _lock
        self.desired = 0                          # guarded-by: _lock
        self.resize_log: list[dict] = []          # guarded-by: _lock
        self.drain_log: list[dict] = []           # guarded-by: _lock
        # the logs stay the audit API; the obs registry serves the
        # same tallies as scrapeable gauges (unregistered on close)
        self._obs = obs_metrics.register_stats("pool", self.stats)

    def stats(self) -> dict:
        """Pool counters as a dict view (obs registry source)."""
        with self._lock:
            return {"teachers": len(self._teachers),
                    "desired": self.desired,
                    "spawned_total": self._spawned,
                    "resizes": len(self.resize_log),
                    "drains": len(self.drain_log),
                    "hard_kills": sum(1 for d in self.drain_log
                                      if d.get("hard_killed"))}

    def pool_size(self) -> int:
        with self._lock:
            return len(self._teachers)

    def endpoints(self) -> list[str]:
        with self._lock:
            return [t.endpoint for t in self._teachers]

    def actuate(self, service: str, desired: int) -> dict:
        """`ScalerController.serving_actuate` signature."""
        del service  # one actuator owns one service's pool
        return self.resize(desired)

    def resize(self, desired: int) -> dict:
        # a real span (not instant): it parents onto scaler.decide when
        # the controller drove it, and its [t0, t0+dur) window is what a
        # merged trace intersects the per-request serve.admit spans with
        # to attribute shed (tenant, class) traffic to THIS resize
        with trace.span("serve.resize", attrs={"service": self.service,
                                               "requested": desired}):
            return self._resize_locked_protocol(desired)

    def _resize_locked_protocol(self, desired: int) -> dict:
        requested = desired
        with self._lock:
            desired = max(self.min_teachers,
                          min(self.max_teachers, desired))
            self.desired = desired
            cur = len(self._teachers)
            self.resize_log.append({"from": cur, "to": desired,
                                    "ts": time.time()})
            victims: list[TeacherHandle] = []
            while len(self._teachers) > desired:
                # LIFO: the newest teacher retires first — the seniors
                # keep their warmed caches and long-lived client links
                victims.append(self._teachers.pop())
            to_spawn = desired - len(self._teachers)
        flight.record("resize", plane="serving", service=self.service,
                      frm=cur, to=desired)
        for handle in victims:
            self._begin_drain(handle)
        for _ in range(to_spawn):
            with self._lock:
                index = self._spawned
                self._spawned += 1
            handle = self.spawn(index)
            with self._lock:
                self._teachers.append(handle)
            log.info("pool %s: spawned teacher %s (-> %d)", self.service,
                     getattr(handle, "endpoint", "?"), desired)
        return {"desired_teachers": desired, "requested": requested,
                "clamped": desired != requested}

    def _begin_drain(self, handle: TeacherHandle) -> None:
        thread = threading.Thread(target=self._drain, args=(handle,),
                                  daemon=True,
                                  name=f"teacher-drain-{self.service}")
        thread.start()
        with self._lock:
            self._drains.append(thread)

    def _drain(self, handle: TeacherHandle) -> None:
        t0 = time.monotonic()
        entry = {"endpoint": getattr(handle, "endpoint", "?"),
                 "drained": False, "hard_killed": False, "wait_s": 0.0}
        try:
            handle.deregister()
        except Exception as exc:  # noqa: BLE001 — registry outage must
            # not leave the teacher serving forever; keep draining
            log.warning("deregister %s failed: %s", entry["endpoint"], exc)
        # duck-typed (getattr, not the Protocol): this module must stay
        # importable without the distill plane, and pre-r23 handles
        # without drain() keep the deregister-only behavior
        drain_fn = getattr(handle, "drain", None)
        if callable(drain_fn):
            try:
                drain_fn()
            except Exception as exc:  # noqa: BLE001 — a dying server
                # refusing the drain op still drains via deregistration
                log.warning("drain op on %s failed: %s",
                            entry["endpoint"], exc)
        deadline = t0 + self.drain_deadline_s
        quiet = 0
        while time.monotonic() < deadline:
            stats = handle.stats()
            if stats is None:
                entry["drained"] = True  # server already gone
                break
            if (stats.get("queue_depth", 0) == 0
                    and stats.get("inflight_groups", 0) == 0):
                quiet += 1
                if quiet >= self.drain_quiet_polls:
                    entry["drained"] = True
                    break
            else:
                quiet = 0
            time.sleep(self.drain_poll_s)
        entry["wait_s"] = round(time.monotonic() - t0, 3)
        try:
            if entry["drained"]:
                handle.stop()
                log.info("pool %s: drained %s in %.2fs", self.service,
                         entry["endpoint"], entry["wait_s"])
            else:
                entry["hard_killed"] = True
                handle.kill()
                log.warning("pool %s: drain of %s exceeded %.1fs; "
                            "hard-killed", self.service, entry["endpoint"],
                            self.drain_deadline_s)
        except Exception as exc:  # noqa: BLE001 — teardown
            log.warning("stopping %s failed: %s", entry["endpoint"], exc)
        with self._lock:
            self.drain_log.append(entry)
        flight.record("drain", plane="serving", service=self.service,
                      **entry)

    def wait_drains(self, timeout: float = 30.0) -> bool:
        """Join outstanding drain threads (tests, orderly shutdown)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            drains = list(self._drains)
        for thread in drains:
            thread.join(timeout=max(0.0, deadline - time.monotonic()))
        return all(not t.is_alive() for t in drains)

    def close(self) -> None:
        """Tear the pool down (shutdown path, not a drain)."""
        with self._lock:
            teachers, self._teachers = self._teachers, []
        for handle in teachers:
            try:
                handle.deregister()
            except Exception:  # noqa: BLE001 — teardown
                pass
            try:
                handle.stop()
            except Exception:  # noqa: BLE001 — teardown
                pass
        self.wait_drains(timeout=5.0)
        obs_metrics.unregister(self._obs)


# -- the jax-free CI smoke ---------------------------------------------------


def selftest(verbose: bool = True) -> int:
    """Drive `ServingPolicy` over the deterministic `SimServingPool`
    traces and fail loudly unless the closed loop behaves:

      - steady load: zero resizes, 100% SLO attainment (no thrash);
      - 4x load step: the SLO is restored within a bounded number of
        ticks and the pool converges to the oracle size with zero
        post-convergence resizes;
      - burst: grows into the burst, drains back down after it.

    numpy/jax-free — runnable on a scheduler node or a bare CI runner.
    """
    from edl_tpu.scaler.simulator import (SimServingPool, burst,
                                          run_serving_policy, steady, step)

    def fresh_policy():
        return ServingPolicy(ServingConfig(
            slo_p95_ms=250.0, breach_ticks=2, idle_ticks=5,
            cooldown_s=15.0, max_teachers=16))

    failures: list[str] = []

    def check(cond: bool, what: str) -> None:
        if verbose:
            print(("ok   " if cond else "FAIL ") + what)
        if not cond:
            failures.append(what)

    pool = SimServingPool("svc", steady(200.0), teachers=1,
                          max_teachers=16, tick_s=1.0, seed=0)
    out = run_serving_policy(pool, fresh_policy(), ticks=120)
    check(out["resizes"] == 0,
          f"steady: zero resizes (got {out['resizes']})")
    check(out["slo_attainment"] == 1.0,
          f"steady: 100% SLO attainment (got {out['slo_attainment']:.2%})")

    at = 40
    pool = SimServingPool("svc", step(100.0, 4.0, at=at), teachers=1,
                          max_teachers=16, tick_s=1.0, seed=0)
    out = run_serving_policy(pool, fresh_policy(), ticks=160)
    oracle = pool.oracle_teachers(400.0)
    check(out["last_violation_tick"] - at <= 25,
          f"step: SLO restored within 25 ticks (took "
          f"{out['last_violation_tick'] - at})")
    check(out["final_teachers"] == oracle,
          f"step: converged to oracle {oracle} "
          f"(got {out['final_teachers']})")
    check(out["post_convergence_resizes"] == 0,
          f"step: zero post-convergence resizes "
          f"(got {out['post_convergence_resizes']})")

    pool = SimServingPool("svc", burst(100.0, 4.0, at=30, length=25),
                          teachers=1, max_teachers=16, tick_s=1.0, seed=0)
    out = run_serving_policy(pool, fresh_policy(), ticks=200)
    check(out["resizes"] >= 2,
          f"burst: grew into and shrank out of the burst "
          f"(got {out['resizes']} resizes)")
    check(out["final_teachers"] == pool.oracle_teachers(100.0),
          f"burst: drained back to the steady oracle "
          f"(got {out['final_teachers']})")
    check(out["post_convergence_resizes"] == 0,
          f"burst: zero post-convergence resizes "
          f"(got {out['post_convergence_resizes']})")

    if verbose:
        print(f"serving selftest: {len(failures)} failure(s)")
    return 1 if failures else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="edl_tpu.scaler.serving",
        description="Serving-elasticity plane (SLO-driven teacher pools)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("selftest",
                   help="jax-free sim smoke: ServingPolicy vs the "
                        "steady/step/burst traces (the CI gate)")
    args = parser.parse_args(argv)
    if args.cmd == "selftest":
        return selftest()
    return 2


if __name__ == "__main__":
    sys.exit(main())
