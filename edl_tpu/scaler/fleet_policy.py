"""Preemptive gang fair-share: revoke batch nodes when serving hurts.

`FairSharePolicy.decide_mixed` already funds pool grows from trainer
shrinks inside one tick's accounting — but only from trainers that
happen to be actionable. At fleet scale that is the gap: the pool
breaching its SLO waits behind cooldown clocks on trainers that just
resized, and a spot preemption deadline does not wait for anyone.

`PreemptiveFairSharePolicy` closes it with an explicit REVOCATION pass
on top of the base decision:

* when the live plan exceeds the budget (a spot notice just shrank the
  effective capacity), it revokes nodes from the lowest tier up until
  the fleet fits — uncapped, because the alternative at the deadline is
  a forced eviction (stop-resume + unsealed progress lost);
* when an SLO-breached pool's grow was held on "awaiting-budget", it
  revokes up to `revocation_budget` nodes per pass from batch /
  best-effort trainers and hands the freed headroom to the worst
  breaches ("slo-preempt-grow");
* every revocation is a SCHEDULED shrink through the reform ladder —
  gang-legal target sizes, never below a batch job's min (best-effort
  gangs may suspend to zero), never from `prod` — not a kill. The
  cooldown/settling holds that protect steady-state convergence are
  deliberately overridden for victims: revocation is the emergency
  path. Jobs with stale stats or a resize already in flight stay
  untouchable.

Like the rest of the scaler decision plane this is pure stdlib, wall-
clock-free, and seed-deterministic (covered by the ``sim-determinism``
edl-lint row); `decide_fleet` is the fleet-simulator entry point that
also folds pending preemption notices into the budget.
"""

from __future__ import annotations

from edl_tpu.scaler.policy import FairSharePolicy, Proposal

TIER_RANK = {"prod": 0, "batch": 1, "best-effort": 2}


def _tier(view) -> str:
    return getattr(view, "tier", "batch")


def _gang(view) -> int:
    return max(1, int(getattr(view, "gang", 1)))


class PreemptiveFairSharePolicy(FairSharePolicy):
    """Fair share + tiered revocation + spot-notice riding."""

    def __init__(self, budget: int, *, revocation_budget: int = 16,
                 **kw):
        super().__init__(budget, **kw)
        # max nodes revoked per pass for SLO relief; capacity
        # enforcement (spot deadlines) is never capped
        self.revocation_budget = revocation_budget
        self.revocations: list[dict] = []

    # -- fleet entry point -------------------------------------------------

    def decide_fleet(self, trainer_views, serving_views, now, *,
                     notices=(), capacity: int | None = None):
        """`decide_mixed` with the budget set to the capacity the fleet
        will have AFTER every pending preemption notice lands — riding
        a notice means being small enough before the deadline, so the
        post-deadline capacity is the only honest budget."""
        if capacity is not None:
            drop = sum(int(n.get("nodes", 0)) for n in notices)
            self.budget = max(0, capacity - drop)
        return self.decide_mixed(trainer_views, serving_views, now)

    # -- the revocation pass -----------------------------------------------

    def decide_mixed(self, trainer_views, serving_views, now):
        t_props, s_props = super().decide_mixed(trainer_views,
                                                serving_views, now)
        return self._revoke(t_props, s_props, trainer_views,
                            serving_views, now)

    def _revoke(self, t_props, s_props, trainer_views, serving_views,
                now):
        tmap = {p.job_id: p for p in t_props}
        smap = {p.job_id: p for p in s_props}
        # post-actuation totals the base decision implies
        planned: dict[str, int] = {}
        for p, v in zip(t_props, trainer_views):
            planned[v.job_id] = (p.desired if p.is_resize
                                 else v.effective_desired)
        pool_planned: dict[str, int] = {}
        for p, v in zip(s_props, serving_views):
            pool_planned[v.service] = (p.desired if p.is_resize
                                       else v.effective_desired)
        total = sum(planned.values()) + sum(pool_planned.values())
        hard_need = max(0, total - self.budget)
        # SLO-breached pools whose grow the base pass could not fund
        blocked = []
        for p, v in zip(s_props, serving_views):
            if p.reason == "awaiting-budget" \
                    and v.latency_ms_p95 > v.slo_p95_ms:
                delta = self.pool_demand(v) - v.effective_desired
                if delta > 0:
                    blocked.append((v, delta))
        soft_need = sum(d for _, d in blocked)
        soft_cap = self.revocation_budget
        if hard_need == 0 and (soft_need == 0 or soft_cap == 0):
            return t_props, s_props

        # victims: lowest tier first, then cheapest goodput per node.
        # Cooldown/settling holds are overridden (emergency path);
        # stale stats or an in-flight resize stay untouchable.
        def actionable(v):
            return (v.fresh and _tier(v) != "prod"
                    and v.effective_desired == v.world_size
                    and planned[v.job_id] > 0)

        victims = sorted(
            (v for v in trainer_views if actionable(v)),
            key=lambda v: (-TIER_RANK.get(_tier(v), 1),
                           v.throughput / v.world_size
                           if v.world_size else 0.0,
                           v.job_id))
        for v in victims:
            want = hard_need + min(soft_need, soft_cap)
            if want <= 0:
                break
            cur = planned[v.job_id]
            gang = _gang(v)
            floor = 0 if _tier(v) == "best-effort" else v.min_nodes
            legal = [n for n in range(floor, cur)
                     if n == 0 or (n % gang == 0 and n >= v.min_nodes)]
            if not legal:
                continue
            # smallest step that covers the remaining need. Gang
            # granularity may force overshooting toward the floor —
            # acceptable for capacity enforcement (the alternative is
            # a forced eviction), pure waste for SLO relief.
            fits = [n for n in legal if n >= cur - want]
            if fits:
                target = min(fits)
            elif hard_need > 0:
                target = legal[0]
            else:
                continue
            yielded = cur - target
            h = min(yielded, hard_need)
            s = min(yielded - h, soft_need, soft_cap)
            if h + s == 0:
                continue
            hard_need -= h
            soft_need -= s
            soft_cap -= s
            planned[v.job_id] = target
            total -= yielded
            tmap[v.job_id] = Proposal(v.job_id, v.world_size, target,
                                      "preempt-revoke")
            self.revocations.append({
                "ts": now, "job": v.job_id, "tier": _tier(v),
                "from": cur, "to": target,
                "for": "capacity" if h else "slo"})
        # hand the freed headroom to the worst breaches first
        avail = max(0, self.budget - total)
        for v, delta in sorted(
                blocked, key=lambda t: (-t[0].latency_ms_p95
                                        / t[0].slo_p95_ms,
                                        t[0].service)):
            grant = min(delta, avail, v.max_teachers
                        - v.effective_desired)
            if grant <= 0:
                continue
            smap[v.service] = Proposal(
                v.service, v.n_teachers,
                v.effective_desired + grant, "slo-preempt-grow")
            pool_planned[v.service] += grant
            avail -= grant
        return ([tmap[v.job_id] for v in trainer_views],
                [smap[v.service] for v in serving_views])

    def stats(self) -> dict:
        by_cause: dict[str, int] = {"capacity": 0, "slo": 0}
        for r in self.revocations:
            by_cause[r["for"]] = by_cause.get(r["for"], 0) + 1
        return {"revocations": len(self.revocations),
                "revocations_by_cause": by_cause}


class GreedyRebalancePolicy(FairSharePolicy):
    """Chase the water-fill plan on RAW observations: no cooldown, no
    EWMA smoothing (``ema=1.0``), and the amortization gate bypassed.

    This is the policy cheap reforms unlock: it re-packs the fleet
    toward the instantaneous optimum every pass and pays a resize for
    every wiggle the observations make. Under the measured ladder
    (0.138 s in-place reform) that tax is negligible and the constant
    re-packing wins noisy regimes; under the legacy ladder (1.2 s
    stop-resume per action) the same behavior bleeds goodput and plain
    fair-share beats it — the ``noisy`` tournament trace is pinned at
    exactly that crossover. It deliberately does NOT read
    ``view.downtime_s``: a ladder-blind contestant is what makes the
    ladder's effect visible in the table."""

    def __init__(self, budget: int, **kw):
        kw.setdefault("cooldown_s", 0.0)
        kw.setdefault("horizon_s", 60.0)
        kw.setdefault("ema", 1.0)
        super().__init__(budget, **kw)

    def _amortizes(self, gain_per_sec: float, view) -> bool:
        return True


def default_policies() -> dict:
    """The tournament's default contestant list: name -> factory (a
    fresh policy per cell so learned curves never leak between runs).
    The placeholder budget is overwritten every decision from the
    fleet's live capacity."""
    kw = dict(cooldown_s=15.0, horizon_s=60.0)
    return {
        "fair-share": lambda: FairSharePolicy(1, **kw),
        "preemptive-fair-share":
            lambda: PreemptiveFairSharePolicy(1, **kw),
        "greedy-rebalance": lambda: GreedyRebalancePolicy(1),
    }
