"""The scaler control loop: Collector snapshots -> policy -> JobServer.

Closes the loop the reference reserved the registry ``info`` field for
("report job performance to the scheduler"): a leader-elected
controller scrapes each job's `Collector` snapshot, digests it into a
`JobView` (aggregate fresh throughput, live world size, generation),
asks the policy, and actuates accepted proposals through the
JobServer's ``/resize`` endpoint — or only journals them under
``--dry-run``.

Exactly one scaler acts: controllers campaign on a lease-backed
leadership key (`coord/lock.LeaderElection`); a follower's ticks are
no-ops, and on takeover the new leader replays the decision journal's
tail to re-learn the throughput models and resume the cooldown clocks
(so a leader crash never causes a double resize).

The serving plane rides the same election and journal
(``services=`` / ``serving_policy=`` / ``serving_actuate=``): teacher
pools are digested from `Collector.service_rollup` into ServingViews,
decided by a `ServingPolicy` (or jointly with the trainers by a budget
policy exposing ``decide_mixed`` — FairShare), and actuated through a
`TeacherPoolActuator`; their journal entries carry ``kind: "serving"``
+ ``service`` so each policy's replay finds its own
(`scaler/serving.py`).

Every decision — hold or resize, with its inputs and reason — is one
JSON journal entry, appended both as a JSON line to ``journal_path``
(observability; ``tail -f``-able) and under the store prefix
``/{scope}/scaler/journal/`` (bounded retention; what a successor
replays).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass
from typing import Callable

from edl_tpu.coord.collector import Collector
from edl_tpu.coord.store import Store
from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import trace
from edl_tpu.scaler.policy import JobView, Proposal, ScalingPolicy
from edl_tpu.utils.config import field
from edl_tpu.utils.exceptions import EdlStoreError
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.scaler.controller")


@dataclass
class ScalerConfig:
    # the decision-pass FALLBACK period: with store watches the
    # controller ticks when fresh utilization actually arrives (floor =
    # min_tick_s), so reaction latency is event latency, not interval/2
    interval: float = field(5.0, env="EDL_TPU_SCALER_INTERVAL")
    # event-driven tick floor: a busy fleet publishing utilization every
    # second must not turn the scaler into a hot loop
    min_tick_s: float = field(1.0, env="EDL_TPU_SCALER_MIN_TICK")
    cooldown_s: float = field(30.0, env="EDL_TPU_SCALER_COOLDOWN")
    gain_threshold: float = field(0.05, env="EDL_TPU_SCALER_GAIN")
    # the resize price the policy amortizes every grow against — the
    # FALLBACK only: the controller measures the real downtime of every
    # resize it actuates (actuation -> first fresh utilization at the
    # new world) and feeds the per-job EWMA into the policy instead, so
    # a faster resize path (p2p live migration) loosens the grow gate
    # without anyone re-tuning a constant
    downtime_s: float = field(1.5, env="EDL_TPU_ELASTIC_DOWNTIME_S")
    # optional bench artifact (BENCH_r*.json) seeding the fallback:
    # extras.elastic_downtime_p2p_s preferred over elastic_downtime_s
    downtime_artifact: str | None = field(None,
                                          env="EDL_TPU_DOWNTIME_ARTIFACT")
    # utilization docs older than this are ignored (published_unix)
    staleness_s: float = field(15.0, env="EDL_TPU_SCALER_STALENESS")
    min_nodes: int = field(1, env="EDL_TPU_SCALER_MIN_NODES")
    max_nodes: int = field(8, env="EDL_TPU_SCALER_MAX_NODES")
    journal_keep: int = 512
    leader_ttl: float = field(10.0, env="EDL_TPU_SCALER_LEADER_TTL")


def journal_prefix(scope: str) -> str:
    return f"/{scope}/scaler/journal/"


def leader_key(scope: str) -> str:
    return f"/{scope}/scaler/leader"


def artifact_downtime(path: str) -> float | None:
    """Read a measured elastic downtime out of a bench artifact
    (``extras.elastic_downtime_p2p_s`` preferred — the live-migration
    number — else ``elastic_downtime_s``). None when unreadable."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    extras = doc.get("extras", doc) or {}
    for key in ("elastic_downtime_p2p_s", "elastic_downtime_s"):
        val = extras.get(key)
        if val is not None:
            try:
                return float(val)
            except (TypeError, ValueError):
                continue
    return None


class DecisionJournal:
    """Append-only decision log: store-backed tail + local JSON lines.

    The store half is the handoff medium (a successor leader replays
    it); the file half is the operator's observability surface. Entries
    are sequence-numbered store keys so lexicographic prefix order IS
    replay order; retention keeps the newest `keep` entries.
    """

    def __init__(self, store: Store | None, scope: str, *,
                 path: str | None = None, keep: int = 512):
        self.store = store
        self.scope = scope
        self.path = path
        self.keep = keep
        self._fh = open(path, "a", encoding="utf-8") if path else None
        self._seq = self._last_seq() + 1

    def _last_seq(self) -> int:
        if self.store is None:
            return -1
        records, _ = self.store.get_prefix(journal_prefix(self.scope))
        if not records:
            return -1
        return int(records[-1].key.rsplit("/", 1)[-1])

    def append(self, entry: dict) -> dict:
        entry = dict(entry, seq=self._seq)
        line = json.dumps(entry, sort_keys=True)
        # File first: the local JSONL is the durable audit trail (the
        # chaos soak's journal<->resize_log invariant reads it), so a
        # store outage between an actuated resize and its journal entry
        # must not lose the record. The store copy is the takeover
        # leader's replay source — best-effort; a missed entry costs a
        # cooldown resume at worst and heals on the next append.
        if self._fh is not None:
            self._fh.write(line + "\n")
            self._fh.flush()
        if self.store is not None:
            prefix = journal_prefix(self.scope)
            try:
                self.store.put(f"{prefix}{self._seq:010d}", line)
                drop = self._seq - self.keep
                if drop >= 0:
                    self.store.delete(f"{prefix}{drop:010d}")
            except EdlStoreError as exc:
                log.warning("journal entry %d not mirrored to the store "
                            "(%s) — file journal has it", self._seq, exc)
        self._seq += 1
        return entry

    def tail(self, n: int | None = None) -> list[dict]:
        if self.store is None:
            return []
        records, _ = self.store.get_prefix(journal_prefix(self.scope))
        if n is not None:
            records = records[-n:]
        out = []
        for rec in records:
            try:
                out.append(json.loads(rec.value))
            except json.JSONDecodeError:
                continue
        return out

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class ScalerController:
    """Scrape -> decide -> actuate -> journal, while leader.

    Args:
      store: coordination store (same one the job runs on).
      jobs: job ids to scale.
      policy: a `ScalingPolicy`.
      job_server: JobServer endpoint ("host:port") for min/max/desired
        and `/resize` actuation; None = store-only (observe + journal).
      actuate: override actuation, e.g. a local `JobState.resize` when
        the controller runs inside the JobServer process. Signature
        ``(job_id, desired) -> snapshot dict``.
      dry_run: never actuate; decisions are journaled with action
        "dry-run".
      clock: injectable time source (tests); defaults to time.time so
        journal timestamps and `published_unix` share one scale.
    """

    def __init__(self, store: Store, jobs: list[str],
                 policy: ScalingPolicy, *,
                 config: ScalerConfig | None = None,
                 job_server: str | None = None,
                 actuate: Callable[[str, int], dict] | None = None,
                 dry_run: bool = False,
                 journal_path: str | None = None,
                 scope: str | None = None,
                 owner: str | None = None,
                 elect: bool = True,
                 services: list[str] | tuple[str, ...] = (),
                 serving_policy=None,
                 serving_actuate: Callable[[str, int], dict] | None = None,
                 serving_config=None,
                 registry_root: str = "edl_distill",
                 clock: Callable[[], float] = time.time):
        self.store = store
        self.jobs = list(jobs)
        self.policy = policy
        self.config = config or ScalerConfig()
        if job_server is not None and len(self.jobs) > 1:
            # one JobServer holds ONE job's state: sharing it would read
            # the same min/max/desired for every job and land every
            # /resize on the same JobState (jobs overwriting each other)
            raise ValueError("job_server actuates a single job; run "
                             "store-only or one controller per job")
        self.job_server = job_server
        self._actuate_fn = actuate
        self.dry_run = dry_run
        # the serving plane, scaled side by side with the trainer jobs
        # under the SAME leader election and journal: either its own
        # ServingPolicy (serving_policy=...) or jointly with the
        # trainers by a budget policy exposing decide_mixed (FairShare)
        self.services = list(services)
        self.serving_policy = serving_policy
        self._serving_actuate = serving_actuate
        self.serving_config = serving_config
        self.registry_root = registry_root
        self._service_collector = None
        self._serving_desired: dict[str, int] = {}
        if self.services:
            if self.serving_policy is None \
                    and not hasattr(self.policy, "decide_mixed"):
                raise ValueError(
                    "services need a serving_policy (ServingPolicy) or "
                    "a budget policy with decide_mixed (FairSharePolicy)")
            if self.serving_config is None:
                from edl_tpu.scaler.serving import ServingConfig
                from edl_tpu.utils.config import from_env
                self.serving_config = from_env(ServingConfig)
            self._service_collector = Collector(
                store, services=tuple(self.services),
                registry_root=registry_root)
        self.scope = scope or (self.jobs[0] if len(self.jobs) == 1
                               else (self.services[0]
                                     if not self.jobs
                                     and len(self.services) == 1
                                     else "cluster"))
        self.owner = owner or f"{socket.gethostname()}-{os.getpid()}"
        self.clock = clock
        self.journal = DecisionJournal(store, self.scope,
                                       path=journal_path,
                                       keep=self.config.journal_keep)
        self._collectors = {j: Collector(store, job_id=j)
                            for j in self.jobs}
        # Measured-downtime feedback: resizes this controller actuated,
        # awaiting their first fresh utilization at the new world — the
        # close of a probe updates the per-job EWMA that replaces the
        # configured downtime constant in every subsequent JobView
        # (quantized to the tick interval, so it over- rather than
        # under-charges the amortization gate).
        self._downtime: dict[str, float] = {}
        # the per-ACTION ladder the fleet simulator prices with
        # (scaler/fleet.py DowntimeLadder): a shrink the survivors
        # adopt in place is ~20x cheaper than a grow's reform, so one
        # blended EWMA systematically over-charges shrinks. Keyed
        # (job, kind); the blended EWMA above stays the fallback for
        # kinds never yet measured.
        self._downtime_kind: dict[tuple[str, str], float] = {}
        self._resize_pending: dict[str, tuple[float, int, str]] = {}
        self._observed_downtime: dict[str, float] = {}  # this tick's
        self._observed_kind: dict[str, str] = {}        # this tick's
        self._default_downtime = self.config.downtime_s
        if self.config.downtime_artifact:
            seeded = artifact_downtime(self.config.downtime_artifact)
            if seeded is not None:
                self._default_downtime = seeded
                log.info("downtime fallback seeded from %s: %.2fs",
                         self.config.downtime_artifact, seeded)
        self.election = None
        if elect:
            from edl_tpu.coord.lock import LeaderElection
            self.election = LeaderElection(
                store, leader_key(self.scope), self.owner,
                ttl=self.config.leader_ttl)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._restored = False
        # event-driven pacing: fresh utilization under /{job}/util/
        # kicks the next tick instead of waiting out the interval
        self._kick = threading.Event()
        self._util_watches: list = []
        # decision-plane counters; the journal stays the audit trail,
        # the obs registry serves the same tallies as gauges
        self._n_ticks = 0
        self._n_resizes = 0
        self._obs = obs_metrics.register_stats("scaler", self.stats)

    def stats(self) -> dict:
        """Controller counters as a dict view (obs registry source)."""
        return {"is_leader": self.is_leader(),
                "ticks": self._n_ticks,
                "resizes_actuated": self._n_resizes,
                "jobs": len(self.jobs),
                "services": len(self.services),
                "resize_pending": len(self._resize_pending),
                "journal_seq": self.journal._seq}

    # -- observation --------------------------------------------------------

    def _job_limits(self, job_id: str) -> tuple[int, int, int | None]:
        """(min, max, desired) from the JobServer, else config defaults."""
        if self.job_server is not None:
            from edl_tpu.collective.job_server import get_job
            try:
                doc = get_job(self.job_server)
                return (int(doc["min_nodes"]), int(doc["max_nodes"]),
                        int(doc["desired_nodes"]))
            except (OSError, KeyError, ValueError) as exc:
                log.warning("job server unreachable (%s); using config "
                            "limits", exc)
        return self.config.min_nodes, self.config.max_nodes, None

    def observe(self, job_id: str, now: float | None = None) -> JobView:
        """Digest one Collector snapshot into the policy's JobView."""
        now = self.clock() if now is None else now
        snap = self._collectors[job_id].snapshot()
        job = snap.get("job") or {}
        world = int(job.get("world_size") or 0)
        lo, hi, desired = self._job_limits(job_id)
        throughput, fresh_pods = 0.0, 0
        for pod in job.get("pods") or []:
            util = pod.get("utilization")
            if not isinstance(util, dict):
                continue
            published = util.get("published_unix", util.get("ts"))
            if published is None \
                    or now - float(published) > self.config.staleness_s:
                continue  # stale: a dead pod's lease hasn't expired yet
            # both sides are POD counts: the publisher's world_size is
            # the elastic world (EDL_TPU_WORLD_SIZE), `world` is
            # Cluster.world_size — never the per-pod device count
            pod_world = util.get("world_size")
            if pod_world is not None and world and int(pod_world) != world:
                continue  # pre-resize record: wrong allocation's rate
            throughput += float(util.get("examples_per_sec", 0.0))
            fresh_pods += 1
        fresh = bool(fresh_pods) and world > 0
        self._note_downtime(job_id, world, fresh, now)
        # the view's downtime is the GROW price (a reform): that is the
        # action the amortization gate actually guards, and the most
        # expensive one — pricing shrinks with it only over-charges
        downtime = self._downtime_kind.get(
            (job_id, "reform"),
            self._downtime.get(job_id, self._default_downtime))
        return JobView(job_id, world, throughput, lo, hi,
                       downtime,
                       generation=job.get("generation"),
                       desired=desired,
                       fresh=fresh)

    def _note_downtime(self, job_id: str, world: int, fresh: bool,
                       now: float) -> None:
        """Close an open downtime probe: the first FRESH utilization at
        the resize's target world stamps `actuation -> now` as that
        resize's measured downtime and folds it into the per-job EWMA
        the policy amortizes against."""
        pending = self._resize_pending.get(job_id)
        if pending is None or not fresh:
            return
        ts, target, kind = pending
        if world != target:
            return
        measured = max(0.0, now - ts)
        prev = self._downtime.get(job_id)
        self._downtime[job_id] = (measured if prev is None
                                  else 0.5 * prev + 0.5 * measured)
        kprev = self._downtime_kind.get((job_id, kind))
        self._downtime_kind[(job_id, kind)] = (
            measured if kprev is None else 0.5 * kprev + 0.5 * measured)
        self._observed_downtime[job_id] = measured
        self._observed_kind[job_id] = kind
        del self._resize_pending[job_id]
        log.info("measured elastic downtime for %s: %.2fs (%s ema "
                 "%.2fs)", job_id, measured, kind,
                 self._downtime_kind[(job_id, kind)])

    def observe_service(self, service: str):
        """Digest one `Collector.service_rollup` into the serving
        policy's ServingView. ``desired`` is the last actuated target
        (resize-in-flight detection: the actuator spawns/drains
        asynchronously, so the registry trails the decision)."""
        from edl_tpu.scaler.serving import ServingView
        roll = self._service_collector.service_rollup(service)
        cfg = self.serving_config
        n = roll["n_teachers"]
        desired = self._serving_desired.get(service)
        if desired is not None and desired == n:
            # the pool caught up with the target: back to steady state
            del self._serving_desired[service]
            desired = None
        return ServingView(
            service, n,
            rows_per_sec=roll["rows_per_sec"],
            util=roll["util"] if roll["util"] is not None else 0.0,
            queue_depth=roll["queue_depth"],
            latency_ms_p50=roll["latency_ms_p50"],
            latency_ms_p95=roll["latency_ms_p95"],
            shed_per_sec=roll.get("shed_per_sec", 0.0),
            queue_depth_by_class=roll.get("queue_depth_by_class") or None,
            latency_ms_p95_by_class=(roll.get("latency_ms_p95_by_class")
                                     or None),
            draining=roll.get("draining", 0),
            slo_p95_ms=cfg.slo_p95_ms,
            min_teachers=cfg.min_teachers,
            max_teachers=cfg.max_teachers,
            desired=desired,
            fresh=bool(n and roll["reporting"]))

    # -- actuation ----------------------------------------------------------

    def _actuate(self, job_id: str, desired: int) -> dict:
        if self._actuate_fn is not None:
            return self._actuate_fn(job_id, desired)
        if self.job_server is None:
            raise RuntimeError("no actuation path (job_server/actuate)")
        from edl_tpu.collective.job_server import request_resize
        return request_resize(self.job_server, desired)

    # -- the loop -----------------------------------------------------------

    def is_leader(self) -> bool:
        return self.election is None or self.election.is_leader()

    def _restore_from_journal(self) -> None:
        entries = self.journal.tail()
        if entries:
            self.policy.restore(entries)
            if self.serving_policy is not None:
                self.serving_policy.restore(entries)
            # replay measured downtimes too: a takeover leader must not
            # fall back to the configured constant when the journal
            # already recorded how fast this fleet really resizes
            for e in entries:
                job, m = e.get("job_id"), e.get("observed_downtime_s")
                if job and m is not None:
                    prev = self._downtime.get(job)
                    self._downtime[job] = (float(m) if prev is None
                                           else 0.5 * prev + 0.5 * float(m))
                    kind = e.get("downtime_kind")
                    if kind:
                        kprev = self._downtime_kind.get((job, kind))
                        self._downtime_kind[(job, kind)] = (
                            float(m) if kprev is None
                            else 0.5 * kprev + 0.5 * float(m))
            log.info("restored %d journal entries (scope %s)",
                     len(entries), self.scope)
        self._restored = True

    def tick(self, now: float | None = None) -> list[dict]:
        """One decision pass; returns the journal entries it wrote."""
        if not self.is_leader():
            return []
        if not self._restored:
            self._restore_from_journal()
        now = self.clock() if now is None else now
        self._n_ticks += 1
        views = [self.observe(j, now) for j in self.jobs]
        serving_views = [self.observe_service(s) for s in self.services]
        if serving_views and self.serving_policy is None:
            # one budget policy governs both planes (FairShare mixed)
            proposals, serving_props = self.policy.decide_mixed(
                views, serving_views, now)
        else:
            proposals = self.policy.decide(views, now) if views else []
            serving_props = (self.serving_policy.decide(serving_views, now)
                             if serving_views else [])
        entries = []
        for view, prop in zip(views, proposals):
            entries.append(self._apply(view, prop, now))
        for view, prop in zip(serving_views, serving_props):
            entries.append(self._apply_serving(view, prop, now))
        return entries

    def _apply(self, view: JobView, prop: Proposal, now: float) -> dict:
        action, reason = "hold", prop.reason
        applied = None
        if prop.is_resize:
            if self.dry_run:
                action = "dry-run"
            else:
                try:
                    # trace root of the resize: the /resize actuation,
                    # the epoch publication, the surviving trainers'
                    # adoptions and the peer restores all parent onto
                    # this span (obs/trace.py propagation contract)
                    with trace.span("scaler.decide",
                                    attrs={"job": view.job_id,
                                           "from": prop.current,
                                           "to": prop.desired,
                                           "reason": prop.reason}):
                        resp = self._actuate(view.job_id, prop.desired)
                    applied = int(resp.get("desired_nodes", prop.desired))
                    action = "resize"
                    self._n_resizes += 1
                    if resp.get("clamped"):
                        reason += "; clamped by job server"
                    self.policy.notify_resized(view.job_id, applied, now)
                    # arm the downtime probe (closed by _note_downtime
                    # on the first fresh record at the new world; a
                    # follow-up resize re-arms it at the newer target).
                    # The kind matches the fleet ladder's taxonomy: a
                    # shrink is an in-place adopt, a grow a reform.
                    kind = ("adopt" if applied < prop.current
                            else "reform")
                    self._resize_pending[view.job_id] = (now, applied,
                                                         kind)
                    log.info("resize %s: %d -> %d (%s)", view.job_id,
                             prop.current, applied, prop.reason)
                except Exception as exc:  # noqa: BLE001 — journal it;
                    # a dead job server must not kill the control loop
                    action, reason = "error", f"{prop.reason}; {exc}"
        return self.journal.append({
            "ts": now, "job_id": view.job_id, "leader": self.owner,
            "world_size": view.world_size,
            "throughput": round(view.throughput, 3),
            "generation": view.generation, "fresh": view.fresh,
            "current": prop.current, "desired": prop.desired,
            "applied": applied, "action": action, "reason": reason,
            # the downtime charge this decision amortized against, and
            # (when a probe closed this tick) the freshly measured value
            "downtime_s": round(view.downtime_s, 3),
            "observed_downtime_s": (
                round(self._observed_downtime.pop(view.job_id), 3)
                if view.job_id in self._observed_downtime else None),
            "downtime_kind": self._observed_kind.pop(view.job_id, None),
            "predicted_gain": (round(prop.predicted_gain, 3)
                               if prop.predicted_gain is not None
                               else None)})

    def _apply_serving(self, view, prop: Proposal, now: float) -> dict:
        """Actuate + journal one serving-plane proposal. Entries carry
        ``kind: "serving"`` + ``service`` (no ``job_id``), so trainer
        policies skip them on replay and `ServingPolicy.restore` finds
        its own."""
        action, reason = "hold", prop.reason
        applied = None
        if prop.is_resize:
            if self.dry_run:
                action = "dry-run"
            elif self._serving_actuate is None:
                # observe-only deployments journal what they WOULD do
                action, reason = "error", (f"{prop.reason}; no serving "
                                           "actuation path")
            else:
                try:
                    resp = self._serving_actuate(view.service, prop.desired)
                    applied = int(resp.get("desired_teachers",
                                           prop.desired))
                    action = "resize"
                    if resp.get("clamped"):
                        reason += "; clamped by actuator"
                    self._serving_desired[view.service] = applied
                    pol = self.serving_policy or self.policy
                    pol.notify_resized(view.service, applied, now)
                    log.info("resize pool %s: %d -> %d (%s)", view.service,
                             prop.current, applied, prop.reason)
                except Exception as exc:  # noqa: BLE001 — journal it; a
                    # dead actuator must not kill the control loop
                    action, reason = "error", f"{prop.reason}; {exc}"
        return self.journal.append({
            "ts": now, "kind": "serving", "service": view.service,
            "leader": self.owner, "n_teachers": view.n_teachers,
            "rows_per_sec": round(view.rows_per_sec, 2),
            "util": round(view.util, 4),
            "queue_depth": view.queue_depth,
            "latency_ms_p95": view.latency_ms_p95,
            "shed_per_sec": round(view.shed_per_sec, 2),
            "slo_p95_ms": view.slo_p95_ms, "fresh": view.fresh,
            "current": prop.current, "desired": prop.desired,
            "applied": applied, "action": action, "reason": reason})

    # -- event-driven pacing -------------------------------------------------

    def _start_util_watches(self) -> None:
        """Subscribe to each job's utilization prefix: a fresh record
        kicks the next decision pass so reaction latency is event
        latency (floored at min_tick_s), with `interval` demoted to the
        no-traffic fallback. Unavailable/disabled watches leave the
        original fixed-interval loop untouched."""
        from edl_tpu.coord.collector import util_prefix
        from edl_tpu.coord.registry import ServiceRegistry
        from edl_tpu.coord.store import try_watch
        prefixes = [(job, util_prefix(job)) for job in self.jobs]
        if self.services:
            # registrar stats updates land on the service registry
            # prefix: the serving plane ticks at event latency too
            registry = ServiceRegistry(self.store, root=self.registry_root)
            prefixes += [(svc, registry.service_prefix(svc))
                         for svc in self.services]
        for name, prefix in prefixes:
            watch = try_watch(self.store, prefix)
            if watch is None:
                continue
            thread = threading.Thread(target=self._pump_kicks, args=(watch,),
                                      daemon=True,
                                      name=f"edl-scaler-watch-{name}")
            thread.start()
            self._util_watches.append((watch, thread))
        if self._util_watches:
            log.info("scaler ticking on utilization events (%d watches; "
                     "fallback every %.1fs)", len(self._util_watches),
                     self.config.interval)

    def _pump_kicks(self, watch) -> None:
        while not self._stop.is_set():
            batch = watch.get(timeout=5.0)
            if batch is None:
                if watch.cancelled:
                    return
                continue
            if batch.events or batch.compacted:
                self._kick.set()

    def _stop_util_watches(self) -> None:
        for watch, _ in self._util_watches:
            watch.cancel()
        for _, thread in self._util_watches:
            thread.join(timeout=2.0)
        self._util_watches = []

    def run(self) -> None:
        """Campaign, then tick on fresh utilization (or every interval
        as the fallback) while leader (blocking)."""
        self._start_util_watches()
        try:
            while not self._stop.is_set():
                if self.election is not None \
                        and not self.election.is_leader():
                    try:
                        won = self.election.campaign(timeout=1.0)
                    except EdlStoreError as exc:
                        # store outage mid-campaign (leader failover,
                        # partition): the scaler must outlive it and
                        # re-campaign, not die silently
                        log.warning("scaler campaign failed: %s", exc)
                        if self._stop.wait(timeout=1.0):
                            break
                        continue
                    if not won:
                        continue
                    log.info("scaler leadership acquired (%s)", self.owner)
                    self._restored = False  # re-replay on every takeover
                self._kick.clear()
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — scrape failures are
                    log.exception("scaler tick failed")  # transient
                # kicks that landed during the tick are still set here
                if self._kick.wait(timeout=self.config.interval) \
                        and not self._stop.is_set():
                    self._stop.wait(self.config.min_tick_s)
        finally:
            self._stop_util_watches()

    def start(self) -> "ScalerController":
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="edl-scaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()  # wake the fallback wait immediately
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self.election is not None:
            self.election.resign()
        self.journal.close()
        obs_metrics.unregister(self._obs)
