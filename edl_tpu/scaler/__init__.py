"""Elastic autoscaler plane: utilization in, resize decisions out.

jax-free by design (like the coord/ plane): the policies, simulator,
and controller import nothing heavier than the coordination store, so
`python -m edl_tpu.scaler` runs on a scheduler node with no
accelerator stack installed.

- `scaler.policy` — `ScalingPolicy` protocol, `ThroughputPolicy`
  (marginal-gain autoscaling w/ hysteresis + downtime amortization),
  `FairSharePolicy` (budget water-fill across jobs).
- `scaler.controller` — leader-elected Collector->policy->JobServer
  loop with a store+file decision journal and `--dry-run`.
- `scaler.simulator` — deterministic `SimCluster` (synthetic scaling
  curves, seeded noise, virtual time) for tests and benches.
"""

from edl_tpu.scaler.policy import (FairSharePolicy, JobView, Proposal,
                                   ScalingPolicy, ThroughputPolicy)
from edl_tpu.scaler.controller import (DecisionJournal, ScalerConfig,
                                       ScalerController)

__all__ = ["FairSharePolicy", "JobView", "Proposal", "ScalingPolicy",
           "ThroughputPolicy", "DecisionJournal", "ScalerConfig",
           "ScalerController"]
