"""Scaler CLI: run the decision plane against a live store + JobServer.

    python -m edl_tpu.scaler --store STOREHOST:2379 --job myjob \
        --server JOBSERVERHOST:8180 --interval 5

    # observe-only (decisions journaled, nothing actuated):
    python -m edl_tpu.scaler --store ... --job myjob --dry-run

Flags are flag-else-env (`EDL_TPU_SCALER_*`; utils/config overlay).
`--policy fairshare --budget N` scales several `--job`s against one
node budget by marginal throughput — store-only (a `--server` holds a
single job's state, so it cannot be combined with multiple `--job`).

`--service NAME` adds a teacher pool to the loop (the serving
elasticity plane, `scaler/serving.py`): its registrar-published
latency/queue/utilization rollup drives a `ServingPolicy` targeting
`--slo-p95-ms` (or, under `--policy fairshare`, the pool joins the
trainer jobs in one budget water-fill). From this CLI the serving
plane observes and journals only — the `TeacherPoolActuator` lives in
the process that owns the pool.
"""

from __future__ import annotations

import argparse
import json
import sys

from edl_tpu.scaler.controller import ScalerConfig, ScalerController
from edl_tpu.scaler.policy import FairSharePolicy, ThroughputPolicy
from edl_tpu.utils.config import from_env


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="edl_tpu.scaler",
        description="Elastic autoscaler: Collector -> policy -> JobServer")
    parser.add_argument("--store", required=True,
                        help="store endpoint (host:port or redis://...)")
    parser.add_argument("--job", action="append", default=[],
                        dest="jobs", help="job id (repeatable)")
    parser.add_argument("--service", action="append", default=[],
                        dest="services",
                        help="teacher-pool service name to scale by its "
                             "serving SLO (repeatable; observe/journal "
                             "only from this CLI — live actuation runs "
                             "where the pool runs, e.g. elastic_demo "
                             "--serve-scaler or an embedded "
                             "TeacherPoolActuator)")
    parser.add_argument("--slo-p95-ms", type=float, default=None,
                        help="serving SLO target "
                             "(EDL_TPU_SERVE_SLO_P95_MS)")
    parser.add_argument("--registry-root", default="edl_distill",
                        help="service registry root for --service")
    parser.add_argument("--server", default=None,
                        help="JobServer host:port for limits + /resize")
    parser.add_argument("--policy", choices=("throughput", "fairshare"),
                        default="throughput")
    parser.add_argument("--budget", type=int, default=None,
                        help="node budget (fairshare policy)")
    parser.add_argument("--interval", type=float, default=None,
                        help="decision interval s "
                             "(EDL_TPU_SCALER_INTERVAL)")
    parser.add_argument("--cooldown", type=float, default=None,
                        help="per-job seconds between resizes")
    parser.add_argument("--gain-threshold", type=float, default=None,
                        help="hysteresis: min relative marginal gain")
    parser.add_argument("--downtime-s", type=float, default=None,
                        help="measured elastic_downtime_s to amortize "
                             "(EDL_TPU_ELASTIC_DOWNTIME_S)")
    parser.add_argument("--journal", default=None,
                        help="JSON-lines decision journal file")
    parser.add_argument("--dry-run", action="store_true",
                        help="journal decisions without actuating")
    parser.add_argument("--once", action="store_true",
                        help="one tick (skips leader election), then exit")
    args = parser.parse_args(argv)
    if not args.jobs and not args.services:
        parser.error("at least one --job or --service is required")
    if args.policy == "fairshare" and args.budget is None:
        parser.error("--policy fairshare requires --budget")
    if args.server and len(args.jobs) > 1:
        # one JobServer holds ONE job's state: sharing it would read the
        # same min/max/desired for every job and land every /resize on
        # the same JobState, the jobs overwriting each other each tick
        parser.error("--server actuates a single job; with multiple "
                     "--job run store-only (omit --server, decisions "
                     "are journaled) or one scaler per job")

    overrides = {k: v for k, v in (
        ("interval", args.interval), ("cooldown_s", args.cooldown),
        ("gain_threshold", args.gain_threshold),
        ("downtime_s", args.downtime_s)) if v is not None}
    config = from_env(ScalerConfig, **overrides)
    policy_kw = dict(gain_threshold=config.gain_threshold,
                     cooldown_s=config.cooldown_s)
    policy = (FairSharePolicy(args.budget, **policy_kw)
              if args.policy == "fairshare"
              else ThroughputPolicy(**policy_kw))
    serving_policy, serving_config = None, None
    if args.services:
        from edl_tpu.scaler.serving import ServingConfig, ServingPolicy
        serve_overrides = {}
        if args.slo_p95_ms is not None:
            serve_overrides["slo_p95_ms"] = args.slo_p95_ms
        serving_config = from_env(ServingConfig, **serve_overrides)
        if args.policy != "fairshare":
            # fairshare runs both planes itself (decide_mixed); the
            # throughput policy pairs with a dedicated ServingPolicy
            serving_policy = ServingPolicy(serving_config)

    from edl_tpu.coord.redis_store import connect_store
    store = connect_store(args.store)
    controller = ScalerController(
        store, args.jobs, policy, config=config,
        job_server=args.server, dry_run=args.dry_run,
        journal_path=args.journal, elect=not args.once,
        services=args.services, serving_policy=serving_policy,
        serving_config=serving_config,
        registry_root=args.registry_root)
    try:
        if args.once:
            for entry in controller.tick():
                print(json.dumps(entry, sort_keys=True), flush=True)
            return 0
        controller.run()
        return 0
    except KeyboardInterrupt:
        return 0
    finally:
        controller.stop()
        store.close()


if __name__ == "__main__":
    sys.exit(main())
