"""Fleet simulator: hundreds of jobs and pools under one node budget.

`SimCluster` proves a policy on a handful of clean curves; this module
scales the same deterministic substrate to FLEET shape — the regime the
reference's cluster-level TrainingJob controller actually schedules:

* hundreds of `SimJob` trainers AND `SimServingPool`s composed from
  seeded arrival/departure traces (`FleetTrace.generate`), each job
  with a priority tier (``prod`` / ``batch`` / ``best-effort``) and a
  GANG constraint — a job runs at multiples of its gang size between
  min and max nodes, or not at all;
* per-action downtime charged from a `DowntimeLadder` seeded by the
  MEASURED bench numbers (0.061 s p2p adopt / 0.138 s in-place reform /
  ~1.2 s stop-resume, r12/r20) instead of one blended constant —
  shrinks adopt, grows reform, forced evictions stop-resume. A LEGACY
  ladder (everything costs the disk stop-resume) is kept so policy
  tournaments can show that cheap reforms change which policies win;
* SPOT capacity: a seeded fraction of the fleet's nodes is revocable.
  Preemptions arrive as NOTICES (capacity drop + deadline, the cloud
  spot contract); a policy that shrinks the fleet under the post-
  deadline capacity before the deadline pays only cheap scheduled
  shrinks, while a notice-blind policy is force-evicted at the
  deadline — stop-resume downtime plus the UNSEALED progress since the
  job's last checkpoint seal, exactly the price the live chaos
  ``preempt`` fault audits (chaos/audit.py I7).

Everything is virtual-clock + `random.Random(seed)` — no wall clock,
no global RNG (the ``sim-determinism`` edl-lint row covers this file) —
so a 200-job tournament is exactly reproducible and sha256-pinnable
(`tools/fleet_bench.py`, `bench.py::bench_fleet`).

Pure stdlib, jax/numpy-free (scaler layer row in layers.toml; the CI
selftest runs before any dependency install and asserts it).
"""

from __future__ import annotations

import hashlib
import json
import random
import sys
from dataclasses import dataclass, field

from edl_tpu.scaler.policy import JobView, Proposal
from edl_tpu.scaler.simulator import (ArrivalTrace, ScalingCurve, SimJob,
                                      SimServingPool, burst, concave, knee,
                                      linear, steady, step)
from edl_tpu.utils.config import env_float, env_int

TIERS = ("prod", "batch", "best-effort")
TIER_RANK = {t: i for i, t in enumerate(TIERS)}

# Measured resize-ladder numbers (bench.py artifacts: r12 p2p adoption,
# r20 in-place reform, r9 disk stop-resume). The defaults double as the
# documented fallback when no artifact is supplied.
MEASURED_ADOPT_S = 0.061
MEASURED_REFORM_S = 0.138
MEASURED_STOP_RESUME_S = 1.2


@dataclass(frozen=True)
class DowntimeLadder:
    """Seconds of zero progress per resize ACTION KIND.

    The classification mirrors the live stack: a shrink keeps every
    survivor's device set unchanged (p2p adoption), a grow re-forms the
    mesh in place with peer restore, and only a forced eviction — or a
    world that lost its state — pays the full disk stop-resume.
    """

    name: str = "measured"
    adopt_s: float = MEASURED_ADOPT_S
    reform_s: float = MEASURED_REFORM_S
    stop_resume_s: float = MEASURED_STOP_RESUME_S

    def cost(self, kind: str) -> float:
        return {"adopt": self.adopt_s, "reform": self.reform_s,
                "stop-resume": self.stop_resume_s}[kind]

    def classify(self, current: int, desired: int) -> str:
        """Action kind of a SCHEDULED resize (forced evictions are
        always ``stop-resume`` and never come through here)."""
        return "adopt" if desired < current else "reform"

    @classmethod
    def from_artifact(cls, path: str) -> "DowntimeLadder | None":
        """Build a ladder from a bench artifact's measured extras
        (``elastic_downtime_p2p_s`` -> adopt,
        ``elastic_downtime_multihost_s`` -> reform,
        ``elastic_downtime_s`` -> stop-resume; missing keys keep the
        defaults). None when the file is unreadable."""
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        extras = doc.get("extras", doc) or {}

        def _get(key: str, default: float) -> float:
            try:
                val = extras.get(key)
                return float(val) if val is not None else default
            except (TypeError, ValueError):
                return default

        return cls(name=f"artifact:{path}",
                   adopt_s=_get("elastic_downtime_p2p_s",
                                MEASURED_ADOPT_S),
                   reform_s=_get("elastic_downtime_multihost_s",
                                 MEASURED_REFORM_S),
                   stop_resume_s=_get("elastic_downtime_s",
                                      MEASURED_STOP_RESUME_S))


MEASURED = DowntimeLadder("measured")
# The pre-r12 world: every resize is a disk stop-resume. Tournaments
# run both ladders because the POLICY ranking depends on the ladder —
# preemptive revocation only pays when a scheduled shrink is cheap.
LEGACY = DowntimeLadder("legacy", MEASURED_STOP_RESUME_S,
                        MEASURED_STOP_RESUME_S, MEASURED_STOP_RESUME_S)


@dataclass
class FleetJobView(JobView):
    """JobView + the fleet-scheduling facts a preemptive policy needs.

    ``downtime_s`` carries the ladder's GROW charge (reform) — grows
    are what the amortization gate prices; shrinks ride the cheaper
    adopt path and revocation decisions read the ladder directly."""

    tier: str = "batch"
    gang: int = 1


@dataclass(frozen=True)
class FleetJobSpec:
    job_id: str
    curve: ScalingCurve
    tier: str = "batch"
    gang: int = 1
    min_nodes: int = 1
    max_nodes: int = 8
    arrive_tick: int = 0
    depart_tick: int | None = None
    noise: float = 0.01


@dataclass(frozen=True)
class FleetPoolSpec:
    service: str
    trace: ArrivalTrace
    tenant: str = "default"
    slo_p95_ms: float = 250.0
    teacher_rate: float = 250.0
    teachers: int = 1
    min_teachers: int = 1
    max_teachers: int = 8
    arrive_tick: int = 0


@dataclass(frozen=True)
class Preemption:
    """One spot revocation: ``nodes`` leave capacity at
    ``deadline_tick``; the notice is visible from ``notice_tick``; a
    replacement grant restores the capacity at ``restore_tick``."""

    notice_tick: int
    deadline_tick: int
    nodes: int
    restore_tick: int


@dataclass
class FleetTrace:
    """A seeded fleet scenario: who arrives when, on what capacity."""

    name: str
    seed: int
    ticks: int
    jobs: list[FleetJobSpec]
    pools: list[FleetPoolSpec]
    reserved_nodes: int
    spot_nodes: int
    preemptions: list[Preemption] = field(default_factory=list)

    @property
    def total_nodes(self) -> int:
        return self.reserved_nodes + self.spot_nodes

    @property
    def spot_fraction(self) -> float:
        total = self.total_nodes
        return self.spot_nodes / total if total else 0.0

    @classmethod
    def generate(cls, name: str, seed: int, *, n_jobs: int = 180,
                 n_pools: int = 24, ticks: int = 240,
                 spot_fraction: float = 0.0, churn: float = 0.0,
                 noise: float = 0.01, pool_surge: bool = True,
                 preempt_every: int = 40,
                 notice_ticks: int = 2) -> "FleetTrace":
        """Seeded fleet scenario. ``churn`` is the fraction of jobs
        that arrive late / depart early; ``noise`` is the per-job
        multiplicative sigma on observed rates; ``spot_fraction`` of
        the node budget is revocable with a seeded preemption every
        ``preempt_every`` ticks (notice ``notice_ticks`` ahead of the
        deadline, replacement grant 4 ticks after it)."""
        rng = random.Random(seed)
        jobs: list[FleetJobSpec] = []
        for i in range(n_jobs):
            kind = rng.choice(("concave", "knee", "linear", "flat-ish"))
            r1 = rng.uniform(40.0, 160.0)
            if kind == "concave":
                curve = concave(r1, rng.uniform(0.35, 0.8))
            elif kind == "knee":
                curve = knee(r1, rng.randint(2, 6))
            elif kind == "linear":
                curve = linear(r1)
            else:
                curve = concave(r1, 0.15)  # near-flat
            tier = rng.choices(TIERS, weights=(1, 5, 3))[0]
            gang = rng.choice((1, 1, 2, 2, 4))
            min_nodes = gang
            max_nodes = gang * rng.randint(2, max(2, 8 // gang))
            arrive, depart = 0, None
            if rng.random() < churn:
                arrive = rng.randint(1, max(1, ticks // 3))
                if rng.random() < 0.5:
                    depart = rng.randint(arrive + ticks // 4, ticks)
            jobs.append(FleetJobSpec(f"j{i:03d}", curve, tier, gang,
                                     min_nodes, max_nodes, arrive,
                                     depart, noise=noise))
        pools: list[FleetPoolSpec] = []
        for i in range(n_pools):
            lam = rng.uniform(120.0, 260.0)
            if pool_surge and rng.random() < 0.5:
                at = rng.randint(ticks // 4, 3 * ticks // 4)
                trace = (step(lam, rng.uniform(2.5, 4.0), at)
                         if rng.random() < 0.5 else
                         burst(lam, rng.uniform(2.5, 4.0), at,
                               rng.randint(20, 40)))
            else:
                trace = steady(lam)
            pools.append(FleetPoolSpec(
                f"svc{i:02d}", trace, tenant=f"tenant{i % 6}",
                teachers=1, max_teachers=8,
                arrive_tick=0 if i < n_pools - n_pools // 4
                else rng.randint(1, ticks // 4)))
        # size the budget so the fleet is genuinely contended: roughly
        # half of the summed max demand
        demand = (sum(j.max_nodes for j in jobs)
                  + sum(p.max_teachers for p in pools))
        total = max(8, int(demand * 0.45))
        spot = int(total * spot_fraction)
        preemptions: list[Preemption] = []
        if spot:
            t = preempt_every
            while t + notice_ticks < ticks - 8:
                k = max(1, int(spot * rng.uniform(0.2, 0.5)))
                preemptions.append(Preemption(
                    notice_tick=t, deadline_tick=t + notice_ticks,
                    nodes=k, restore_tick=t + notice_ticks + 4))
                t += preempt_every + rng.randint(-4, 4)
        return cls(name, seed, ticks, jobs, pools,
                   reserved_nodes=total - spot, spot_nodes=spot,
                   preemptions=preemptions)


def trace_menu(*, n_jobs: int = 180, n_pools: int = 24,
               ticks: int = 240) -> list[FleetTrace]:
    """The tournament's trace grid — four fleet regimes, each at
    >= ``n_jobs + n_pools`` concurrent workloads. ``noisy`` sits at
    the rebalance-profitability boundary: raw-observation chasing
    (GreedyRebalancePolicy) wins it under the measured reform ladder
    and loses it under legacy stop-resume pricing — the cell where
    cheap reforms change which policy wins."""
    return [
        FleetTrace.generate("steady-surge", 11, n_jobs=n_jobs,
                            n_pools=n_pools, ticks=ticks),
        FleetTrace.generate("churn", 12, n_jobs=n_jobs, n_pools=n_pools,
                            ticks=ticks, churn=0.35),
        FleetTrace.generate("spot-heavy", 13, n_jobs=n_jobs,
                            n_pools=n_pools, ticks=ticks,
                            spot_fraction=0.5, churn=0.15),
        FleetTrace.generate("noisy", 16, n_jobs=n_jobs,
                            n_pools=n_pools, ticks=ticks,
                            noise=0.04, pool_surge=False),
    ]


class _LiveJob:
    """Runtime state of one admitted trainer."""

    __slots__ = ("spec", "sim", "sealed_rows", "unsealed_rows",
                 "alive_ticks", "node_ticks", "forced_evictions",
                 "suspended_ticks")

    def __init__(self, spec: FleetJobSpec, nodes: int):
        self.spec = spec
        self.sim = SimJob(spec.job_id, spec.curve, spec.min_nodes,
                          spec.max_nodes, nodes=nodes, noise=spec.noise)
        self.sealed_rows = 0.0
        self.unsealed_rows = 0.0
        self.alive_ticks = 0
        self.node_ticks = 0
        self.forced_evictions = 0
        self.suspended_ticks = 0

    def legal_sizes(self) -> list[int]:
        g = self.spec.gang
        return [n for n in range(self.spec.min_nodes,
                                 self.spec.max_nodes + 1)
                if n % g == 0]

    def snap(self, desired: int) -> int:
        """Largest gang-legal size <= desired (0 = suspended: the gang
        either runs whole or not at all)."""
        legal = [n for n in self.legal_sizes() if n <= desired]
        return legal[-1] if legal else 0


class _LivePool:
    __slots__ = ("spec", "sim", "ok_ticks", "alive_ticks", "served_rows",
                 "ok_rows")

    def __init__(self, spec: FleetPoolSpec, seed: int, tick_s: float):
        self.spec = spec
        self.sim = SimServingPool(
            spec.service, spec.trace, teacher_rate=spec.teacher_rate,
            slo_p95_ms=spec.slo_p95_ms, teachers=spec.teachers,
            min_teachers=spec.min_teachers,
            max_teachers=spec.max_teachers, seed=seed, tick_s=tick_s)
        self.ok_ticks = 0
        self.alive_ticks = 0
        self.served_rows = 0.0   # throughput: everything served
        self.ok_rows = 0.0       # goodput: served within the SLO


@dataclass
class FleetObs:
    """One tick's observation bundle for the scheduling policy."""

    now: float
    tick: int
    trainer_views: list[FleetJobView]
    serving_views: list
    capacity: int
    notices: list[dict]


class FleetSim:
    """Seeded fleet: arrivals, departures, gangs, spot, per-action
    downtime. Deterministic under (trace.seed, seed, ladder)."""

    def __init__(self, trace: FleetTrace, *,
                 ladder: DowntimeLadder = MEASURED, tick_s: float = 5.0,
                 seed: int = 0, seal_every_ticks: int = 6):
        self.trace = trace
        self.ladder = ladder
        self.tick_s = tick_s
        self.seal_every_ticks = max(1, seal_every_ticks)
        self.now = 0.0
        self.ticks = 0
        self._rng = random.Random((trace.seed << 8) ^ seed)
        self.jobs: dict[str, _LiveJob] = {}
        self.pools: dict[str, _LivePool] = {}
        # tick counts from 1, so tick-0 arrivals are queued up front
        self._waiting: list[FleetJobSpec] = [
            s for s in trace.jobs if s.arrive_tick == 0]
        self._departed: list[_LiveJob] = []
        self._capacity = trace.total_nodes
        self._pending_notices: list[Preemption] = []
        self.downtime_paid_s = 0.0
        self.forced_evictions = 0
        self.notices_issued = 0
        self.notices_ridden = 0
        self.lost_rows = 0.0
        self.resizes_by_kind: dict[str, int] = {
            "adopt": 0, "reform": 0, "stop-resume": 0}

    # -- capacity ----------------------------------------------------------

    def capacity(self) -> int:
        return self._capacity

    def allocated(self) -> int:
        return (sum(j.sim.nodes for j in self.jobs.values())
                + sum(p.sim.desired for p in self.pools.values()))

    def notices(self) -> list[dict]:
        """Pending preemption notices (issued, deadline not reached)."""
        return [{"nodes": p.nodes, "deadline_tick": p.deadline_tick,
                 "notice_tick": p.notice_tick}
                for p in self._pending_notices]

    # -- the tick ----------------------------------------------------------

    def tick(self) -> FleetObs:
        self.ticks += 1
        self.now += self.tick_s
        t = self.ticks
        # 1. spot lifecycle: issue notices, enforce deadlines, restore
        for p in self.trace.preemptions:
            if p.notice_tick == t:
                self._pending_notices.append(p)
                self.notices_issued += 1
            if p.restore_tick == t:
                self._capacity += p.nodes
        expired = [p for p in self._pending_notices
                   if p.deadline_tick <= t]
        self._pending_notices = [p for p in self._pending_notices
                                 if p.deadline_tick > t]
        for p in expired:
            self._capacity -= p.nodes
            if self.allocated() <= self._capacity:
                # the fleet shrank under the post-deadline capacity in
                # time: the preemption was RIDDEN, nothing is killed
                self.notices_ridden += 1
        self._force_evict()
        # 2. arrivals / departures (gang-whole admission)
        for spec in self.trace.jobs:
            if spec.arrive_tick == t:
                self._waiting.append(spec)
        for spec in list(self._waiting):
            job = _LiveJob(spec, nodes=spec.min_nodes)
            if self.allocated() + spec.min_nodes <= self._capacity:
                self.jobs[spec.job_id] = job
                self._waiting.remove(spec)
        for job_id, job in list(self.jobs.items()):
            if job.spec.depart_tick is not None \
                    and job.spec.depart_tick <= t:
                job.sealed_rows += job.unsealed_rows
                job.unsealed_rows = 0.0
                self._departed.append(self.jobs.pop(job_id))
        for spec in self.trace.pools:
            if spec.arrive_tick == t or (spec.arrive_tick == 0
                                         and t == 1):
                if spec.service not in self.pools:
                    self.pools[spec.service] = _LivePool(
                        spec, seed=self.trace.seed * 1000 + len(self.pools),
                        tick_s=self.tick_s)
        # 3. advance trainers (downtime accounting like SimCluster.tick)
        trainer_views: list[FleetJobView] = []
        for job in self.jobs.values():
            job.alive_ticks += 1
            job.node_ticks += job.sim.nodes
            sim = job.sim
            if sim.nodes == 0:
                # a suspended gang's stats ARE fresh — it is
                # definitively producing zero; fresh=True lets the
                # policy propose a resume instead of holding forever
                # on "no-fresh-utilization"
                job.suspended_ticks += 1
                trainer_views.append(self._view(job, 0.0, fresh=True))
                continue
            if sim.downtime_left > 0:
                paid = min(sim.downtime_left, self.tick_s)
                sim.downtime_left = max(0.0,
                                        sim.downtime_left - self.tick_s)
                # partial tick: the remainder of the interval produces
                rate = sim.curve(sim.nodes) * (1.0 - paid / self.tick_s)
                rate *= max(0.0, 1.0 + self._rng.gauss(0.0, sim.noise))
                job.unsealed_rows += rate * self.tick_s
                trainer_views.append(self._view(job, 0.0, fresh=False))
            else:
                rate = sim.curve(sim.nodes)
                rate *= max(0.0, 1.0 + self._rng.gauss(0.0, sim.noise))
                job.unsealed_rows += rate * self.tick_s
                trainer_views.append(self._view(job, rate, fresh=True))
            if t % self.seal_every_ticks == 0:
                job.sealed_rows += job.unsealed_rows
                job.unsealed_rows = 0.0
        # 4. advance pools
        serving_views = []
        for pool in self.pools.values():
            pool.alive_ticks += 1
            view = pool.sim.tick()
            served = view.rows_per_sec * pool.sim.tick_s
            pool.served_rows += served
            if view.latency_ms_p95 <= view.slo_p95_ms:
                pool.ok_ticks += 1
                pool.ok_rows += served
            serving_views.append(view)
        return FleetObs(self.now, t, trainer_views, serving_views,
                        self._capacity, self.notices())

    def _view(self, job: _LiveJob, rate: float,
              fresh: bool) -> FleetJobView:
        return FleetJobView(job.spec.job_id, job.sim.nodes, rate,
                            job.spec.min_nodes, job.spec.max_nodes,
                            downtime_s=self.ladder.reform_s,
                            fresh=fresh, tier=job.spec.tier,
                            gang=job.spec.gang)

    # -- actuation ---------------------------------------------------------

    def resize(self, job_id: str, desired: int) -> int:
        """Scheduled resize through the reform ladder: gang-snapped,
        charged by action kind. Returns the actual new size."""
        job = self.jobs.get(job_id)
        if job is None:
            return 0
        sim = job.sim
        target = job.snap(max(0, desired))
        if target == sim.nodes:
            return sim.nodes
        kind = self.ladder.classify(sim.nodes, target)
        if sim.nodes == 0:
            kind = "stop-resume"  # un-suspending restarts from disk
        cost = self.ladder.cost(kind)
        if target == 0:
            # scheduled suspend = quiesce-seal-donate: progress seals
            job.sealed_rows += job.unsealed_rows
            job.unsealed_rows = 0.0
        sim.nodes = target
        sim.downtime_left = cost
        sim.downtime_paid += cost
        sim.resizes += 1
        sim.resize_ticks.append(self.ticks)
        self.downtime_paid_s += cost
        self.resizes_by_kind[kind] += 1
        return target

    def resize_pool(self, service: str, desired: int) -> int:
        pool = self.pools.get(service)
        return pool.sim.resize(desired) if pool is not None else 0

    def _force_evict(self) -> None:
        """Capacity dropped under the live allocation (a preemption
        deadline the policy did not ride): evict gang-whole from the
        lowest tier up. Each eviction is a HARD stop — stop-resume
        downtime plus every unsealed row since the last seal."""
        while self.allocated() > self._capacity:
            victims = sorted(
                (j for j in self.jobs.values() if j.sim.nodes > 0),
                key=lambda j: (-TIER_RANK.get(j.spec.tier, 1),
                               -j.sim.nodes, j.spec.job_id))
            if not victims:
                break
            job = victims[0]
            sim = job.sim
            legal = [n for n in job.legal_sizes() if n < sim.nodes]
            over = self.allocated() - self._capacity
            target = 0
            for n in reversed(legal):
                if sim.nodes - n >= over:
                    target = n
                    break
            cost = self.ladder.stop_resume_s
            sim.nodes = target
            sim.downtime_left = cost
            sim.downtime_paid += cost
            sim.resizes += 1
            sim.resize_ticks.append(self.ticks)
            self.downtime_paid_s += cost
            self.resizes_by_kind["stop-resume"] += 1
            self.lost_rows += job.unsealed_rows
            job.unsealed_rows = 0.0
            job.forced_evictions += 1
            self.forced_evictions += 1

    # -- scoring -----------------------------------------------------------

    def metrics(self) -> dict:
        """Tournament scores: goodput (sealed trainer rows + served
        pool rows per second of sim time), Jain fairness over
        entitlement-normalized node occupancy, SLO attainment over
        pool-ticks, and the downtime/eviction bill."""
        jobs = list(self.jobs.values()) + self._departed
        horizon = max(self.now, self.tick_s)
        trainer_rows = sum(j.sealed_rows + j.unsealed_rows for j in jobs)
        # serving GOODPUT counts only rows served within the SLO — a
        # row served during a breach is throughput, not goodput (the
        # caller already timed out on it); total served is reported
        # separately so the distinction stays auditable
        pool_rows = sum(p.ok_rows for p in self.pools.values())
        pool_served = sum(p.served_rows for p in self.pools.values())
        shares = [j.node_ticks / (j.alive_ticks * j.spec.max_nodes)
                  for j in jobs if j.alive_ticks > 0]
        jain = (sum(shares) ** 2 / (len(shares) * sum(s * s
                                                      for s in shares))
                if shares and sum(shares) > 0 else 1.0)
        pool_ticks = sum(p.alive_ticks for p in self.pools.values())
        ok_ticks = sum(p.ok_ticks for p in self.pools.values())
        return {
            "trace": self.trace.name,
            "ladder": self.ladder.name,
            "jobs": len(jobs),
            "pools": len(self.pools),
            "ticks": self.ticks,
            "goodput_rows_per_s": round(
                (trainer_rows + pool_rows) / horizon, 2),
            "trainer_rows_per_s": round(trainer_rows / horizon, 2),
            "pool_rows_per_s": round(pool_rows / horizon, 2),
            "pool_served_rows_per_s": round(pool_served / horizon, 2),
            "jain_fairness": round(jain, 4),
            "slo_attainment": round(ok_ticks / pool_ticks, 4)
            if pool_ticks else 1.0,
            "downtime_paid_s": round(self.downtime_paid_s, 2),
            "resizes_by_kind": dict(self.resizes_by_kind),
            "forced_evictions": self.forced_evictions,
            "notices_issued": self.notices_issued,
            "notices_ridden": self.notices_ridden,
            "lost_rows": round(self.lost_rows, 1),
            "spot_fraction": round(self.trace.spot_fraction, 3),
        }


def run_fleet(sim: FleetSim, policy, *, decide_every: int = 2) -> dict:
    """Drive one policy over one fleet. Policies exposing
    ``decide_fleet`` (fleet_policy.PreemptiveFairSharePolicy) see the
    capacity + pending notices; plain mixed policies get the current
    capacity as their budget and stay notice-blind — exactly the
    baseline the tournament compares against."""
    for _ in range(sim.trace.ticks):
        obs = sim.tick()
        if sim.ticks % decide_every:
            continue
        if hasattr(policy, "decide_fleet"):
            t_props, s_props = policy.decide_fleet(
                obs.trainer_views, obs.serving_views, obs.now,
                notices=obs.notices, capacity=obs.capacity)
        else:
            policy.budget = obs.capacity
            t_props, s_props = policy.decide_mixed(
                obs.trainer_views, obs.serving_views, obs.now)
        for prop in t_props:
            if prop.is_resize:
                actual = sim.resize(prop.job_id, prop.desired)
                if actual != prop.current:  # gang-snap can no-op
                    policy.notify_resized(prop.job_id, actual, obs.now)
        for prop in s_props:
            if prop.is_resize:
                actual = sim.resize_pool(prop.job_id, prop.desired)
                if actual != prop.current:
                    policy.notify_resized(prop.job_id, actual, obs.now)
    out = sim.metrics()
    out["policy"] = type(policy).__name__
    return out


def tournament(*, traces: list[FleetTrace] | None = None,
               ladders: list[DowntimeLadder] | None = None,
               policies: dict | None = None,
               decide_every: int = 2, tick_s: float = 5.0) -> dict:
    """Seeded policy tournament over the policy x trace x ladder grid.
    ``policies`` maps name -> zero-arg factory (a fresh policy per
    cell — models must not leak between runs). Returns
    ``{"rows": [...], "fingerprint": sha256-of-rows}``."""
    from edl_tpu.scaler.fleet_policy import default_policies
    traces = trace_menu() if traces is None else traces
    ladders = [MEASURED, LEGACY] if ladders is None else ladders
    policies = default_policies() if policies is None else policies
    rows = []
    for trace in traces:
        for ladder in ladders:
            for pname, factory in policies.items():
                sim = FleetSim(trace, ladder=ladder, tick_s=tick_s)
                row = run_fleet(sim, factory(), decide_every=decide_every)
                row["policy"] = pname
                rows.append(row)
    blob = json.dumps(rows, sort_keys=True).encode()
    return {"rows": rows,
            "fingerprint": hashlib.sha256(blob).hexdigest()}


# -- the jax-free CI smoke ---------------------------------------------------


def selftest(verbose: bool = True) -> int:
    """Small-fleet correctness gate (runs before dependency install in
    CI, so it doubles as the stdlib-only proof)."""
    assert "jax" not in sys.modules and "numpy" not in sys.modules, \
        "fleet selftest must run jax/numpy-free"
    from edl_tpu.scaler.fleet_policy import PreemptiveFairSharePolicy
    from edl_tpu.scaler.policy import FairSharePolicy
    failures: list[str] = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        if verbose:
            print(f"  {'ok  ' if ok else 'FAIL'} {name} {detail}")
        if not ok:
            failures.append(name)

    kw = dict(cooldown_s=15.0, horizon_s=60.0)
    small = dict(n_jobs=28, n_pools=6, ticks=120)

    # 1. determinism: identical seeds => identical tournament rows
    t1 = tournament(traces=[FleetTrace.generate("t", 3, **small)],
                    ladders=[MEASURED],
                    policies={"fair": lambda: FairSharePolicy(64, **kw)})
    t2 = tournament(traces=[FleetTrace.generate("t", 3, **small)],
                    ladders=[MEASURED],
                    policies={"fair": lambda: FairSharePolicy(64, **kw)})
    check("deterministic-tournament",
          t1["fingerprint"] == t2["fingerprint"], t1["fingerprint"][:12])

    # 2. gang constraint: every live allocation is gang-legal
    trace = FleetTrace.generate("gang", 5, **small)
    sim = FleetSim(trace)
    run_fleet(sim, PreemptiveFairSharePolicy(sim.capacity(), **kw))
    gang_ok = all(j.sim.nodes == 0 or (j.sim.nodes % j.spec.gang == 0
                                       and j.sim.nodes >= j.spec.min_nodes)
                  for j in sim.jobs.values())
    check("gang-legal-allocations", gang_ok)

    # 3. preemptive vs plain fair-share: better SLO attainment at
    # equal-or-better goodput on a surging fleet (small-scale version
    # of the tournament acceptance bar)
    trace = FleetTrace.generate("surge", 7, **small)
    base = run_fleet(FleetSim(trace),
                     FairSharePolicy(1, **kw))
    pre = run_fleet(FleetSim(trace),
                    PreemptiveFairSharePolicy(1, **kw))
    check("preemptive-wins-slo",
          pre["slo_attainment"] >= base["slo_attainment"],
          f"{pre['slo_attainment']} vs {base['slo_attainment']}")
    check("preemptive-holds-goodput",
          pre["goodput_rows_per_s"] >= 0.98 * base["goodput_rows_per_s"],
          f"{pre['goodput_rows_per_s']} vs {base['goodput_rows_per_s']}")

    # 4. spot riding: the notice-aware policy shrinks ahead of the
    # deadline (zero forced evictions); the notice-blind baseline pays
    spot = FleetTrace.generate("spot", 9, spot_fraction=0.5, **small)
    blind = run_fleet(FleetSim(spot), FairSharePolicy(1, **kw))
    aware = run_fleet(FleetSim(spot),
                      PreemptiveFairSharePolicy(1, **kw))
    check("notice-blind-pays-evictions", blind["forced_evictions"] > 0,
          str(blind["forced_evictions"]))
    check("notice-aware-rides",
          aware["forced_evictions"] < blind["forced_evictions"]
          and aware["notices_ridden"] > blind["notices_ridden"],
          f"evict {aware['forced_evictions']} vs "
          f"{blind['forced_evictions']}, rode "
          f"{aware['notices_ridden']} vs {blind['notices_ridden']}")

    # 5. the ladder changes the bill: the same policy on the same trace
    # pays visibly more downtime under the legacy (all-stop-resume)
    # ladder than under the measured reform ladder
    m = run_fleet(FleetSim(trace, ladder=MEASURED),
                  PreemptiveFairSharePolicy(1, **kw))
    lg = run_fleet(FleetSim(trace, ladder=LEGACY),
                   PreemptiveFairSharePolicy(1, **kw))
    check("ladder-prices-differ",
          lg["downtime_paid_s"] > 2.0 * m["downtime_paid_s"],
          f"{lg['downtime_paid_s']} vs {m['downtime_paid_s']}")

    # 6. artifact ladder parsing falls back field-by-field
    check("artifact-ladder-defaults",
          DowntimeLadder.from_artifact("/nonexistent") is None)

    if failures:
        print(f"fleet selftest: {len(failures)} FAILED: {failures}")
        return 1
    if verbose:
        print("fleet selftest: all checks passed")
    return 0


def _fleet_env_defaults() -> dict:
    """The EDL_TPU_FLEET_* knobs (registered in utils/config.ENV_VARS;
    the CLI reads them as defaults so tournaments are tunable without
    flag soup)."""
    return {
        "n_jobs": env_int("EDL_TPU_FLEET_JOBS", 180),
        "n_pools": env_int("EDL_TPU_FLEET_POOLS", 24),
        "ticks": env_int("EDL_TPU_FLEET_TICKS", 240),
        "spot_fraction": env_float("EDL_TPU_FLEET_SPOT_FRACTION", 0.0),
    }


def main(argv: list[str] | None = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m edl_tpu.scaler.fleet",
        description="fleet simulator: selftest / seeded tournament")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("selftest", help="deterministic small-fleet gate")
    tour = sub.add_parser("tournament",
                          help="policy x trace x ladder grid (JSON)")
    tour.add_argument("--jobs", type=int, default=None)
    tour.add_argument("--pools", type=int, default=None)
    tour.add_argument("--ticks", type=int, default=None)
    tour.add_argument("--ladder", default=None,
                      help="bench artifact JSON for measured downtimes")
    args = parser.parse_args(argv)
    if args.cmd == "selftest":
        return selftest()
    env = _fleet_env_defaults()
    traces = trace_menu(
        n_jobs=args.jobs if args.jobs is not None else env["n_jobs"],
        n_pools=args.pools if args.pools is not None else env["n_pools"],
        ticks=args.ticks if args.ticks is not None else env["ticks"])
    ladders = None
    if args.ladder:
        measured = DowntimeLadder.from_artifact(args.ladder)
        if measured is None:
            print(f"unreadable ladder artifact: {args.ladder}",
                  file=sys.stderr)
            return 2
        ladders = [measured, LEGACY]
    out = tournament(traces=traces, ladders=ladders)
    print(json.dumps(out, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
