"""Deterministic cluster simulator: policies testable without training.

`SimCluster` replays the elastic control loop against synthetic
scaling curves — concave (diminishing returns), flat (the job can't
use more nodes), knee (linear up to a bandwidth knee, flat past it) —
with seeded multiplicative noise and a modeled resize downtime during
which the job produces nothing (the measured `elastic_downtime_s`
price). Time is virtual: `tick()` advances it by `tick_s`; nothing
reads the wall clock, so every run is exactly reproducible and a
thousand-tick sweep costs milliseconds (`tools/scaler_bench.py`,
`bench.py::bench_scaler`).

`run_policy` is the harness: drive a policy over N ticks, actuate its
proposals on the SimCluster, and report convergence (last-resize tick,
post-convergence resize count, allocation gap vs the oracle computed
from the TRUE noise-free curve) plus the downtime the policy paid.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Callable

from edl_tpu.scaler.policy import JobView, ScalingPolicy


@dataclass(frozen=True)
class ScalingCurve:
    """True throughput as a function of world size."""

    name: str
    rate: Callable[[int], float]

    def __call__(self, n: int) -> float:
        return 0.0 if n < 1 else float(self.rate(n))


def concave(r1: float = 100.0, alpha: float = 0.6) -> ScalingCurve:
    """Diminishing returns: T(n) = r1 * n^alpha."""
    return ScalingCurve(f"concave(a={alpha})", lambda n: r1 * n ** alpha)


def flat(r: float = 100.0) -> ScalingCurve:
    """More nodes buy nothing: T(n) = r."""
    return ScalingCurve("flat", lambda n: r)


def knee(r1: float = 100.0, knee_n: int = 4) -> ScalingCurve:
    """Linear to the knee, flat past it: T(n) = r1 * min(n, knee_n)."""
    return ScalingCurve(f"knee(k={knee_n})",
                        lambda n: r1 * min(n, knee_n))


def linear(r1: float = 100.0) -> ScalingCurve:
    """Perfect scaling: T(n) = r1 * n."""
    return ScalingCurve("linear", lambda n: r1 * n)


@dataclass
class SimJob:
    """One elastic job: a curve, an allocation, a resize in flight."""

    job_id: str
    curve: ScalingCurve
    min_nodes: int = 1
    max_nodes: int = 8
    nodes: int = 1
    noise: float = 0.01           # multiplicative sigma on observed rate
    downtime_left: float = 0.0    # seconds of the current resize stall
    resizes: int = 0
    downtime_paid: float = 0.0
    resize_ticks: list[int] = field(default_factory=list)


class SimCluster:
    """Seeded, wall-clock-free cluster the decision plane runs against."""

    def __init__(self, jobs: list[SimJob], *, tick_s: float = 5.0,
                 downtime_s: float = 1.5, seed: int = 0):
        self.jobs = {j.job_id: j for j in jobs}
        self.tick_s = tick_s
        self.downtime_s = downtime_s
        self.now = 0.0
        self.ticks = 0
        self._rng = random.Random(seed)

    def tick(self) -> list[JobView]:
        """Advance virtual time one interval; emit Collector-like views.

        A job inside its resize downtime reports nothing trustworthy
        (``fresh=False``, zero rate) — exactly what the live controller
        sees while a world re-forms."""
        self.now += self.tick_s
        self.ticks += 1
        views = []
        for job in self.jobs.values():
            if job.downtime_left > 0:
                job.downtime_left = max(0.0,
                                        job.downtime_left - self.tick_s)
                views.append(JobView(job.job_id, job.nodes, 0.0,
                                     job.min_nodes, job.max_nodes,
                                     self.downtime_s, fresh=False))
                continue
            rate = job.curve(job.nodes)
            rate *= max(0.0, 1.0 + self._rng.gauss(0.0, job.noise))
            views.append(JobView(job.job_id, job.nodes, rate,
                                 job.min_nodes, job.max_nodes,
                                 self.downtime_s))
        return views

    def resize(self, job_id: str, desired: int) -> int:
        """Actuate: clamp, pay the downtime, count it. Returns the new
        allocation."""
        job = self.jobs[job_id]
        desired = max(job.min_nodes, min(job.max_nodes, desired))
        if desired != job.nodes:
            job.nodes = desired
            job.downtime_left = self.downtime_s
            job.downtime_paid += self.downtime_s
            job.resizes += 1
            job.resize_ticks.append(self.ticks)
        return job.nodes

    # -- oracles (computed from the TRUE curve, noise-free) ----------------

    def oracle_alloc(self, job_id: str, epsilon: float) -> int:
        """Largest n in [min, max] whose last node still gains >= epsilon
        relative throughput — the marginal-gain-positive allocation the
        ThroughputPolicy converges to."""
        job = self.jobs[job_id]
        best = job.min_nodes
        for n in range(job.min_nodes + 1, job.max_nodes + 1):
            t0, t1 = job.curve(n - 1), job.curve(n)
            if t0 <= 0 or (t1 - t0) / t0 < epsilon:
                break
            best = n
        return best

    def oracle_fair_share(self, budget: int) -> dict[str, int]:
        """Greedy water-fill on the true curves (optimal for concave)."""
        alloc = {j.job_id: j.min_nodes for j in self.jobs.values()}
        left = budget - sum(alloc.values())
        while left > 0:
            best_job, best_gain = None, 0.0
            for job in self.jobs.values():
                n = alloc[job.job_id]
                if n >= job.max_nodes:
                    continue
                gain = job.curve(n + 1) - job.curve(n)
                if best_job is None or gain > best_gain:
                    best_job, best_gain = job.job_id, gain
            if best_job is None:
                break
            alloc[best_job] += 1
            left -= 1
        return alloc


# -- the serving pool (SLO-driven elasticity; scaler/serving.py) -------------


@dataclass(frozen=True)
class ArrivalTrace:
    """Open-loop arrival rate (rows/sec) as a function of the tick."""

    name: str
    rate: Callable[[int], float]

    def __call__(self, tick: int) -> float:
        return max(0.0, float(self.rate(tick)))


def steady(lam: float = 200.0) -> ArrivalTrace:
    """Constant demand: the no-thrash baseline."""
    return ArrivalTrace(f"steady({lam:g})", lambda t: lam)


def step(lam: float = 100.0, factor: float = 4.0,
         at: int = 40) -> ArrivalTrace:
    """Demand jumps ``factor``x at tick ``at`` and stays: the SLO
    recovery case."""
    return ArrivalTrace(f"step({lam:g}x{factor:g}@{at})",
                        lambda t: lam * factor if t >= at else lam)


def burst(lam: float = 100.0, factor: float = 4.0, at: int = 40,
          length: int = 20) -> ArrivalTrace:
    """Demand spikes ``factor``x for ``length`` ticks then returns:
    grow in, drain out."""
    return ArrivalTrace(f"burst({lam:g}x{factor:g}@{at}+{length})",
                        lambda t: lam * factor if at <= t < at + length
                        else lam)


class SimServingPool:
    """Deterministic open-loop serving pool the `ServingPolicy` runs
    against: arrivals from a trace, capacity = ready teachers x
    ``teacher_rate`` rows/sec, explicit backlog dynamics.

    The latency model is queueing-naive but directionally honest:
    p95 = ``base_ms / (1 - rho)`` (service-time inflation as load
    approaches capacity, rho clamped at 0.95) plus the time the current
    backlog takes to drain at full capacity. Seeded multiplicative
    noise on top. A grow takes ``spawn_delay_ticks`` before the new
    teacher counts (the view's ``desired`` stays ahead of
    ``n_teachers`` meanwhile — exactly the live resize-in-flight
    signal); a shrink drains within the tick, so — unlike trainer
    resizes — serving NEVER pays a fresh=False downtime window. That
    asymmetry is the whole point of keep-then-fill.
    """

    def __init__(self, service: str, trace: ArrivalTrace, *,
                 teacher_rate: float = 250.0, base_ms: float = 20.0,
                 slo_p95_ms: float = 250.0, teachers: int = 1,
                 min_teachers: int = 1, max_teachers: int = 16,
                 spawn_delay_ticks: int = 2, tick_s: float = 1.0,
                 request_rows: int = 16, noise: float = 0.0,
                 seed: int = 0):
        from edl_tpu.scaler.serving import ServingView
        self._view_cls = ServingView
        self.service = service
        self.trace = trace
        self.teacher_rate = teacher_rate
        self.base_ms = base_ms
        self.slo_p95_ms = slo_p95_ms
        self.min_teachers = min_teachers
        self.max_teachers = max_teachers
        self.spawn_delay_ticks = spawn_delay_ticks
        self.tick_s = tick_s
        self.request_rows = request_rows
        self.noise = noise
        self._rng = random.Random(seed)
        self.ready = teachers
        self.desired = teachers
        self._pending_spawns: list[int] = []  # tick each becomes ready
        self.backlog_rows = 0.0
        self.now = 0.0
        self.ticks = 0
        self.resizes = 0
        self.resize_ticks: list[int] = []

    def tick(self):
        """Advance one interval; emit the rollup-shaped ServingView."""
        self.ticks += 1
        self.now += self.tick_s
        ready_now = sum(1 for t in self._pending_spawns if t <= self.ticks)
        self.ready += ready_now
        self._pending_spawns = [t for t in self._pending_spawns
                                if t > self.ticks]
        lam = self.trace(self.ticks)
        cap = self.ready * self.teacher_rate
        arrived = lam * self.tick_s
        served = min(self.backlog_rows + arrived, cap * self.tick_s)
        self.backlog_rows = max(0.0,
                                self.backlog_rows + arrived - served)
        rho = lam / cap if cap > 0 else float("inf")
        wait_ms = (self.backlog_rows / cap) * 1e3 if cap > 0 else 0.0
        p95 = self.base_ms / max(1.0 - min(rho, 0.95), 0.05) + wait_ms
        p95 *= max(0.0, 1.0 + self._rng.gauss(0.0, self.noise))
        p50 = self.base_ms + wait_ms
        return self._view_cls(
            self.service, self.ready,
            rows_per_sec=round(served / self.tick_s, 2),
            util=min(1.0, rho),
            queue_depth=int(self.backlog_rows // self.request_rows),
            latency_ms_p50=round(p50, 2), latency_ms_p95=round(p95, 2),
            slo_p95_ms=self.slo_p95_ms, min_teachers=self.min_teachers,
            max_teachers=self.max_teachers, desired=self.desired)

    def resize(self, desired: int) -> int:
        """Actuate: spawn after a delay, drain within the tick."""
        desired = max(self.min_teachers, min(self.max_teachers, desired))
        total = self.ready + len(self._pending_spawns)
        if desired > total:
            for _ in range(desired - total):
                self._pending_spawns.append(self.ticks
                                            + self.spawn_delay_ticks)
        elif desired < total:
            drop = total - desired
            while drop and self._pending_spawns:  # cancel unspawned first
                self._pending_spawns.pop()
                drop -= 1
            self.ready -= drop
        if desired != total:
            self.resizes += 1
            self.resize_ticks.append(self.ticks)
        self.desired = desired
        return desired

    def oracle_teachers(self, lam: float) -> int:
        """Smallest pool whose steady-state p95 meets the SLO at
        arrival rate ``lam`` (from the true noise-free model):
        base/(1-rho) <= slo  =>  n >= lam / (rate * (1 - base/slo))."""
        headroom = 1.0 - self.base_ms / self.slo_p95_ms
        if headroom <= 0:
            return self.max_teachers
        need = math.ceil(lam / (self.teacher_rate * headroom))
        return max(self.min_teachers,
                   min(self.max_teachers, max(1, need)))


def run_serving_policy(pool: SimServingPool, policy, *,
                       ticks: int = 120, settle_ticks: int = 40) -> dict:
    """Drive a `ServingPolicy` over the pool; summarize SLO attainment
    and convergence. ``last_violation_tick`` is the recovery anchor:
    for a step trace, reaction = last_violation_tick - step tick."""
    ok: list[bool] = []
    for _ in range(ticks):
        view = pool.tick()
        ok.append(view.latency_ms_p95 <= view.slo_p95_ms)
        (prop,) = policy.decide([view], pool.now)
        if prop.is_resize:
            actual = pool.resize(prop.desired)
            policy.notify_resized(view.service, actual, pool.now)
    post = sum(1 for t in pool.resize_ticks if t > ticks - settle_ticks)
    return {"ticks": ticks, "trace": pool.trace.name,
            "slo_attainment": round(sum(ok) / len(ok), 4),
            "last_violation_tick": max(
                (i + 1 for i, good in enumerate(ok) if not good),
                default=0),
            "final_teachers": pool.ready,
            "resizes": pool.resizes,
            "post_convergence_resizes": post,
            "resize_ticks": list(pool.resize_ticks)}


def run_policy(cluster: SimCluster, policy: ScalingPolicy, *,
               ticks: int = 120, settle_ticks: int = 50) -> dict:
    """Drive `policy` over the cluster; summarize convergence.

    Convergence = no resize in the trailing `settle_ticks` window; the
    acceptance bar is gap <= 1 node vs the oracle AND zero resizes in
    that window (post-convergence stability)."""
    epsilon = getattr(policy, "gain_threshold", 0.05)
    decisions = 0
    for _ in range(ticks):
        views = cluster.tick()
        for prop in policy.decide(views, cluster.now):
            decisions += 1
            if prop.is_resize:
                actual = cluster.resize(prop.job_id, prop.desired)
                policy.notify_resized(prop.job_id, actual, cluster.now)
    out: dict = {"ticks": ticks, "decisions": decisions, "jobs": {}}
    last_resize_tick = 0
    for job in cluster.jobs.values():
        oracle = cluster.oracle_alloc(job.job_id, epsilon)
        post = sum(1 for t in job.resize_ticks
                   if t > ticks - settle_ticks)
        out["jobs"][job.job_id] = {
            "curve": job.curve.name,
            "final_nodes": job.nodes,
            "oracle_nodes": oracle,
            "gap_nodes": abs(job.nodes - oracle),
            "resizes": job.resizes,
            "downtime_paid_s": round(job.downtime_paid, 2),
            "post_convergence_resizes": post,
            "decisions_to_converge": (job.resize_ticks[-1]
                                      if job.resize_ticks else 0),
        }
        last_resize_tick = max(last_resize_tick,
                               out["jobs"][job.job_id]
                               ["decisions_to_converge"])
    out["decisions_to_converge"] = last_resize_tick
    out["downtime_paid_s"] = round(
        sum(j.downtime_paid for j in cluster.jobs.values()), 2)
    out["gap_nodes"] = max(j["gap_nodes"] for j in out["jobs"].values())
    out["post_convergence_resizes"] = sum(
        j["post_convergence_resizes"] for j in out["jobs"].values())
    return out
