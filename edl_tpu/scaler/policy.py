"""Scaling policies: utilization in, resize proposals out.

The decision half of the reference's defining pillar — the TrainingJob
controller that grows/shrinks a job's node count from observed
utilization (SURVEY §1; `coord/collector.py` publishes exactly the
records the registry `info` field was reserved for). Policies are pure
state machines over (world_size, throughput) observations: no store, no
HTTP, no wall clock — the caller supplies `now`, which is what makes
them drivable by both the live controller (`scaler/controller.py`) and
the deterministic simulator (`scaler/simulator.py`).

Two policies, in the spirit of goodput-driven elastic schedulers
(Pollux) and cluster-wide dynamic scaling (AntMan):

- `ThroughputPolicy` — single-job autoscaling. Fits a throughput-vs-
  world-size curve from observed rates, probes unexplored sizes while
  the measured marginal gain clears a threshold, and settles on the
  smallest allocation within the hysteresis band of the best known
  rate. Every grow must amortize: predicted extra samples before the
  next decision must exceed the samples lost to the resize downtime
  (the measured `elastic_downtime_s`), so a resize that can't pay for
  itself is never proposed.
- `FairSharePolicy` — multi-job: water-fills a fixed node budget by
  marginal throughput (each next node goes to the job whose curve says
  it gains most), honoring per-job min/max, shrink-before-grow so the
  budget is never transiently exceeded.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Protocol, runtime_checkable


@dataclass
class JobView:
    """One job's state at one decision instant (a Collector digest)."""

    job_id: str
    world_size: int            # current allocation (live cluster world)
    throughput: float          # aggregate fresh examples/sec across pods
    min_nodes: int = 1
    max_nodes: int = 8
    # The price of one resize that every grow must amortize. The live
    # controller feeds the MEASURED per-job EWMA here (actuation ->
    # first fresh utilization at the new world, journal-replayed across
    # leader takeovers); the configured constant / bench artifact is
    # only the fallback before the first observation — so a faster
    # resize path (p2p live migration) loosens the grow gate on its own.
    downtime_s: float = 1.5
    generation: int | None = None
    desired: int | None = None  # job-server desired (None = world_size)
    fresh: bool = True         # False: stale/reforming — do not learn

    @property
    def effective_desired(self) -> int:
        return self.world_size if self.desired is None else self.desired


@dataclass
class Proposal:
    """Policy output for one job: resize to `desired`, or hold + why."""

    job_id: str
    current: int
    desired: int
    reason: str
    predicted_gain: float | None = None  # examples/sec delta (grows)

    @property
    def is_resize(self) -> bool:
        return self.desired != self.current


@runtime_checkable
class ScalingPolicy(Protocol):
    """The policy contract the controller and simulator drive."""

    def decide(self, views: list[JobView], now: float) -> list[Proposal]:
        """One decision pass; returns a Proposal per view, same order."""
        ...

    def notify_resized(self, job_id: str, desired: int, now: float) -> None:
        """Actuation feedback: starts the job's cooldown clock."""
        ...

    def restore(self, entries: list[dict]) -> None:
        """Warm-start from journal entries (leader takeover)."""
        ...


class ThroughputModel:
    """EWMA throughput per observed world size + curve extrapolation.

    Known sizes answer with their smoothed mean; unknown sizes get a
    power-law fit ``T = c * n^a`` (log-log least squares, ``a`` clamped
    to [0, 1.2]) once two distinct sizes exist, else an optimistic
    linear extension of the single known point — optimism is what makes
    an unexplored size worth probing.
    """

    def __init__(self, ema: float = 0.3):
        self.ema = ema
        self._mean: dict[int, float] = {}
        self._count: dict[int, int] = {}
        # cached log-log fit (c, a); None = stale. Fleet-scale planning
        # (scaler/fleet.py) calls predict O(budget * jobs) times per
        # decision, so the fit must not be recomputed per call.
        self._fit: tuple[float, float] | None = None

    def observe(self, n: int, rate: float) -> None:
        if n < 1 or rate < 0:
            return
        if n in self._mean:
            self._mean[n] += self.ema * (rate - self._mean[n])
        else:
            self._mean[n] = float(rate)
        self._count[n] = self._count.get(n, 0) + 1
        self._fit = None

    def known(self) -> list[int]:
        return sorted(self._mean)

    def observed(self, n: int) -> float | None:
        return self._mean.get(n)

    def predict(self, n: int) -> float | None:
        if n in self._mean:
            return self._mean[n]
        pts = [(k, v) for k, v in self._mean.items() if v > 0]
        if len(pts) >= 2:
            if self._fit is None:
                xs = [math.log(k) for k, _ in pts]
                ys = [math.log(v) for _, v in pts]
                mx, my = sum(xs) / len(xs), sum(ys) / len(ys)
                denom = sum((x - mx) ** 2 for x in xs)
                a = (sum((x - mx) * (y - my)
                         for x, y in zip(xs, ys)) / denom
                     if denom > 0 else 0.0)
                a = max(0.0, min(a, 1.2))
                self._fit = (math.exp(my - a * mx), a)
            c, a = self._fit
            return c * n ** a
        if len(pts) == 1:
            k, v = pts[0]
            return v * n / k
        return None

    def marginal(self, n1: int, n2: int) -> float | None:
        """Relative per-node gain going n1 -> n2 from OBSERVED means."""
        t1, t2 = self.observed(n1), self.observed(n2)
        if t1 is None or t2 is None or t1 <= 0 or n2 <= n1:
            return None
        return (t2 - t1) / (t1 * (n2 - n1))


class _PolicyBase:
    """Shared observation intake, cooldown clocks, journal restore."""

    def __init__(self, *, gain_threshold: float = 0.05,
                 cooldown_s: float = 30.0, horizon_s: float | None = None,
                 ema: float = 0.3):
        self.gain_threshold = gain_threshold
        self.cooldown_s = cooldown_s
        # Amortization horizon: how long a new allocation runs before
        # the next decision can change it — the window a resize must
        # pay for itself within. Cooldown is that window's floor.
        self.horizon_s = cooldown_s if horizon_s is None else horizon_s
        self.ema = ema
        self._models: dict[str, ThroughputModel] = {}
        self._resized_at: dict[str, float] = {}

    def model(self, job_id: str) -> ThroughputModel:
        return self._models.setdefault(job_id, ThroughputModel(self.ema))

    def _intake(self, view: JobView, now: float) -> str | None:
        """Record the observation when trustworthy; else return the
        hold reason that makes this tick a no-op for the job."""
        resized_at = self._resized_at.get(view.job_id)
        settling = (resized_at is not None
                    and now - resized_at < view.downtime_s)
        if view.fresh and not settling and view.world_size >= 1 \
                and view.effective_desired == view.world_size:
            self.model(view.job_id).observe(view.world_size,
                                            view.throughput)
        if not view.fresh:
            return "no-fresh-utilization"
        if view.effective_desired != view.world_size:
            return "resize-in-flight"
        if settling:
            return "settling-after-resize"
        if resized_at is not None and now - resized_at < self.cooldown_s:
            return "cooldown"
        return None

    def _amortizes(self, gain_per_sec: float, view: JobView) -> bool:
        """True when the predicted gain repays the downtime before the
        next decision: gain * (horizon - downtime) > downtime * T_now."""
        usable = self.horizon_s - view.downtime_s
        if usable <= 0:
            return False
        return gain_per_sec * usable > view.downtime_s * view.throughput

    def notify_resized(self, job_id: str, desired: int, now: float) -> None:
        self._resized_at[job_id] = now

    def restore(self, entries: list[dict]) -> None:
        """Replay journal entries (seq order): re-learn the models from
        the recorded observations and resume the cooldown clocks, so a
        takeover leader neither forgets the curve nor double-resizes."""
        for e in entries:
            job = e.get("job_id")
            if not job:
                continue
            if e.get("fresh") and e.get("world_size", 0) >= 1 \
                    and e.get("throughput") is not None:
                self.model(job).observe(int(e["world_size"]),
                                        float(e["throughput"]))
            if e.get("action") == "resize":
                self._resized_at[job] = float(e.get("ts", 0.0))


class ThroughputPolicy(_PolicyBase):
    """Marginal-gain-positive autoscaling for independent jobs.

    Per decision (after cooldown/freshness gates):

    1. *recover* — if the current size runs > 2x the hysteresis band
       below the best known rate (we shrank past the knee), grow back
       to the settle size (amortization-gated).
    2. *probe-up* — while sitting at the largest explored size and the
       top observed marginal still clears `gain_threshold` (or fewer
       than two sizes are known), try one node more. Gated by the
       optimistic amortization bound (one node's perfect-scaling
       contribution must repay the downtime).
    3. *probe-down* — while sitting at the smallest explored size and
       the bottom marginal is below threshold (flat down here), try one
       node less: frees capacity at no predicted cost.
    4. *settle* — shrink to the smallest known size within the
       hysteresis band of the best known rate.
    5. otherwise hold (*converged*).

    The asymmetric bands (shrink within `gain_threshold`, grow back
    only past `2 * gain_threshold`) are the anti-oscillation margin: a
    noisy flat curve cannot alternate proposals, because the rates that
    would trigger a shrink and the rates that would trigger the
    corresponding grow-back are separated by a dead zone wider than the
    smoothed noise.
    """

    def decide(self, views: list[JobView], now: float) -> list[Proposal]:
        return [self._decide_one(v, now) for v in views]

    def _decide_one(self, view: JobView, now: float) -> Proposal:
        job, cur = view.job_id, view.world_size
        hold = self._intake(view, now)
        if hold is not None:
            return Proposal(job, cur, cur, hold)
        model = self.model(job)
        known = model.known()
        if not known:
            return Proposal(job, cur, cur, "no-observations")
        eps = self.gain_threshold
        best = max(model.observed(n) for n in known)
        settle_n = min(n for n in known
                       if model.observed(n) >= (1.0 - eps) * best)
        t_cur = model.observed(cur)

        # 1. recover: we sit measurably below the best known rate.
        if t_cur is not None and best > 0 and settle_n > cur \
                and t_cur < (1.0 - 2.0 * eps) * best:
            gain = model.observed(settle_n) - t_cur
            if self._amortizes(gain, view):
                return Proposal(job, cur, settle_n,
                                "recover-to-best-known", gain)
            return Proposal(job, cur, cur, "recover-unamortized", gain)

        top, bottom = known[-1], known[0]
        # 2. probe up: unexplored room above and the curve still climbs.
        if cur == top and top < view.max_nodes:
            top_marginal = (model.marginal(known[-2], top)
                            if len(known) >= 2 else None)
            if top_marginal is None or top_marginal >= eps:
                optimistic = (t_cur / cur) if t_cur and cur else 0.0
                if t_cur is None or t_cur == 0 \
                        or self._amortizes(optimistic, view):
                    return Proposal(job, cur, cur + 1, "probe-up",
                                    optimistic or None)
                return Proposal(job, cur, cur, "probe-up-unamortized",
                                optimistic)
        # 3. probe down: flat at the bottom of the explored range.
        if cur == bottom and bottom > view.min_nodes and len(known) >= 2:
            if (model.marginal(bottom, known[1]) or 0.0) < eps:
                return Proposal(job, cur, cur - 1, "probe-down")
        # 4. settle: smallest allocation within the hysteresis band.
        if settle_n < cur:
            return Proposal(job, cur, settle_n,
                            "settle-to-marginal-gain-positive")
        return Proposal(job, cur, cur, "converged")


class FairSharePolicy(_PolicyBase):
    """Split a fixed node budget across jobs by marginal throughput.

    Water-filling: every job starts at its `min_nodes`; each remaining
    budget node goes to the job whose model predicts the largest gain
    from one more node (unexplored jobs bid the best observed per-node
    rate across jobs — optimistic on the measured scale, so they
    attract exploration instead of being starved by explored jobs'
    absolute marginals). Proposals then reconcile the plan against the
    live allocations shrink-before-grow: grows are admitted only while
    the post-shrink total stays within budget, so the cluster never
    transiently exceeds it even when cooldowns stagger the actuations.
    """

    def __init__(self, budget: int, **kw):
        super().__init__(**kw)
        self.budget = budget

    def plan(self, views: list[JobView],
             budget: int | None = None) -> dict[str, int]:
        """The budget split this tick's models recommend."""
        alloc: dict[str, int] = {}
        left = self.budget if budget is None else budget
        for v in views:  # mins first, in view order, never past budget
            grant = min(v.min_nodes, max(left, 0))
            alloc[v.job_id] = grant
            left -= grant
        # Exploration bonus for jobs with NO observations yet, in the
        # same absolute examples/sec unit as explored jobs' marginal
        # gains: the best observed per-node rate across all jobs (a
        # constant like 1.0 would starve unexplored jobs whenever the
        # measured curves live at ~100 ex/s). No observations anywhere
        # -> every job is unexplored and any positive constant ties.
        rates = [self.model(v.job_id).observed(n) / n
                 for v in views for n in self.model(v.job_id).known()]
        explore = max([r for r in rates if r > 0], default=1.0)
        while left > 0:
            best_job, best_gain = None, 0.0
            for v in views:
                n = alloc[v.job_id]
                if n >= v.max_nodes:
                    continue
                model = self.model(v.job_id)
                t0, t1 = model.predict(n), model.predict(n + 1)
                # unexplored job: optimistic per-node-rate bonus, decayed
                # by the tentative allocation so several unexplored jobs
                # round-robin probe nodes instead of the first in view
                # order absorbing the whole remaining budget
                gain = (t1 - t0) if t0 is not None and t1 is not None \
                    else explore / (n + 1.0)
                if best_job is None or gain > best_gain:
                    best_job, best_gain = v.job_id, gain
            if best_job is None:
                break
            alloc[best_job] += 1
            left -= 1
        # clamp to each job's range (budget < sum(min) leaves a job
        # under its min; it must still be a legal allocation)
        for v in views:
            alloc[v.job_id] = max(min(alloc[v.job_id], v.max_nodes),
                                  0 if alloc[v.job_id] < v.min_nodes
                                  else v.min_nodes)
        return alloc

    def decide(self, views: list[JobView], now: float) -> list[Proposal]:
        holds = {v.job_id: self._intake(v, now) for v in views}
        alloc = self.plan(views)
        proposals: dict[str, Proposal] = {}
        # shrink-before-grow: shrinks free budget grows then consume
        total = sum(v.effective_desired for v in views)
        for v in sorted(views, key=lambda v: alloc[v.job_id]
                        - v.effective_desired):
            job, cur = v.job_id, v.world_size
            desired = alloc[job]
            if holds[job] is not None:
                proposals[job] = Proposal(job, cur, cur, holds[job])
                continue
            if desired == cur:
                proposals[job] = Proposal(job, cur, cur, "converged")
                continue
            delta = desired - v.effective_desired
            if delta > 0:
                gain = None
                if cur >= 1:  # a suspended world predicts nothing
                    model = self.model(job)
                    t0, t1 = model.predict(cur), model.predict(desired)
                    gain = (t1 - t0) if t0 is not None and t1 is not None \
                        else None
                if gain is not None and gain <= 0:
                    proposals[job] = Proposal(job, cur, cur,
                                              "no-marginal-gain", gain)
                    continue
                if gain is not None and not self._amortizes(gain, v):
                    proposals[job] = Proposal(job, cur, cur,
                                              "grow-unamortized", gain)
                    continue
                if total + delta > self.budget:
                    proposals[job] = Proposal(job, cur, cur,
                                              "awaiting-budget", gain)
                    continue
                proposals[job] = Proposal(job, cur, desired,
                                          "fair-share-grow", gain)
            else:
                proposals[job] = Proposal(job, cur, desired,
                                          "fair-share-shrink")
            total += desired - v.effective_desired
        return [proposals[v.job_id] for v in views]

    # -- teacher pools in the same budget ------------------------------------
    # (`scaler/serving.ServingView`, duck-typed: policy.py stays free of
    # the serving plane's imports)

    @staticmethod
    def pool_demand(view) -> int:
        """Teachers this pool's SLO predicts it needs — the serving
        plane's bid in the water-fill. Capacity scales latency ~ 1/m
        (the pool serves an open-loop arrival stream), so hold the
        predicted p95 at 75% of the SLO; when there is no latency
        signal yet, bound utilization at 0.75 instead. The max of the
        two is the demand: latency is the contract, utilization the
        early warning."""
        n = max(1, view.n_teachers)
        need = view.min_teachers
        if view.latency_ms_p95 and view.slo_p95_ms:
            need = max(need, math.ceil(
                n * view.latency_ms_p95 / (0.75 * view.slo_p95_ms)))
        if view.util:
            need = max(need, math.ceil(n * view.util / 0.75))
        return max(view.min_teachers, min(view.max_teachers, need))

    def plan_mixed(self, trainer_views: list[JobView], serving_views
                   ) -> tuple[dict[str, int], dict[str, int]]:
        """One node budget across trainer worlds AND teacher pools.
        Pools are granted their predicted SLO demand FIRST — serving is
        user-facing, so SLO headroom outranks batch throughput — and
        trainers water-fill the remainder by predicted marginal
        throughput. Returns ``(trainer_alloc, pool_alloc)``."""
        pool_alloc: dict[str, int] = {}
        left = self.budget
        for v in serving_views:
            grant = min(self.pool_demand(v), max(left, 0))
            pool_alloc[v.service] = grant
            left -= grant
        trainer_alloc = self.plan(trainer_views, budget=max(left, 0))
        return trainer_alloc, pool_alloc

    def decide_mixed(self, trainer_views: list[JobView], serving_views,
                     now: float) -> tuple[list[Proposal], list[Proposal]]:
        """`decide` with teacher pools in the budget: one joint
        shrink-before-grow reconcile across BOTH planes, so a pool's
        SLO grow can be funded by a trainer shrink within the same
        tick's accounting and the live total never transiently exceeds
        the budget. Returns proposals per plane, each in view order.
        (Cooldown state is keyed by id: a job and a service sharing a
        name would share a cooldown clock — don't do that.)"""
        trainer_alloc, pool_alloc = self.plan_mixed(trainer_views,
                                                    serving_views)
        # (kind, id, view, target, hold-reason)
        rows: list[tuple[str, str, object, int, str | None]] = []
        for v in trainer_views:
            rows.append(("trainer", v.job_id, v, trainer_alloc[v.job_id],
                         self._intake(v, now)))
        for v in serving_views:
            hold = None
            if not v.fresh or v.n_teachers < 1:
                hold = "no-fresh-serving-stats"
            elif v.effective_desired != v.n_teachers:
                hold = "resize-in-flight"
            else:
                resized_at = self._resized_at.get(v.service)
                if resized_at is not None \
                        and now - resized_at < self.cooldown_s:
                    hold = "cooldown"
            rows.append(("serving", v.service, v, pool_alloc[v.service],
                         hold))
        proposals: dict[str, Proposal] = {}
        total = sum(v.effective_desired for _, _, v, _, _ in rows)
        for kind, rid, v, desired, hold in sorted(
                rows, key=lambda r: r[3] - r[2].effective_desired):
            cur = v.world_size if kind == "trainer" else v.n_teachers
            if hold is not None:
                proposals[rid] = Proposal(rid, cur, cur, hold)
                continue
            if desired == cur:
                proposals[rid] = Proposal(rid, cur, cur, "converged")
                continue
            delta = desired - v.effective_desired
            if delta > 0:
                gain = None
                if kind == "trainer" and cur >= 1:
                    model = self.model(rid)
                    t0, t1 = model.predict(cur), model.predict(desired)
                    gain = (t1 - t0) if t0 is not None and t1 is not None \
                        else None
                    if gain is not None and gain <= 0:
                        proposals[rid] = Proposal(rid, cur, cur,
                                                  "no-marginal-gain", gain)
                        continue
                    if gain is not None and not self._amortizes(gain, v):
                        proposals[rid] = Proposal(rid, cur, cur,
                                                  "grow-unamortized", gain)
                        continue
                if total + delta > self.budget:
                    proposals[rid] = Proposal(rid, cur, cur,
                                              "awaiting-budget", gain)
                    continue
                reason = ("fair-share-grow" if kind == "trainer"
                          else "slo-fair-share-grow")
                proposals[rid] = Proposal(rid, cur, desired, reason, gain)
            else:
                reason = ("fair-share-shrink" if kind == "trainer"
                          else "slo-fair-share-shrink")
                proposals[rid] = Proposal(rid, cur, desired, reason)
            total += desired - v.effective_desired
        return ([proposals[v.job_id] for v in trainer_views],
                [proposals[v.service] for v in serving_views])
