"""Benchmark-result JSON emission for training jobs.

Capability of the reference's benchmark_test output (example/collective/
resnet50/train_with_fleet.py:642-658: rank 0 writes
benchmark_logs/log_{rank} holding final eval metrics, the per-epoch
metric log, max epoch throughput x world size, and the batch size) with
a sane schema instead of numbered string keys.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any

from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.train.benchlog")


class BenchmarkLog:
    """Collects per-epoch metrics + throughput; writes one JSON file.

    Usage:
        blog = BenchmarkLog("resnet50_vd", batch_size=256, world_size=8)
        for epoch ...:
            blog.epoch(epoch, examples_per_sec=..., **eval_metrics)
        blog.write(out_dir, rank)
    """

    def __init__(self, model: str, batch_size: int, world_size: int = 1,
                 **extra: Any):
        self.result: dict[str, Any] = {
            "model": model,
            "batch_size": batch_size,
            "world_size": world_size,
            "started_unix": time.time(),
            "epochs": [],
            **extra,
        }

    def extra(self, **kv: Any) -> None:
        """Merge late top-level extras (e.g. the train loop's checkpoint
        save-stall/restore accounting, known only after the run)."""
        self.result.update({k: _scalar(v) for k, v in kv.items()})

    def epoch(self, epoch: int, examples_per_sec: float | None = None,
              **metrics: Any) -> None:
        entry = {"epoch": epoch, **{k: _scalar(v) for k, v in metrics.items()}}
        if examples_per_sec is not None:
            entry["examples_per_sec"] = float(examples_per_sec)
        self.result["epochs"].append(entry)

    def finalize(self) -> dict:
        if self.result.get("elapsed_secs") is not None:
            return self.result  # idempotent: keep the first finalize's stats
        epochs = self.result["epochs"]
        speeds = [e["examples_per_sec"] for e in epochs
                  if "examples_per_sec" in e]
        if speeds:
            # reference result['1']: max epoch speed x trainer count
            self.result["max_examples_per_sec"] = max(speeds)
            self.result["max_examples_per_sec_global"] = (
                max(speeds) * self.result["world_size"])
        if epochs:
            self.result["final"] = {k: v for k, v in epochs[-1].items()
                                    if k != "epoch"}
        self.result["elapsed_secs"] = time.time() - self.result["started_unix"]
        return self.result

    def write(self, out_dir: str = "./benchmark_logs", rank: int = 0) -> str:
        self.finalize()
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f"log_{rank}.json")
        with open(path, "w") as f:
            json.dump(self.result, f, indent=1)
        log.info("benchmark log written to %s", path)
        return path


def _scalar(v: Any) -> Any:
    try:
        return float(v)
    except (TypeError, ValueError):
        return v
