"""Epoch-based training loop with checkpoint/resume and throughput logging.

The host-side driver equivalent of the reference's trainer main loop
(example/collective/resnet50/train_with_fleet.py:347-610: resume epoch from
TrainStatus, hot loop over the input pipeline, rank-0 checkpoint each epoch,
periodic img/s + loss prints, optional eval each epoch) — redesigned for
JAX: the step is a jitted pure function `(state, batch) -> (state, metrics)`
with the batch sharded over the mesh's data axes and state placement left to
the step's shardings; elasticity comes from re-entering `run()` after a
restart with a different mesh.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Iterable

import jax

from edl_tpu.obs import recorder as flight
from edl_tpu.obs import trace
from edl_tpu.parallel import mesh as mesh_lib
from edl_tpu.train.checkpoint import CheckpointManager
from edl_tpu.train.state import TrainStatus
from edl_tpu.utils.config import field
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.train.loop")


@dataclass
class LoopConfig:
    num_epochs: int = field(1, env="EDL_TPU_NUM_EPOCHS")
    log_every_steps: int = field(20, env="EDL_TPU_LOG_EVERY")
    ckpt_dir: str | None = field(None, env="EDL_TPU_CHECKPOINT_PATH")
    ckpt_every_epochs: int = field(1, env="EDL_TPU_SAVE_CHECKPOINT_INTER")
    # Step-interval checkpointing — cheap under async saves, so elastic
    # jobs can shrink their replay-after-reformation window to N steps.
    ckpt_every_steps: int = field(0, env=("EDL_TPU_CKPT_STEPS",
                                          "EDL_TPU_SAVE_CHECKPOINT_STEPS"))
    ckpt_max_to_keep: int = field(3, env="EDL_TPU_CHECKPOINT_KEEP")
    # Async snapshot-then-write saves (checkpoint.save_async): the step
    # loop blocks only for the device->host snapshot; serialization +
    # disk + mirror ride a background writer. False = the synchronous
    # escape hatch (every save is a full stall, bytes identical).
    ckpt_async: bool = field(True, env="EDL_TPU_CKPT_ASYNC")
    # Persistent XLA compilation-cache dir: a re-formed world whose
    # programs didn't change skips recompiling them on restart
    # (parallel/distributed.enable_compilation_cache).
    compile_cache_dir: str | None = field(None,
                                          env="EDL_TPU_COMPILE_CACHE_DIR")
    # Sharded (per-process chunk) checkpoints — required once params are
    # fsdp/tp-sharded; replicated msgpack is the small-model default.
    ckpt_sharded: bool = field(False, env="EDL_TPU_CHECKPOINT_SHARDED")
    # Remote mirror URI (gs://, hdfs://, file://) — rank 0 uploads each
    # sealed version, cold pods fetch before restore (utils/fs.py).
    ckpt_remote: str | None = field(None, env="EDL_TPU_CKPT_REMOTE")
    # jax.profiler trace window (the reference's --profile traces batches
    # 100-105 on trainer 0, train_with_fleet.py:521-530): when
    # profile_dir is set, rank 0 captures [profile_start_step,
    # profile_start_step + profile_steps) global steps.
    profile_dir: str | None = field(None, env="EDL_TPU_PROFILE_DIR")
    profile_start_step: int = field(10, env="EDL_TPU_PROFILE_START")
    profile_steps: int = field(5, env="EDL_TPU_PROFILE_STEPS")
    # Host->device prefetch: stage up to N placed batches on a daemon
    # thread while the current step computes, so the device_put of batch
    # i+1 hides under step i (H2D overlap — the distill serving path's
    # student-side half). 0 = place inline on the training thread.
    prefetch_batches: int = field(0, env="EDL_TPU_PREFETCH_BATCHES")
    # Input-plane worker processes (DataLoader num_workers): the
    # shared-memory mp loader that scales host decode/augment past the
    # GIL (data/mp_loader.py). 0 = inline/threaded path. The imagenet/lm
    # entrypoints read this as the DataLoader's num_workers whenever the
    # --loader-workers CLI flag is not given; DataLoader itself also
    # honors the same env var when num_workers is left unset.
    loader_workers: int = field(0, env="EDL_TPU_LOADER_WORKERS")
    # Device-side augmentation (ops/augment.py): the loader ships raw
    # packed/npz bytes + the parent-drawn per-step seed and jitted
    # crop/flip/normalize runs on the accelerator, overlapping the step
    # instead of burning host cores. Entrypoints read this to build the
    # loader with emit_batch_seed=True and hand TrainLoop an augment_fn
    # (imagenet_train --augment-device); 0 = host transforms, the
    # unchanged fallback path.
    augment_device: bool = field(False, env="EDL_TPU_AUGMENT_DEVICE")
    # DCN-aware gradient path (train/comm.py): bucket the gradient
    # tree into comm_bucket_mb-MiB reduction groups (0 = keep the
    # XLA-partitioned single-graph reduction) and optionally compress
    # the cross-slice DCN leg (off|topk|int8, error-feedback residuals,
    # loss-parity gated). Entrypoints read these to build the manual
    # step (--dcn-compress / --comm-bucket-mb override).
    comm_bucket_mb: float = field(0.0, env="EDL_TPU_COMM_BUCKET_MB")
    dcn_compress: str = field("off", env="EDL_TPU_DCN_COMPRESS")
    # Expert-parallel dispatch (train/comm.py MoE section): how the
    # token all-to-all decomposes (flat single collective | hier =
    # ICI leg + cross-slice DCN leg) and the DCN leg's wire format
    # (off | int8, one scale per destination slice, parity-gated).
    # Entrypoints read these for --moe runs (--moe-dispatch /
    # --moe-compress override).
    moe_dispatch: str = field("hier", env="EDL_TPU_MOE_DISPATCH")
    moe_compress: str = field("off", env="EDL_TPU_MOE_COMPRESS")
    # Fused optimizer path (train/fused_opt.py): the whole momentum-SGD
    # / Adam update as one Pallas VMEM pass per parameter bucket.
    # off = the optax chain; fp32 = fused, bitwise vs optax; int8/fp8 =
    # fused + quantized resident moments with error-feedback residuals
    # (opt state, checkpoint and migration bytes halve; convergence-
    # parity gated). Entrypoints read these (--fused-opt overrides).
    fused_opt: str = field("off", env="EDL_TPU_FUSED_OPT")
    # Resident-moment codec override: off | int8 | fp8. Empty = derive
    # from fused_opt (fp32 -> off, int8 -> int8, fp8 -> fp8).
    opt_quant: str = field("", env="EDL_TPU_OPT_QUANT")


class TrainLoop:
    """Drives (state, batch) -> (state, metrics) steps over epochs.

    Args:
      step_fn: jitted step. Called as step_fn(state, batch).
      state: initial TrainState (ignored if a checkpoint is restored).
      mesh: device mesh; batches are sharded over its data axes.
      config: LoopConfig.
      eval_fn: optional callable(state, epoch) -> dict, run after each epoch.
      hooks: optional callables(loop, epoch, step, metrics) run at log points.
    """

    def __init__(self, step_fn: Callable, state: Any,
                 mesh=None, config: LoopConfig | None = None,
                 eval_fn: Callable | None = None,
                 hooks: list[Callable] | None = None,
                 batch_axes: tuple[str, ...] | None = None,
                 place_state: Callable | None = None,
                 on_reform: Callable | None = None,
                 reform_mesh: Callable | None = None,
                 reform_config=None,
                 augment_fn: Callable | None = None):
        self.step_fn = step_fn
        self.state = state
        self.mesh = mesh
        # Device-side augmentation hook (ops.augment.make_device_augment):
        # `(placed_batch, seed) -> batch`, applied after placement with
        # the per-step seed the loader emitted (emit_batch_seed=True) —
        # the jitted dispatch overlaps the running step.
        self.augment_fn = augment_fn
        # Re-places a restored host-side state pytree onto devices (required
        # in a multi-process world where host numpy can't feed a global-mesh
        # jit directly — e.g. mesh_lib.replicate_host_tree, or a sharded
        # checkpoint's re-placement rules).
        self.place_state = place_state
        self.config = config or LoopConfig()
        self.eval_fn = eval_fn
        self.hooks = hooks or []
        self.batch_axes = batch_axes
        self.status = TrainStatus(
            world_size=mesh_lib.dp_size(mesh) if mesh is not None
            else jax.device_count())
        self.ckpt = (CheckpointManager(self.config.ckpt_dir,
                                       self.config.ckpt_max_to_keep,
                                       sharded=self.config.ckpt_sharded,
                                       remote=self.config.ckpt_remote)
                     if self.config.ckpt_dir else None)
        if self.config.compile_cache_dir:
            from edl_tpu.parallel.distributed import enable_compilation_cache
            enable_compilation_cache(self.config.compile_cache_dir)
        self.last_metrics: dict = {}
        self._profiling = False
        # Save-stall accounting (benchlog/timeline): step-loop-visible ms
        # spent in _save calls — full write under sync, snapshot copy
        # under async — plus the restore seconds of this run's resume.
        self.ckpt_stall_ms_total = 0.0
        self.ckpt_saves = 0
        self.restore_s: float | None = None
        self._first_step_done = False
        # World size recorded in the restored checkpoint, set by
        # try_restore(); None until a restore happens. Consumers use it to
        # rescale LR/batch after an elastic resize (lr.scale_for_world).
        self.saved_world_size: int | None = None
        # Under the elastic launcher, publish step rate / samples_seen
        # into the pod's leased /{job}/util/ record so the Collector
        # (scheduler data path, reference discovery/register.py:36-40
        # `info`) sees fresh trainer utilization. No-op standalone, and
        # never blocks training: a failure here only disables publishing.
        try:
            from edl_tpu.coord.collector import UtilizationPublisher
            self._util_publisher = UtilizationPublisher.from_env()
        except Exception:  # noqa: BLE001 — observability is optional
            self._util_publisher = None
        if self._util_publisher is not None:
            self.hooks = list(self.hooks) + [self._util_publisher]
        # State-migration plane (collective/migration.py): under the
        # elastic launcher with EDL_TPU_RESIZE_P2P on, this trainer (a)
        # serves its retained sealed checkpoint snapshot to peers, (b)
        # prefers restoring from live donors over disk, and (c) adopts
        # resizes that keep this pod IN PLACE — re-entering the epoch at
        # the cursor with the new (rank, world) instead of dying into a
        # stop-resume. `on_reform(rank, world, cluster)` is the caller's
        # hook to re-derive data sharding for the new world.
        self.on_reform = on_reform
        # Reform state machine hooks (collective/reform.py) — the
        # device-world half of elasticity: `reform_mesh(rank, world,
        # cluster)` returns the NEW mesh when the resize changes this
        # process's device world (None = unchanged, the fast adoption
        # path). The hook owns any `jax.distributed` re-initialization
        # (parallel/distributed.reform_world) for true multi-host
        # worlds; the loop then reshapes state through peer restore
        # (disk fallback), re-jits under the compilation cache, and
        # acks generation-fenced. A loop wired with this hook seals its
        # live state at quiesce, so a reform loses zero progress.
        self.reform_mesh = reform_mesh
        self._reform_config = reform_config
        self._reform_machine = None
        self.last_reform: dict | None = None
        self._migration = None
        if self.ckpt is not None:
            try:
                from edl_tpu.collective.migration import MigrationService
                self._migration = MigrationService.from_env(self.ckpt)
            except Exception:  # noqa: BLE001 — the plane is optional;
                log.warning("migration service unavailable",  # train on
                            exc_info=True)
        self.restore_source: str | None = None
        self.bytes_from_peers = 0
        self.reforms = 0
        self.last_reform_downtime_s: float | None = None
        self.stop_reason: str | None = None
        self._reform_t0: float | None = None
        # the in-flight adoption's trace span: opened at reform, ended
        # at the first step of the new generation — its duration IS the
        # measured p2p downtime, inside the resize's causal trace
        self._reform_span = None

    # -- checkpoint glue ---------------------------------------------------

    def try_restore(self) -> bool:
        if self.ckpt is None:
            return False
        # Startup GC: torn .tmp-* partial saves from a crashed/killed
        # writer are invisible to restore (never sealed) but leak disk
        # forever otherwise — the trainer start path owns the sweep.
        self.ckpt.gc_stale_tmp()
        restored = None
        if self._migration is not None:
            # Peer-first restore: live donors serve the state straight
            # from memory over the tensor wire; disk is only the
            # fallback. The local disk version is the fence — a stale
            # donor never beats a newer sealed local checkpoint.
            from edl_tpu.collective.migration import PeerRestoreError
            t0 = time.perf_counter()
            try:
                state, status, stats = self._migration.restore_from_peers(
                    self.state, local_version=self.ckpt.latest_version())
                restored = (state, status)
                self.restore_source = "peers"
                self.bytes_from_peers = int(stats["bytes_from_peers"])
                self.ckpt.last_restore_s = time.perf_counter() - t0
            except PeerRestoreError as exc:
                log.info("peer restore unavailable (%s) — falling back "
                         "to disk", exc)
        if restored is None:
            restored = self.ckpt.restore(self.state)
            if restored is not None:
                self.restore_source = "disk"
        self.restore_s = self.ckpt.last_restore_s
        if restored is None:
            return False
        self.state, self.status = restored
        if self.place_state is not None:
            self.state = self.place_state(self.state)
        # Preserve the save-time world size (the resharding/LR-rescale hint)
        # before stamping the current world for the next save.
        self.saved_world_size = self.status.world_size
        self.status.world_size = (mesh_lib.dp_size(self.mesh)
                                  if self.mesh is not None
                                  else jax.device_count())
        return True

    def _save(self, sync: bool | None = None) -> None:
        """Checkpoint now. Async by default (config.ckpt_async): blocks
        only for the snapshot copy; ``sync=True`` is the per-call escape
        hatch that waits for the full write."""
        if self.ckpt is None:
            return
        use_sync = (not self.config.ckpt_async) if sync is None else sync
        t0 = time.perf_counter()
        if use_sync:
            self.ckpt.save(self.state, self.status)
        else:
            self.ckpt.save_async(self.state, self.status)
        self.ckpt_stall_ms_total += (time.perf_counter() - t0) * 1e3
        self.ckpt_saves += 1

    def _adopt(self, reform) -> str:
        """Adopt a resize in place: the new cluster still contains this
        pod, so instead of dying into a stop-resume it walks the reform
        state machine (collective/reform.py). An unchanged device set
        keeps the fast path (no seal, no restore — the 0.061 s survivor
        gap); a device-world change (reform_mesh returns a new mesh)
        pays quiesce-seal -> mesh-reform -> peer-restore (disk
        fallback) -> re-jit, all inside the same OS process. Returns
        "reform" (in place) or "stop" — the clean stop-resume downgrade
        when a phase missed its deadline or failed past its fallback.
        The measured gap (adoption -> first step of the new generation)
        is the resize downtime for survivors either way."""
        from edl_tpu.collective import reform as rf
        self._reform_t0 = time.perf_counter()
        if trace.enabled():
            from edl_tpu.collective.migration import resize_trace_ctx
            self._reform_span = trace.start_span(
                "resize.adopt",
                parent=resize_trace_ctx(self._migration.store,
                                        self._migration.job_id),
                attrs={"pod": self._migration.pod_id,
                       "rank": reform.rank, "world": reform.world_size,
                       "generation": reform.generation})
        log.info("live-reform: adopting cluster v%d rank=%d world=%d in "
                 "place (no respawn)", reform.generation, reform.rank,
                 reform.world_size)
        machine = rf.ReformMachine(
            reform.generation, self._reform_config,
            trace_parent=(self._reform_span.context
                          if self._reform_span is not None else None),
            who=self._migration.pod_id)
        self._reform_machine = machine
        changed: dict = {}
        try:
            machine.run_phase("quiesce", self._reform_quiesce)
            machine.run_phase(
                "mesh-reform",
                lambda dl: changed.update(
                    mesh=self._reform_mesh_phase(reform, dl)))
            if changed.get("mesh") is not None:
                try:
                    machine.run_phase("peer-restore",
                                      self._reform_restore_peers)
                    machine.restore = "peers"
                except rf.ReformError as exc:
                    if exc.downgrade != "disk":
                        raise
                    log.warning("reform peer-restore failed (%s) — "
                                "disk-restore downgrade", exc)
                    machine.run_phase("disk-restore",
                                      self._reform_restore_disk)
                    machine.restore = "disk"
                self.status.world_size = mesh_lib.dp_size(self.mesh)
            machine.result = rf.IN_PLACE
        except rf.ReformError as exc:
            # The defined downgrade: degrade to a CLEAN stop-resume.
            # This trainer behaves exactly like a graceful SIGTERM stop
            # — run() seals the live state, exits 143 and the migration
            # shutdown lingers as a donor — and the launcher's adopt
            # timeout respawns the world. A half-reformed survivor
            # never acks: its generation is stale, so even a late ack
            # attempt bounces off the epoch-doc fence.
            machine.result = rf.STOP_RESUME
            machine.error = str(exc)
            self.last_reform = machine.finish()
            self._reform_machine = None
            log.warning("reform of generation %d degraded to "
                        "stop-resume: %s", reform.generation, exc)
            self.stop_reason = "reform-downgrade"
            self._migration.stop_requested.set()
            if self._reform_span is not None:
                self._reform_span.end(result=machine.result,
                                      error=machine.error)
                self._reform_span = None
            self._reform_t0 = None
            return "stop"
        if self.on_reform is not None:
            self.on_reform(reform.rank, reform.world_size, reform.cluster)
        if self._util_publisher is not None:
            # the scaler's unit contract: rates must be tagged with the
            # allocation (pod count) + generation that produced them
            self._util_publisher.world_size = reform.world_size
            self._util_publisher.generation = reform.generation
        self._migration.adopted(reform)
        self.reforms += 1
        return "reform"

    # -- reform phase executors (collective/reform.py ladder) --------------

    def _reform_quiesce(self, deadline: float) -> None:
        """Settle the device and (for device-world reforms) seal the
        LIVE state: peer-restore then reassembles exactly this step on
        the new mesh — a reform loses zero progress. Orchestration-only
        adoptions (no reform_mesh hook) keep the cheap drain."""
        if self._first_step_done:
            jax.block_until_ready(self.state)
        if self.ckpt is None:
            return
        if self.reform_mesh is not None:
            self._save()
        # TimeoutError here is the typed quiesce failure the machine
        # downgrades on (a writer that cannot drain is a torn world)
        self.ckpt.wait(timeout=max(0.1, deadline - time.monotonic()))
        if self._migration is not None and self.reform_mesh is not None:
            # make the fresh seal discoverable before peer-restore runs
            self._migration.flush_advert()

    def _reform_mesh_phase(self, reform, deadline: float):
        """Apply the new topology. The hook owns any jax.distributed
        re-initialization (reform_world) for true multi-host worlds and
        returns the new mesh, or None when this process's device world
        is unchanged (the fast adoption path)."""
        del deadline  # cooperative: the hook gets the machine's budget
        if self.reform_mesh is None:
            return None
        mesh = self.reform_mesh(reform.rank, reform.world_size,
                                reform.cluster)
        if mesh is None:
            return None
        log.info("reform: device world changed — new mesh %s",
                 getattr(mesh, "shape", mesh))
        self.mesh = mesh
        return mesh

    def _reform_target(self):
        """Zero state pytree shaped like the live state, placed for the
        NEW mesh — what the resharding planner assembles into."""
        import numpy as np
        zeros = jax.tree.map(
            lambda a: np.zeros(a.shape, a.dtype)
            if hasattr(a, "shape") else a, self.state)
        return self.place_state(zeros) if self.place_state else zeros

    def _reform_restore_peers(self, deadline: float) -> None:
        del deadline  # restore_from_peers carries its own wire timeouts
        # Sharded worlds merge every donor (versions are world-aligned
        # by the save barrier); replicated per-pod states restore from
        # their OWN just-sealed snapshot — per-pod version counters are
        # not comparable, and each pod's state is its own lineage.
        pods = None if self.config.ckpt_sharded \
            else [self._migration.pod_id]
        state, status, stats = self._migration.restore_from_peers(
            self._reform_target(),
            local_version=self.ckpt.latest_version()
            if self.ckpt else None, pods=pods)
        self.state = state
        self.status = status
        self.restore_source = "peers"
        self.bytes_from_peers = int(stats["bytes_from_peers"])

    def _reform_restore_disk(self, deadline: float) -> None:
        del deadline
        restored = self.ckpt.restore(self._reform_target()) \
            if self.ckpt else None
        if restored is None:
            raise RuntimeError("no sealed local checkpoint to fall "
                               "back to")
        state, status = restored
        if self.place_state is not None:
            state = self.place_state(state)
        self.state = state
        self.status = status
        self.restore_source = "disk"

    def ckpt_stats(self) -> dict:
        """Checkpoint-plane accounting for benchlog extras: loop-side
        stall totals + the manager's snapshot/write/supersede stats."""
        out = {"ckpt_save_stall_ms_total": round(self.ckpt_stall_ms_total, 3),
               "ckpt_save_stall_ms_mean": round(
                   self.ckpt_stall_ms_total / self.ckpt_saves, 3)
               if self.ckpt_saves else 0.0,
               "ckpt_saves": self.ckpt_saves,
               "ckpt_async": bool(self.config.ckpt_async)}
        if self.restore_s is not None:
            out["ckpt_restore_s"] = round(self.restore_s, 3)
        # state-migration plane accounting (resize_bench/demo audits)
        out["restore_source"] = self.restore_source
        out["bytes_from_peers"] = self.bytes_from_peers
        out["reforms"] = self.reforms
        if self.last_reform_downtime_s is not None:
            out["reform_downtime_s"] = round(
                self.last_reform_downtime_s, 4)
        if self.last_reform is not None:
            # the state machine's outcome (result / restore source /
            # per-phase seconds) — what resize_bench's world axis and
            # the --resize-reform demo audit read
            out["reform"] = self.last_reform
        if self.ckpt is not None:
            out.update({f"ckpt_{k}": (round(v, 3)
                                      if isinstance(v, float) else v)
                        for k, v in self.ckpt.stats().items()})
        return out

    # -- main loop ---------------------------------------------------------

    def _place(self, batch):
        # Device augmentation: the loader-emitted per-step seed comes off
        # the batch BEFORE placement (a 0-d scalar can't shard over the
        # batch axes); the jitted augment applies after. Batches already
        # augmented upstream (prefetch_to_device(augment=...)) carry no
        # seed and pass through; a seed with no augment_fn (or the
        # reverse) raises a wiring error instead of mis-sharding.
        seed = None
        if self.augment_fn is not None or (isinstance(batch, dict)
                                           and "augment_seed" in batch):
            from edl_tpu.data.pipeline import pop_augment_seed
            batch, seed = pop_augment_seed(batch, self.augment_fn)
        if self.mesh is not None:
            # form_global_batch degenerates to shard_batch in a
            # single-process world; in a multi-process world it treats
            # the fed batch as this process's slice of the global batch
            # (multipod contract).
            batch = mesh_lib.form_global_batch(self.mesh, batch,
                                               self.batch_axes)
        if self.augment_fn is not None:
            batch = self.augment_fn(batch, seed)
        return batch

    def run(self, data_fn: Callable[[int], Iterable],
            batch_size_fn: Callable[[Any], int] | None = None) -> TrainStatus:
        """Train from the resume point to num_epochs.

        data_fn(epoch) returns the epoch's batch iterator (the seed-per-pass
        hook: the callee should derive data order from the epoch number so an
        elastic restart replays the same order — reference reader_cv2
        pass_id_as_seed, train_with_fleet.py:459-464).
        """
        try:
            self.try_restore()
            cfg = self.config
            start_epoch = self.status.next_epoch()
            if start_epoch >= cfg.num_epochs:
                log.info("training already complete (epoch=%d)",
                         self.status.epoch)
                return self.status
            for epoch in range(start_epoch, cfg.num_epochs):
                outcome = self._run_epoch(epoch, data_fn, batch_size_fn)
                while outcome == "reform":
                    # In-place adoption: same epoch re-entered at the
                    # step cursor with the new (rank, world) — the
                    # mid-epoch resume machinery replays the skip, the
                    # state never leaves the devices.
                    outcome = self._run_epoch(epoch, data_fn,
                                              batch_size_fn)
                if outcome == "stop":
                    # Graceful stop (SIGTERM under the launcher): seal
                    # the live state so the donor linger serves the
                    # freshest params to the re-formed world, then exit
                    # 143 — the finally block drains the write and
                    # lingers. Raising (not returning) matters: an
                    # example main that returns 0 after run() would
                    # read to the launcher as "training complete" and
                    # mark the whole job done off a stray SIGTERM.
                    log.info("graceful stop at epoch %d step %d",
                             epoch, self.status.step)
                    self._save()
                    raise SystemExit(143)
                self.status.epoch = epoch
                self.status.step_in_epoch = 0
                if (epoch + 1) % max(1, cfg.ckpt_every_epochs) == 0 \
                        or epoch == cfg.num_epochs - 1:
                    self._save()
                if self.eval_fn is not None:
                    results = self.eval_fn(self.state, epoch)
                    log.info("eval epoch %d: %s", epoch, _fmt(results))
                if self.ckpt is not None:
                    # Epoch-end barrier: the epoch's (async) save becomes
                    # durable before the next epoch starts — its write
                    # overlapped eval above — and a background write
                    # failure surfaces here, not epochs later.
                    self.ckpt.wait()
            if self._profiling:  # run shorter than the window: still flush
                jax.profiler.stop_trace()
                self._profiling = False
            if self.ckpt_saves:
                log.info("ckpt plane: %s", self.ckpt_stats())
            return self.status
        finally:
            if self.ckpt is not None:
                # Shutdown barrier: drain the pending snapshot (crash
                # paths still seal their last state) without masking an
                # in-flight exception; clean-path write errors already
                # surfaced at the epoch-end wait() above.
                self.ckpt.close(raise_errors=False)
            if self._migration is not None:
                # After ckpt.close() so the drained final snapshot is
                # retained and served: on a graceful stop this lingers
                # as a donor until the re-formed world acks (bounded).
                try:
                    self._migration.shutdown()
                except Exception:  # noqa: BLE001 — teardown
                    log.exception("migration shutdown failed")
            # Even on a crash or the already-complete early return, the
            # lease must be revoked so a dead trainer's utilization
            # record expires instead of being kept fresh forever.
            if self._util_publisher is not None:
                self._util_publisher.stop()
            # The loop owns the lifetime of the data plane it drives: a
            # data_fn with a close() (DataLoader is callable and is one;
            # examples attach loader.close to their wrappers) gets its
            # decode pool / worker processes joined and shm unlinked —
            # including on the crash path, where an abandoned mp pool
            # would otherwise linger until GC.
            closer = getattr(data_fn, "close", None)
            if callable(closer):
                closer()

    def close(self) -> None:
        """Teardown for a loop that never ran (or whose owner wants a
        deterministic release without calling `run`): drain/stop the
        checkpoint writer, migration donor and utilization publisher.
        `run()` performs the same teardown on its own finally path —
        this exists so an owner that builds a TrainLoop and aborts
        before running it still has a joining close (edl-lint
        resource-lifecycle); idempotent either way."""
        if self.ckpt is not None:
            self.ckpt.close(raise_errors=False)
        if self._migration is not None:
            try:
                self._migration.shutdown()
            except Exception:  # noqa: BLE001 — teardown
                log.exception("migration shutdown failed")
        if self._util_publisher is not None:
            self._util_publisher.stop()

    def _profile_window(self) -> None:
        """Start/stop the jax profiler trace at the configured global
        steps (rank 0 only — one host's trace is the analysis unit)."""
        cfg = self.config
        if cfg.profile_dir is None or jax.process_index() != 0:
            return
        if self.status.step == cfg.profile_start_step \
                and not self._profiling:
            log.info("profiler: tracing steps %d..%d -> %s",
                     cfg.profile_start_step,
                     cfg.profile_start_step + cfg.profile_steps,
                     cfg.profile_dir)
            jax.profiler.start_trace(cfg.profile_dir)
            self._profiling = True
        elif self._profiling and self.status.step >= \
                cfg.profile_start_step + cfg.profile_steps:
            # force pending dispatches to land inside the trace; the
            # state is the live device data (last_metrics is already
            # host numpy by the time it's stored)
            jax.block_until_ready(self.state)
            jax.profiler.stop_trace()
            self._profiling = False
            log.info("profiler: trace written to %s", cfg.profile_dir)

    def _epoch_iter(self, src, skip: int):
        """(index, device-placed batch) pairs starting at ``skip``.

        Skipping happens BEFORE placement so a mid-epoch resume never
        transfers already-trained batches. With ``prefetch_batches > 0``
        placement runs on a staging thread `prefetch_batches` deep, so
        the host->device copy of batch i+1 hides under step i.
        """
        end = object()
        it = iter(src)
        for _ in range(skip):
            if next(it, end) is end:
                return
        if self.config.prefetch_batches > 0:
            from edl_tpu.data.pipeline import prefetch
            staged = prefetch(it, size=self.config.prefetch_batches,
                              place=self._place)
            try:
                yield from enumerate(staged, start=skip)
            finally:
                staged.close()
        else:
            for i, batch in enumerate(it, start=skip):
                yield i, self._place(batch)

    def _run_epoch(self, epoch: int, data_fn, batch_size_fn) -> None:
        cfg = self.config
        window_start = time.perf_counter()
        window_samples = 0
        rejit_s = 0.0  # set at the first dispatch of an adopted reform
        # Intra-epoch resume: a mid-epoch checkpoint recorded how many steps
        # of this (deterministically re-generated, seed-per-pass) epoch were
        # already applied — skip exactly that many batches without training
        # or re-counting them. The data-level analogue of the reference's
        # record-skip design (collective/dataloader.py:100-120 "PROCSSED"
        # record ranges).
        skip = self.status.step_in_epoch
        if skip:
            log.info("resuming mid-epoch: skipping %d already-trained "
                     "batches of epoch %d", skip, epoch)
        src = data_fn(epoch)
        it = self._epoch_iter(src, skip)
        for i, batch in it:
            if self._migration is not None:
                if self._migration.stop_requested.is_set():
                    # Graceful stop: leave at the step boundary with the
                    # cursor intact; run() seals the live state and the
                    # donor linger takes over.
                    self.stop_reason = "sigterm"
                    it.close()
                    return "stop"
                reform = self._migration.poll_reform()
                if reform is not None:
                    it.close()
                    # "reform" re-enters the epoch in place; "stop" is
                    # the machine's clean stop-resume downgrade
                    return self._adopt(reform)
            self._profile_window()
            t_dispatch = time.perf_counter()
            self.state, metrics = self.step_fn(self.state, batch)
            if self._reform_t0 is not None:
                # first dispatch of the adopted generation: the call
                # wall covers trace + (cache-missing) compile — the
                # re-jit phase of the reform ladder
                rejit_s = time.perf_counter() - t_dispatch
            if not self._first_step_done:
                # Downtime-accounting marker: the first step of THIS run
                # (post-restore, post-compile) has really executed — the
                # elastic kill->resume bench keys on this line, so force
                # the dispatch before stamping it.
                jax.block_until_ready(self.state)
                self._first_step_done = True
                log.info("first-step-complete global_step=%d restore_s=%s",
                         self.status.step + 1,
                         "%.3f" % self.restore_s
                         if self.restore_s is not None else "none")
                if self._migration is not None:
                    # restore ack: this pod is trained-and-running —
                    # what lingering donors and the resize audit key on
                    self._migration.ack(
                        self.restore_source or "fresh",
                        bytes_from_peers=self.bytes_from_peers,
                        restore_s=self.restore_s)
                    if self.restore_source == "peers" \
                            and trace.enabled() \
                            and self._util_publisher is not None:
                        # a grown pod's first fresh util closes the
                        # resize trace the same way an adoption's does
                        from edl_tpu.collective.migration import \
                            resize_trace_ctx
                        self._util_publisher.resize_trace = \
                            resize_trace_ctx(self._migration.store,
                                             self._migration.job_id)
            if self._reform_t0 is not None:
                # First step of the adopted generation: force the
                # dispatch so the measured gap covers real training
                # resumption, not an async enqueue.
                t_block = time.perf_counter()
                jax.block_until_ready(self.state)
                now = time.perf_counter()
                gap = now - self._reform_t0
                self._reform_t0 = None
                self.last_reform_downtime_s = gap
                reform_doc = None
                if self._reform_machine is not None:
                    # close the deferred ladder phases: the first
                    # post-reform step IS re-jit (dispatch wall; a
                    # compile-cache hit collapses it) + first-step
                    machine = self._reform_machine
                    self._reform_machine = None
                    machine.note_deferred("re-jit", rejit_s)
                    machine.note_deferred("first-step", now - t_block)
                    reform_doc = self.last_reform = machine.finish()
                log.info("reform-step-complete generation=%d "
                         "downtime_s=%.3f",
                         self._migration.generation, gap)
                flight.record("resize_adopt",
                              pod=self._migration.pod_id,
                              generation=self._migration.generation,
                              downtime_s=round(gap, 4))
                if self._reform_span is not None:
                    # the span covers reform -> first step of the new
                    # generation: duration == the measured survivor gap
                    self._reform_span.end(downtime_s=round(gap, 4))
                    if self._util_publisher is not None:
                        # first fresh util at the new world closes the
                        # trace (the scaler's downtime probe signal)
                        self._util_publisher.resize_trace = \
                            self._reform_span.context
                    self._reform_span = None
                self._migration.ack(
                    "adopted", downtime_s=round(gap, 4),
                    bytes_from_peers=self.bytes_from_peers
                    if reform_doc and reform_doc.get("restore") == "peers"
                    else 0,
                    reform=reform_doc)
            self.status.step += 1
            self.status.step_in_epoch = i + 1
            n = (batch_size_fn(batch) if batch_size_fn
                 else _default_batch_size(batch))
            window_samples += n
            self.status.samples_seen += n
            if cfg.ckpt_every_steps and \
                    self.status.step % cfg.ckpt_every_steps == 0:
                self._save()  # epoch = last complete; step_in_epoch = cursor
            if self.status.step % max(1, cfg.log_every_steps) == 0:
                metrics = jax.device_get(metrics)
                self.last_metrics = metrics
                elapsed = time.perf_counter() - window_start
                rate = window_samples / max(elapsed, 1e-9)
                log.info("epoch %d step %d: %s %.1f samples/s",
                         epoch, self.status.step, _fmt(metrics), rate)
                for hook in self.hooks:
                    hook(self, epoch, self.status.step, metrics)
                window_start = time.perf_counter()
                window_samples = 0


def _default_batch_size(batch) -> int:
    leaves = jax.tree.leaves(batch)
    return int(leaves[0].shape[0]) if leaves else 0


def _fmt(metrics: dict) -> str:
    parts = []
    for k, v in metrics.items():
        try:
            parts.append(f"{k}={float(v):.4f}")
        except (TypeError, ValueError):
            parts.append(f"{k}={v}")
    return " ".join(parts)
