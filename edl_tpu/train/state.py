"""Train state and resume status.

``TrainState`` is the functional training-step state (params/opt/batch_stats)
threaded through jitted step functions. ``TrainStatus`` is the host-side
resume cursor — the capability of the reference's ``TrainStatus`` carrying
``epoch_no`` for checkpoint resume (doc/fault_tolerance.md, used at
example/collective/resnet50/train_with_fleet.py:491 "for pass_id in
range(train_status.next(), num_epochs)").
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import Any, Callable

import jax
import optax
from flax import struct


@struct.dataclass
class TrainState:
    """Minimal functional train state (flax struct pytree).

    apply_fn/tx are static (not serialized); params/opt_state/batch_stats
    and step are the pytree leaves that checkpoints capture.
    """

    step: jax.Array | int
    params: Any
    opt_state: Any
    batch_stats: Any = None
    apply_fn: Callable = struct.field(pytree_node=False, default=None)
    tx: optax.GradientTransformation = struct.field(
        pytree_node=False, default=None)

    @classmethod
    def create(cls, *, apply_fn, params, tx, batch_stats=None, **kwargs):
        return cls(
            step=0,
            params=params,
            opt_state=tx.init(params),
            batch_stats=batch_stats,
            apply_fn=apply_fn,
            tx=tx,
            **kwargs,
        )

    def apply_gradients(self, *, grads, **kwargs):
        if hasattr(self.tx, "fused_apply"):
            # Fused bucket path (train/fused_opt.py): no "updates tree"
            # intermediate — params and moments are rewritten in one
            # kernel pass. Duck-typed so the plain jit step, the amp
            # step and the comm step all pick it up through this seam.
            new_params, new_opt_state = self.tx.fused_apply(
                grads, self.opt_state, self.params)
        else:
            updates, new_opt_state = self.tx.update(grads,
                                                    self.opt_state,
                                                    self.params)
            new_params = optax.apply_updates(self.params, updates)
        return self.replace(step=self.step + 1, params=new_params,
                            opt_state=new_opt_state, **kwargs)


@dataclass
class TrainStatus:
    """Host-side resume cursor persisted alongside each checkpoint."""

    epoch: int = -1          # last fully completed epoch (-1 = none)
    step: int = 0            # global optimizer steps completed
    step_in_epoch: int = 0   # steps into the partially-done epoch (0 = none)
    samples_seen: int = 0    # for data-order resume bookkeeping
    world_size: int = 1      # devices at save time (resharding hint)

    def next_epoch(self) -> int:
        return self.epoch + 1

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "TrainStatus":
        return cls(**{k: d[k] for k in
                      ("epoch", "step", "step_in_epoch", "samples_seen",
                       "world_size")
                      if k in d})
