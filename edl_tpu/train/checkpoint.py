"""Versioned atomic checkpoints with resume status.

Contract (capability of the reference's fleet save/load_check_point per
doc/fault_tolerance.md and train_with_fleet.py:422-434,562-570):

- rank 0 (JAX process 0) writes; all processes load;
- write to a temp dir then atomic ``os.rename`` to ``ckpt-{version}``;
- monotonically increasing integer versions; ``latest`` picks the max
  complete one (a crashed half-written temp dir is never visible);
- ``TrainStatus`` (epoch/step/world_size) saved in meta.json next to the
  state so an elastic restart knows where to resume and how the world was
  shaped at save time;
- keep the newest ``max_to_keep`` checkpoints.

Two state-payload formats behind one manager:

- replicated (default): a flax msgpack of the host-gathered pytree,
  written by rank 0 — right for data-parallel states, where every value
  is fully addressable and resharding is trivial re-placement;
- sharded (``sharded=True``): every process writes only its own array
  chunks + an index (train/sharded_checkpoint.py), and restore
  re-assembles each leaf onto the TARGET state's shardings — including a
  different mesh shape/device count — without ever materializing a full
  replica on host. ``restore`` auto-detects which format a version holds,
  so an elastic restart can move between formats.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
from typing import Any

import jax
from flax import serialization

from edl_tpu.train import sharded_checkpoint as sc
from edl_tpu.train.state import TrainStatus
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.train.checkpoint")

_CKPT_RE = re.compile(r"^ckpt-(\d+)$")
_INDEX_FILE_RE = re.compile(r"^index\.(\d+)\.json$")


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3,
                 process_index: int | None = None, sharded: bool = False,
                 remote: str | None = None):
        """`remote`: optional URI root (file://, gs://, hdfs:// — see
        utils/fs.py) mirroring the local dir. Rank 0 uploads each sealed
        version after save; restore on a pod whose local dir lacks the
        wanted version fetches it from the mirror first — the rank-0-
        writes / everyone-reads story on clusters without a shared FS
        (reference doc/fault_tolerance.md:30-45)."""
        self.directory = directory
        self.max_to_keep = max_to_keep
        self._process_index = process_index
        self.sharded = sharded
        self.remote = remote

    @property
    def process_index(self) -> int:
        if self._process_index is not None:
            return self._process_index
        return jax.process_index()

    # -- discovery ---------------------------------------------------------

    def versions(self) -> list[int]:
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            m = _CKPT_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_version(self) -> int | None:
        versions = self.versions()
        return versions[-1] if versions else None

    def _path(self, version: int) -> str:
        return os.path.join(self.directory, f"ckpt-{version}")

    # -- save --------------------------------------------------------------

    def save(self, state: Any, status: TrainStatus) -> int | None:
        """Save a new checkpoint; returns its version (None on non-writers).

        Replicated mode: rank 0 does everything. Sharded mode: every
        process writes its chunks into the same pending dir (all callers
        of the world must call save together), then rank 0 seals it with
        meta.json + atomic rename after a world barrier.
        """
        if self.sharded:
            return self._save_sharded(state, status)
        if self.process_index != 0:
            return None
        latest = self.latest_version()
        version = 0 if latest is None else latest + 1
        os.makedirs(self.directory, exist_ok=True)
        host_state = jax.device_get(state)
        tmp = tempfile.mkdtemp(prefix=".tmp-ckpt-", dir=self.directory)
        try:
            with open(os.path.join(tmp, "state.msgpack"), "wb") as f:
                f.write(serialization.to_bytes(host_state))
            meta = {"version": version, "status": status.to_dict()}
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump(meta, f)
            os.rename(tmp, self._path(version))
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        log.info("saved checkpoint %s (epoch=%d step=%d)",
                 self._path(version), status.epoch, status.step)
        self._mirror(version)
        self._gc()
        return version

    def _mirror(self, version: int) -> None:
        if self.remote is None:
            return
        from edl_tpu.utils import fs
        try:
            fs.mirror_checkpoint(self.directory, version, self.remote,
                                 keep=self.max_to_keep)
        except fs.EdlFsError as exc:
            # The local version is already sealed — a transient mirror
            # failure (GCS 5xx etc.) must not kill the trainer; the next
            # save's upload + LATEST flip supersedes this one.
            log.warning("mirror of ckpt-%d to %s failed: %s", version,
                        self.remote, exc)

    def _sync(self, tag: str) -> None:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(tag)

    def _save_sharded(self, state: Any, status: TrainStatus) -> int | None:
        # All processes agree on the version: the barrier orders this
        # listing after every process finished (and rank 0 sealed) any
        # previous save.
        self._sync("edl_ckpt_begin")
        latest = self.latest_version()
        version = 0 if latest is None else latest + 1
        os.makedirs(self.directory, exist_ok=True)
        tmp = os.path.join(self.directory, f".tmp-ckpt-{version}")
        # A crashed earlier save may have left stale chunks/indexes under
        # the same deterministic name (possibly from a different world
        # shape); sealing them in would corrupt the restore, so rank 0
        # clears the dir before anyone writes.
        if self.process_index == 0:
            shutil.rmtree(tmp, ignore_errors=True)
        self._sync("edl_ckpt_clean")
        # A process that fails mid-write must still reach the barrier
        # (otherwise the healthy ranks hang in it until the coordination
        # timeout); it drops a poison marker so every rank raises after.
        failure: BaseException | None = None
        my_files: list[str] = []
        try:
            my_files = sc.save_sharded(tmp, state)
        except BaseException as exc:  # noqa: BLE001 — re-raised below
            failure = exc
            try:
                os.makedirs(tmp, exist_ok=True)
                with open(os.path.join(
                        tmp, f"save_failed.{self.process_index}"), "w"):
                    pass
            except OSError:
                pass
        self._sync("edl_ckpt_chunks")
        poisoned = [n for n in (os.listdir(tmp) if os.path.isdir(tmp) else [])
                    if n.startswith("save_failed.")]
        if failure is not None or poisoned:
            if self.process_index == 0:
                shutil.rmtree(tmp, ignore_errors=True)
            if failure is not None:
                raise failure
            raise RuntimeError(
                f"sharded save aborted: {poisoned} failed")
        if self.remote is not None:
            self._mirror_sharded_upload(tmp, version, my_files)
        try:
            if self.process_index == 0:
                meta = {"version": version, "status": status.to_dict(),
                        "format": "sharded",
                        "world": {"process_count": jax.process_count(),
                                  "device_count": jax.device_count()}}
                with open(os.path.join(tmp, "meta.json"), "w") as f:
                    json.dump(meta, f)
                os.rename(tmp, self._path(version))
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        if self.process_index != 0:
            return None
        log.info("saved sharded checkpoint %s (epoch=%d step=%d)",
                 self._path(version), status.epoch, status.step)
        if self.remote is not None:
            self._mirror_sharded_finalize(version)
        self._gc()
        return version

    def _mirror_sharded_upload(self, tmp: str, version: int,
                               my_files: list[str]) -> None:
        """EVERY process uploads its own chunks + index from its pending
        dir (local dirs need not be shared across pods); rank 0 uploads
        meta.json + flips LATEST only in `_mirror_sharded_finalize`, so
        the marker is last world-wide."""
        from edl_tpu.utils import fs
        if self.process_index == 0:
            # A crashed earlier save at this version (possibly a
            # different world shape) may have left stale chunks/indexes
            # in the remote dir; merging them in would corrupt the
            # restore — same hazard the local tmp-clean guards against.
            try:
                fs.resolve(self.remote).delete(
                    fs.join_uri(self.remote, f"ckpt-{version}"))
            except Exception as exc:  # noqa: BLE001 — mirror-only
                log.warning("remote clean of ckpt-%d failed: %s",
                            version, exc)
        self._sync("edl_ckpt_mirror_clean")
        try:
            fs.mirror_checkpoint_files(tmp, version, self.remote, my_files)
        except Exception as exc:  # noqa: BLE001 — any transfer error
            # Swallow so this rank still reaches the barrier (a raw
            # OSError from LocalFS would strand the world in _sync). The
            # missing index.{rank}.json is what the finalize gate keys
            # on, so LATEST never flips to this incomplete version.
            log.warning("sharded mirror of ckpt-%d (rank %d) failed: %s",
                        version, self.process_index, exc)
        self._sync("edl_ckpt_mirror")

    def _mirror_sharded_finalize(self, version: int) -> None:
        """Rank 0 only. NOT `_mirror`: a whole-dir upload would replace
        the remote version dir, wiping the other ranks' uploads."""
        from edl_tpu.utils import fs
        try:
            # Completeness gate before the LATEST flip: the remote dir
            # must hold EXACTLY index.{0..world-1}.json. A rank's index
            # uploads last (save_sharded returns it last), so presence
            # implies its chunks made it; an UNEXPECTED extra index —
            # survivor of a failed remote clean, e.g. from a crashed
            # save at a different world shape — would merge stale chunks
            # into every restore, so it also blocks the flip. Skipping
            # the flip keeps LATEST on the previous complete version
            # (and skips its GC).
            have = set(fs.resolve(self.remote).listdir(
                fs.join_uri(self.remote, f"ckpt-{version}")))
            want = {f"index.{r}.json" for r in range(jax.process_count())}
            got = {n for n in have if _INDEX_FILE_RE.match(n)}
            if got != want:
                log.warning(
                    "mirror of ckpt-%d inconsistent (missing indexes %s, "
                    "stale extras %s) — LATEST not flipped", version,
                    sorted(want - got), sorted(got - want))
                return
            fs.mirror_checkpoint_files(self._path(version), version,
                                       self.remote, ["meta.json"])
            fs.finalize_mirror(self.remote, version, keep=self.max_to_keep)
            log.info("mirrored sharded ckpt-%d -> %s", version, self.remote)
        except Exception as exc:  # noqa: BLE001 — a mirror failure must
            log.warning("mirror of ckpt-%d to %s failed: %s", version,
                        self.remote, exc)  # not kill a sealed local save

    def _gc(self) -> None:
        versions = self.versions()
        for version in versions[: max(0, len(versions) - self.max_to_keep)]:
            shutil.rmtree(self._path(version), ignore_errors=True)
        # clean any orphaned temp dirs from crashed saves
        for name in os.listdir(self.directory):
            if name.startswith(".tmp-ckpt-"):
                path = os.path.join(self.directory, name)
                shutil.rmtree(path, ignore_errors=True)

    # -- load --------------------------------------------------------------

    def restore(self, target: Any, version: int | None = None
                ) -> tuple[Any, TrainStatus] | None:
        """Restore into the structure of ``target``; None if no checkpoint.

        Auto-detects the version's format. Sharded checkpoints re-place
        each leaf per ``target``'s shardings (so pass the new world's
        freshly built state — any mesh shape); replicated checkpoints
        deserialize to host numpy in ``target``'s structure.
        """
        if version is None:
            version = self.latest_version()
            if self.remote is not None:
                # The mirror may be ahead of this pod's local dir (e.g. a
                # container restarted in place while rank 0 kept saving);
                # restoring the stale local latest would diverge from the
                # rest of the world, so prefer the remote LATEST marker
                # whenever it is newer.
                from edl_tpu.utils import fs
                try:
                    remote_latest = fs.remote_latest_version(self.remote)
                except fs.EdlFsError as exc:
                    log.warning("mirror %s unreachable for restore: %s",
                                self.remote, exc)
                    remote_latest = None
                if remote_latest is not None and (version is None
                                                  or remote_latest > version):
                    version = fs.fetch_latest_checkpoint(self.remote,
                                                         self.directory)
        if version is None:
            return None
        if (not os.path.isdir(self._path(version))
                and self.remote is not None):
            from edl_tpu.utils import fs
            fs.fetch_latest_checkpoint(self.remote, self.directory,
                                       version=version)
        path = self._path(version)
        if sc.is_sharded_dir(path):
            state = sc.restore_sharded(path, target)
        else:
            with open(os.path.join(path, "state.msgpack"), "rb") as f:
                state = serialization.from_bytes(target, f.read())
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        status = TrainStatus.from_dict(meta["status"])
        log.info("restored checkpoint %s (epoch=%d step=%d)", path,
                 status.epoch, status.step)
        return state, status
