"""Versioned atomic checkpoints with resume status.

Contract (capability of the reference's fleet save/load_check_point per
doc/fault_tolerance.md and train_with_fleet.py:422-434,562-570):

- rank 0 (JAX process 0) writes; all processes load;
- write to a temp dir then atomic ``os.rename`` to ``ckpt-{version}``;
- monotonically increasing integer versions; ``latest`` picks the max
  complete one (a crashed half-written temp dir is never visible);
- ``TrainStatus`` (epoch/step/world_size) saved in meta.json next to the
  state so an elastic restart knows where to resume and how the world was
  shaped at save time;
- keep the newest ``max_to_keep`` checkpoints.

Two state-payload formats behind one manager:

- replicated (default): a flax msgpack of the host-gathered pytree,
  written by rank 0 — right for data-parallel states, where every value
  is fully addressable and resharding is trivial re-placement;
- sharded (``sharded=True``): every process writes only its own array
  chunks + an index (train/sharded_checkpoint.py), and restore
  re-assembles each leaf onto the TARGET state's shardings — including a
  different mesh shape/device count — without ever materializing a full
  replica on host. ``restore`` auto-detects which format a version holds,
  so an elastic restart can move between formats.

Async snapshot-then-write (``save_async``, the CheckFreq/Check-N-Run
recipe): the step loop blocks only for a device->host snapshot into a
double-buffered staging arena; a single background writer thread then
does serialization, chunk writes, the tmp->final seal, mirror upload and
GC. The queue is bounded drop-to-latest — a NEW snapshot supersedes a
queued unwritten one but never an in-flight write — so checkpoint
frequency can rise without the writer ever falling unboundedly behind.
``wait()``/``close()`` are the epoch-end/shutdown barriers; a failed
background write surfaces as ``CheckpointWriteError`` on the NEXT
save/wait/close call. Sync and async saves produce bitwise-identical
checkpoint bytes.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
import threading
import time
from typing import Any

import jax
import numpy as np
from flax import serialization

from edl_tpu.obs import metrics as obs_metrics
from edl_tpu.obs import recorder as flight
from edl_tpu.train import sharded_checkpoint as sc
from edl_tpu.train.state import TrainStatus
from edl_tpu.utils.logging import get_logger
from edl_tpu.utils.timeline import timeline

log = get_logger("edl_tpu.train.checkpoint")


class CheckpointWriteError(RuntimeError):
    """A background checkpoint write failed. Raised on the save/wait/close
    call AFTER the failure (``save_async`` returns before its write runs,
    so the error surfaces at the next synchronization point)."""

_CKPT_RE = re.compile(r"^ckpt-(\d+)$")
_INDEX_FILE_RE = re.compile(r"^index\.(\d+)\.json$")


def _local_sharded_complete(path: str) -> bool:
    """Does this sealed sharded dir hold every rank's index of the world
    that SAVED it (meta.json's world.process_count)? False on a pod-local
    dir that only ever received its own rank's files."""
    try:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return False
    world = (meta.get("world") or {}).get("process_count")
    if not world:
        return True  # pre-world-record format: nothing to check against
    names = set(os.listdir(path))
    return all(f"index.{r}.json" in names for r in range(world))


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3,
                 process_index: int | None = None, sharded: bool = False,
                 remote: str | None = None):
        """`remote`: optional URI root (file://, gs://, hdfs:// — see
        utils/fs.py) mirroring the local dir. Rank 0 uploads each sealed
        version after save; restore on a pod whose local dir lacks the
        wanted version fetches it from the mirror first — the rank-0-
        writes / everyone-reads story on clusters without a shared FS
        (reference doc/fault_tolerance.md:30-45)."""
        self.directory = directory
        self.max_to_keep = max_to_keep
        self._process_index = process_index
        self.sharded = sharded
        self.remote = remote
        # replicated save folds the remote LATEST into its version choice
        # once per manager lifetime (single mirror writer — see save())
        self._remote_folded = False
        # wall seconds of the last restore() (elastic downtime accounting)
        self.last_restore_s: float | None = None
        # -- async snapshot-then-write plane (save_async) ------------------
        # The training thread and the background writer share everything
        # below under _cond (guarded-by annotations checked by edl-lint).
        self._cond = threading.Condition()
        # drop-to-latest slot (size 1)
        self._pending: dict | None = None   # guarded-by: _cond
        self._inflight = False              # guarded-by: _cond
        self._writer: threading.Thread | None = None  # guarded-by: _cond
        self._closed = False                # guarded-by: _cond
        self._write_error: BaseException | None = None  # guarded-by: _cond
        # double-buffered host staging: retired snapshot arenas recycled
        # by np.copyto instead of reallocating the full state per save
        self._staging_free: list[list] = []   # guarded-by: _cond
        self._staging_key: tuple | None = None  # guarded-by: _cond
        self._async_fallback_logged = False   # training-thread-only
        # -- sealed-snapshot retention (state-migration donor plane) -------
        # When retain_sealed is set (collective/migration.py), the newest
        # successfully sealed save's HOST-side payload is kept in memory
        # so surviving pods can serve it to peers during a resize without
        # re-reading disk. Retained payloads are never recycled back into
        # the staging pool — a fetch in flight may still be reading the
        # previous snapshot when a newer one seals, and np.copyto-ing
        # over it would serve torn bytes; the old payload is simply
        # dropped and freed by GC once the last reader releases it.
        self.retain_sealed = False
        self._sealed: dict | None = None    # guarded-by: _cond
        # called (no args, outside the lock) after each retention update;
        # the migration service republishes its advert from here
        self.on_sealed = None
        self._tl = timeline("ckpt")
        self._stats = {  # guarded-by: _cond
            "saves_async": 0, "saves_sync": 0, "superseded": 0,
            "writes": 0, "errors": 0, "state_bytes_last": 0,
            "snapshot_ms_last": 0.0, "save_stall_ms_total": 0.0,
            "write_s_last": 0.0, "write_s_total": 0.0}
        # the stats() dict stays the benchlog API; the per-process obs
        # registry serves the same counters as gauges (close() drops it)
        self._obs = obs_metrics.register_stats("ckpt", self.stats)

    @property
    def process_index(self) -> int:
        if self._process_index is not None:
            return self._process_index
        return jax.process_index()

    # -- discovery ---------------------------------------------------------

    def versions(self) -> list[int]:
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            m = _CKPT_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_version(self) -> int | None:
        versions = self.versions()
        return versions[-1] if versions else None

    def _path(self, version: int) -> str:
        return os.path.join(self.directory, f"ckpt-{version}")

    # -- save --------------------------------------------------------------

    def save(self, state: Any, status: TrainStatus) -> int | None:
        """Save a new checkpoint synchronously; returns its version (None
        on non-writers). The step loop pays the full serialize+write here
        — ``save_async`` is the cheap-per-step path.

        Replicated mode: rank 0 does everything. Sharded mode: every
        process writes its chunks into the same pending dir (all callers
        of the world must call save together), then rank 0 seals it with
        meta.json + atomic rename after a world barrier.
        """
        # An async writer may still be writing an older snapshot; two
        # concurrent writers would race the version choice — drain first
        # (also surfaces a prior background failure on this save call).
        self.wait()
        t0 = time.perf_counter()
        try:
            if self.sharded:
                return self._save_sharded(state, status)
            if self.process_index != 0:
                # Non-writers still accumulate sealed ckpt-N dirs locally
                # via restore-time mirror fetches — prune them
                # (sealed-only: no pending dirs exist in replicated mode,
                # but keep symmetry with the sharded branch).
                self._gc(sealed_only=True)
                return None
            host_state = jax.device_get(state)
            version = self._write_replicated(host_state, status)
            self._retain("replicated", host_state, version, status)
            return version
        finally:
            with self._cond:
                self._stats["saves_sync"] += 1
                self._stats["save_stall_ms_total"] += (
                    time.perf_counter() - t0) * 1e3

    def _write_replicated(self, host_state: Any, status: TrainStatus) -> int:
        """Serialize + write + seal a host-side state pytree (rank 0's
        replicated format). Runs on the caller's thread for `save` and on
        the background writer for `save_async` — identical bytes."""
        latest = self.latest_version()
        mirror_this = self.remote is not None
        folded_now = False
        if self.remote is not None and not self._remote_folded:
            latest, folded_now = self._fold_remote_latest(latest)
            mirror_this = folded_now
        version = 0 if latest is None else latest + 1
        os.makedirs(self.directory, exist_ok=True)
        tmp = tempfile.mkdtemp(prefix=".tmp-ckpt-", dir=self.directory)
        try:
            payload = serialization.to_bytes(host_state)
            # serialized size AS STORED — quantized resident moments
            # (train/fused_opt.py int8 planes) msgpack their codes, so
            # the ~2x opt-state cut is visible here and in bench's
            # checkpoint row, not only in HBM
            with self._cond:
                self._stats["state_bytes_last"] = len(payload)
            with open(os.path.join(tmp, "state.msgpack"), "wb") as f:
                f.write(payload)
            meta = {"version": version, "status": status.to_dict()}
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump(meta, f)
            os.rename(tmp, self._path(version))
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        log.info("saved checkpoint %s (epoch=%d step=%d)",
                 self._path(version), status.epoch, status.step)
        if folded_now:
            # Single mirror writer: once a fold reaches a SEALED local
            # version, local latest >= remote latest by construction —
            # skip the remote round-trip on subsequent saves. Only now:
            # marking before the seal would let a failed write + retry
            # skip the fold and renumber over a published checkpoint.
            self._remote_folded = True
        if mirror_this:
            self._mirror(version)
        self._gc()
        return version

    def _fold_remote_latest(self, latest: int | None
                            ) -> tuple[int | None, bool]:
        """Fold the mirror's LATEST into the version choice — a
        cold-restarted rank 0 whose local dir is empty would otherwise
        recompute a PUBLISHED version number, and mirroring it would
        overwrite the published checkpoint / flip LATEST backwards.
        Returns (folded latest, read_ok); on read_ok=False the caller
        must skip this save's mirror (the next successful read resumes
        numbering above the remote's)."""
        from edl_tpu.utils import fs
        try:
            remote_latest = fs.remote_latest_version(self.remote)
        except Exception as exc:  # noqa: BLE001 — mirror-only
            log.warning("remote LATEST unreadable (%s) — skipping "
                        "this save's mirror", exc)
            return latest, False
        if remote_latest is not None:
            latest = remote_latest if latest is None else max(
                latest, remote_latest)
        return latest, True

    def _mirror(self, version: int) -> None:
        if self.remote is None:
            return
        from edl_tpu.utils import fs
        try:
            fs.mirror_checkpoint(self.directory, version, self.remote,
                                 keep=self.max_to_keep)
        except fs.EdlFsError as exc:
            # The local version is already sealed — a transient mirror
            # failure (GCS 5xx etc.) must not kill the trainer; the next
            # save's upload + LATEST flip supersedes this one.
            log.warning("mirror of ckpt-%d to %s failed: %s", version,
                        self.remote, exc)

    def _sync(self, tag: str) -> None:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(tag)

    def _broadcast_int(self, value: int) -> int:
        """Rank 0's value, world-wide (identity in a 1-process world)."""
        if jax.process_count() > 1:
            import numpy as np
            from jax.experimental import multihost_utils
            return int(multihost_utils.broadcast_one_to_all(
                np.int32(value)))
        return value

    def _save_sharded(self, state: Any, status: TrainStatus,
                      snap: dict | None = None) -> int | None:
        # `snap`: a pre-taken host snapshot (sharded_checkpoint.
        # snapshot_shards) written in place of `state` — the async
        # writer's path, single-process worlds only (the barriers below
        # must run on the thread that owns the collective context).
        # All processes must agree on the version. A per-process
        # latest_version() listing diverges when local dirs are NOT
        # shared (only rank 0 ever seals locally, so other pods would
        # recompute version 0 forever and overwrite the published remote
        # ckpt-0 with later-step chunks) — so rank 0 decides, folding in
        # the remote mirror's LATEST (its own local dir may be cold
        # after an in-place restart), and broadcasts.
        self._sync("edl_ckpt_begin")
        latest = self.latest_version()
        remote_read_ok = True
        if self.remote is not None and self.process_index == 0:
            latest, remote_read_ok = self._fold_remote_latest(latest)
        version = self._broadcast_int(0 if latest is None else latest + 1)
        os.makedirs(self.directory, exist_ok=True)
        tmp = os.path.join(self.directory, f".tmp-ckpt-{version}")
        # A crashed earlier save may have left stale chunks/indexes under
        # the same deterministic name (possibly from a different world
        # shape); sealing them in would corrupt the restore, so rank 0
        # clears the dir before anyone writes.
        if self.process_index == 0:
            shutil.rmtree(tmp, ignore_errors=True)
        # Every rank clears its OWN stale pending dirs from earlier
        # versions: on non-shared dirs only rank 0 ever renames or runs
        # _gc, so without this each save would leak a full shard copy
        # per pod (at most the CURRENT pending dir remains between
        # saves). Safe on shared dirs too — anything below the agreed
        # version is an orphan by the begin barrier.
        for n in os.listdir(self.directory):
            if (n.startswith(".tmp-ckpt-")
                    and n != os.path.basename(tmp)):
                shutil.rmtree(os.path.join(self.directory, n),
                              ignore_errors=True)
        self._sync("edl_ckpt_clean")
        # A process that fails mid-write must still reach the barrier
        # (otherwise the healthy ranks hang in it until the coordination
        # timeout); it drops a poison marker so every rank raises after.
        failure: BaseException | None = None
        my_files: list[str] = []
        owns_snap = snap is None
        try:
            if owns_snap:
                snap = sc.snapshot_shards(state)
            my_files = sc.write_snapshot(tmp, snap)
        except BaseException as exc:  # noqa: BLE001 — re-raised below
            failure = exc
            try:
                os.makedirs(tmp, exist_ok=True)
                with open(os.path.join(
                        tmp, f"save_failed.{self.process_index}"), "w"):
                    pass
            except OSError:
                pass
        self._sync("edl_ckpt_chunks")
        poisoned = [n for n in (os.listdir(tmp) if os.path.isdir(tmp) else [])
                    if n.startswith("save_failed.")]
        ok = failure is None and not poisoned
        if self.remote is not None:
            # The mirror block runs its barriers on EVERY rank — healthy
            # or not — before any raise below: on non-shared dirs a
            # healthy rank cannot see a failed rank's poison marker, so
            # raising first would strand the healthy world in the mirror
            # barriers until the coordination timeout. A rank that
            # failed (or saw poison) participates without uploading.
            mirror_ok = self._mirror_sharded_upload(
                tmp, version, my_files, ok=ok and remote_read_ok)
        else:
            mirror_ok = False
        if not ok:
            if self.process_index == 0:
                shutil.rmtree(tmp, ignore_errors=True)
            if failure is not None:
                raise failure
            raise RuntimeError(
                f"sharded save aborted: {poisoned} failed")
        try:
            if self.process_index == 0:
                meta = {"version": version, "status": status.to_dict(),
                        "format": "sharded",
                        "world": {"process_count": jax.process_count(),
                                  "device_count": jax.device_count()}}
                with open(os.path.join(tmp, "meta.json"), "w") as f:
                    json.dump(meta, f)
                os.rename(tmp, self._path(version))
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        if owns_snap and self.retain_sealed:
            # Sync-path retention: snapshot_shards arrays MAY alias live
            # device buffers (its documented contract), and a donated
            # train step after this save would overwrite them under an
            # in-flight peer fetch — copy before retaining. The async
            # path retains its already-staged arena in the writer loop
            # instead (no copy needed there).
            kept = dict(snap, chunks=[(n, np.array(a))
                                      for n, a in snap["chunks"]])
            self._retain("sharded", kept, version, status)
        if self.process_index != 0:
            # Non-zero pods never seal versions locally, but restore-time
            # mirror fetches accumulate sealed ckpt-N dirs in their
            # (non-shared) local dirs — prune those here; rank 0's full
            # _gc below covers the shared/rank-0 case. Sealed-only: this
            # rank's pending .tmp-ckpt dir must survive until rank 0
            # renames it (shared dir) or the next save's clean sweeps it.
            self._gc(sealed_only=True)
            return None
        log.info("saved sharded checkpoint %s (epoch=%d step=%d)",
                 self._path(version), status.epoch, status.step)
        if self.remote is not None and mirror_ok:
            # mirror_ok=False means nobody uploaded (remote clean or
            # LATEST read failed) — finalizing would gate against STALE
            # files from a crashed earlier attempt at this version,
            # which (same world shape) could pass the exact-set check
            # and flip LATEST to old-step data.
            self._mirror_sharded_finalize(version)
        self._gc()
        return version

    def _mirror_sharded_upload(self, tmp: str, version: int,
                               my_files: list[str], *, ok: bool) -> bool:
        """EVERY process uploads its own chunks + index from its pending
        dir (local dirs need not be shared across pods); rank 0 uploads
        meta.json + flips LATEST only in `_mirror_sharded_finalize`, so
        the marker is last world-wide. `ok=False` ranks (their own write
        failed, they saw a poison marker, or rank 0 could not read the
        remote LATEST) run the barriers without uploading. Returns
        whether the world proceeded with uploads (rank 0's clean
        succeeded) — the caller gates `_mirror_sharded_finalize` on it,
        since finalizing after a failed clean would gate against STALE
        files from a crashed earlier attempt at this version."""
        from edl_tpu.utils import fs
        clean_ok = 1 if ok else 0  # rank 0's value wins via broadcast
        if self.process_index == 0 and ok:
            # A crashed earlier save at this version (possibly a
            # different world shape) may have left stale chunks/indexes
            # in the remote dir; merging them in would corrupt the
            # restore — same hazard the local tmp-clean guards against.
            # If the clean FAILS, a stale index.{r}.json could survive a
            # rank's failed re-upload and defeat the finalize gate's
            # exact-set check (old-attempt chunks merged into restores),
            # so the whole world skips this version's mirror instead.
            try:
                fs.resolve(self.remote).delete(
                    fs.join_uri(self.remote, f"ckpt-{version}"))
            except Exception as exc:  # noqa: BLE001 — mirror-only
                log.warning("remote clean of ckpt-%d failed — skipping "
                            "this version's mirror: %s", version, exc)
                clean_ok = 0
        clean_ok = self._broadcast_int(clean_ok)
        if ok and clean_ok:
            try:
                fs.mirror_checkpoint_files(tmp, version, self.remote,
                                           my_files)
            except Exception as exc:  # noqa: BLE001 — any transfer error
                # Swallow so this rank still reaches the barrier (a raw
                # OSError from LocalFS would strand the world in _sync).
                # The missing index.{rank}.json is what the finalize
                # gate keys on, so LATEST never flips to this
                # incomplete version.
                log.warning(
                    "sharded mirror of ckpt-%d (rank %d) failed: %s",
                    version, self.process_index, exc)
        self._sync("edl_ckpt_mirror")
        return bool(clean_ok)

    def _mirror_sharded_finalize(self, version: int) -> None:
        """Rank 0 only. NOT `_mirror`: a whole-dir upload would replace
        the remote version dir, wiping the other ranks' uploads."""
        from edl_tpu.utils import fs
        try:
            # Completeness gate before the LATEST flip: the remote dir
            # must hold EXACTLY index.{0..world-1}.json. A rank's index
            # uploads last (save_sharded returns it last), so presence
            # implies its chunks made it; an UNEXPECTED extra index —
            # survivor of a failed remote clean, e.g. from a crashed
            # save at a different world shape — would merge stale chunks
            # into every restore, so it also blocks the flip. Skipping
            # the flip keeps LATEST on the previous complete version
            # (and skips its GC).
            have = set(fs.resolve(self.remote).listdir(
                fs.join_uri(self.remote, f"ckpt-{version}")))
            want = {f"index.{r}.json" for r in range(jax.process_count())}
            got = {n for n in have if _INDEX_FILE_RE.match(n)}
            if got != want:
                log.warning(
                    "mirror of ckpt-%d inconsistent (missing indexes %s, "
                    "stale extras %s) — LATEST not flipped", version,
                    sorted(want - got), sorted(got - want))
                return
            fs.mirror_checkpoint_files(self._path(version), version,
                                       self.remote, ["meta.json"])
            fs.finalize_mirror(self.remote, version, keep=self.max_to_keep)
            log.info("mirrored sharded ckpt-%d -> %s", version, self.remote)
        except Exception as exc:  # noqa: BLE001 — a mirror failure must
            log.warning("mirror of ckpt-%d to %s failed: %s", version,
                        self.remote, exc)  # not kill a sealed local save

    def _gc(self, *, sealed_only: bool = False) -> None:
        versions = self.versions()
        for version in versions[: max(0, len(versions) - self.max_to_keep)]:
            shutil.rmtree(self._path(version), ignore_errors=True)
        if sealed_only:
            return
        # clean any orphaned temp dirs from crashed saves
        for name in os.listdir(self.directory):
            if name.startswith(".tmp-ckpt-"):
                path = os.path.join(self.directory, name)
                shutil.rmtree(path, ignore_errors=True)

    def gc_stale_tmp(self) -> None:
        """Startup GC: remove torn ``.tmp-*`` dirs — partial saves from a
        crashed/killed writer (chunks written, never sealed) and orphaned
        refetch staging. The save-time ``_gc`` only runs on ranks that
        write and only after a successful save, so a run that dies before
        its first save leaks them forever. Call at (re)start — e.g.
        ``TrainLoop.try_restore`` — when no save of the current
        generation can be pending; NOT from passive readers (a teacher
        polling a shared dir must not sweep the trainer's in-progress
        pending dir)."""
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return
        for name in names:
            if name.startswith(".tmp-"):
                path = os.path.join(self.directory, name)
                log.info("startup GC: removing stale partial save %s", path)
                shutil.rmtree(path, ignore_errors=True)

    # -- sealed-snapshot retention (state-migration donors) ----------------

    def _retain(self, kind: str, payload: Any, version: int | None,
                status: TrainStatus) -> None:
        """Keep the just-sealed save's host payload for peer serving.
        No-op unless `retain_sealed`. Never recycles the PREVIOUS
        retained payload into the staging pool (see __init__ note —
        torn-serve hazard); it is dropped for GC instead."""
        cb = None
        with self._cond:
            if not self.retain_sealed:
                return
            self._sealed = {"kind": kind, "payload": payload,
                            "version": version,
                            # isolate from the loop's live status cursor
                            "status": TrainStatus.from_dict(
                                status.to_dict())}
            cb = self.on_sealed
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — serving is best-effort;
                log.exception("on_sealed hook failed")  # never fail a save

    def sealed_snapshot(self) -> dict | None:
        """Newest sealed save as a serve-ready view — ``{version,
        status, process_index, leaves, chunks}`` where ``leaves`` is the
        self-describing chunk table (sharded_checkpoint format) and
        ``chunks`` maps chunk file names to host arrays. This is the
        donor manifest+payload the migration server answers peers with.
        None until a save seals with ``retain_sealed`` set."""
        with self._cond:
            rec = self._sealed
        if rec is None:
            return None
        if rec["kind"] == "sharded":
            snap = rec["payload"]
        else:
            snap = sc.snapshot_host_tree(rec["payload"])
        return {"version": rec["version"],
                "status": rec["status"].to_dict(),
                "process_index": snap.get("process_index", 0),
                "leaves": snap["leaves"],
                "chunks": dict(snap["chunks"])}

    # -- async snapshot-then-write -----------------------------------------

    def save_async(self, state: Any, status: TrainStatus) -> None:
        """Queue a checkpoint: the caller blocks only for the
        device->host snapshot copy; serialization, disk writes, the
        tmp->final seal, mirror upload and GC all happen on the
        background writer thread. Raises ``CheckpointWriteError`` here
        if a PREVIOUS background write failed.

        Drop-to-latest: if an earlier snapshot is still queued (writer
        busy), it is superseded by this one — the in-flight write is
        never aborted, so the newest sealed version only moves forward.
        Multi-process sharded worlds fall back to the synchronous path
        (its world barriers must run on the training thread).
        """
        self._raise_pending_error()
        if self.sharded and jax.process_count() > 1:
            if not self._async_fallback_logged:
                log.info("save_async: multi-process sharded world — "
                         "falling back to synchronous saves")
                self._async_fallback_logged = True
            self.save(state, status)
            return
        if not self.sharded and self.process_index != 0:
            self._gc(sealed_only=True)
            return
        t0 = time.perf_counter()
        with self._tl.span("snapshot"):
            # Supersede BEFORE staging so the dropped snapshot's arena is
            # recycled into this copy (true double buffering: at most one
            # in-flight + one pending arena live).
            with self._cond:
                if self._pending is not None:
                    old = self._pending
                    self._pending = None
                    self._stats["superseded"] += 1
                    self._recycle_arena(old)
            status = TrainStatus.from_dict(status.to_dict())  # isolate the
            # snapshot from the loop's live, mutating status cursor
            if self.sharded:
                snap = sc.snapshot_shards(state)
                names = [n for n, _ in snap["chunks"]]
                staged, arena = self._stage([a for _, a in snap["chunks"]])
                snap["chunks"] = list(zip(names, staged))
                job = {"kind": "sharded", "snap": snap}
            else:
                leaves, treedef = jax.tree_util.tree_flatten(state)
                staged, arena = self._stage(jax.device_get(leaves))
                job = {"kind": "replicated",
                       "tree": jax.tree_util.tree_unflatten(treedef, staged)}
            job.update(status=status, arena=arena,
                       arena_key=self._staging_key)
        stall_ms = (time.perf_counter() - t0) * 1e3
        with self._cond:
            if self._closed:
                raise RuntimeError("CheckpointManager is closed")
            self._stats["saves_async"] += 1
            self._stats["snapshot_ms_last"] = stall_ms
            self._stats["save_stall_ms_total"] += stall_ms
            self._pending = job
            if self._writer is None:
                self._writer = threading.Thread(
                    target=self._writer_loop, name="edl-ckpt-writer",
                    daemon=True)
                self._writer.start()
            self._cond.notify_all()

    def _stage(self, arrays: list) -> tuple[list, list]:
        """Copy fetched host arrays into a recycled snapshot arena.
        Copying is mandatory even though `jax.device_get` already ran:
        on the CPU backend the fetched array can be a zero-copy VIEW of
        the live device buffer, which a donating train step overwrites
        before the background write runs. Returns (staged, arena)."""
        key = tuple((tuple(getattr(a, "shape", ())),
                     str(getattr(a, "dtype", type(a).__name__)))
                    for a in arrays)
        with self._cond:
            if key != self._staging_key:
                # state structure changed (resize/reshard) — old arenas
                # no longer fit
                self._staging_free.clear()
                self._staging_key = key
            arena = self._staging_free.pop() if self._staging_free else None
        staged, new_arena = [], []
        for i, a in enumerate(arrays):
            if isinstance(a, np.ndarray):
                dst = arena[i] if arena is not None else np.empty_like(a)
                np.copyto(dst, a)
                staged.append(dst)
                new_arena.append(dst)
            else:  # python scalar leaf — immutable, no copy needed
                staged.append(a)
                new_arena.append(None)
        return staged, new_arena

    def _recycle_arena(self, job: dict) -> None:  # holds-lock: _cond
        if (job.get("arena") is not None
                and job.get("arena_key") == self._staging_key
                and len(self._staging_free) < 2):
            self._staging_free.append(job["arena"])

    def _writer_loop(self) -> None:
        while True:
            with self._cond:
                while self._pending is None and not self._closed:
                    self._cond.wait()
                if self._pending is None:
                    return  # closed and drained
                job = self._pending
                self._pending = None
                self._inflight = True
            try:
                t0 = time.perf_counter()
                with self._tl.span("write"):
                    if job["kind"] == "sharded":
                        ver = self._save_sharded(None, job["status"],
                                                 snap=job["snap"])
                        self._retain("sharded", job["snap"], ver,
                                     job["status"])
                    else:
                        ver = self._write_replicated(job["tree"],
                                                     job["status"])
                        self._retain("replicated", job["tree"], ver,
                                     job["status"])
                dt = time.perf_counter() - t0
                with self._cond:
                    self._stats["writes"] += 1
                    self._stats["write_s_last"] = dt
                    self._stats["write_s_total"] += dt
            except BaseException as exc:  # noqa: BLE001 — surfaced on the
                log.exception(            # next save/wait/close call
                    "async checkpoint write failed")
                with self._cond:
                    self._write_error = exc
                    self._stats["errors"] += 1
            finally:
                with self._cond:
                    payload = (job.get("snap") if job["kind"] == "sharded"
                               else job.get("tree"))
                    if self._sealed is None \
                            or self._sealed.get("payload") is not payload:
                        # not retained (or retention replaced it):
                        # arena returns to the staging pool as before
                        self._recycle_arena(job)
                    self._inflight = False
                    self._cond.notify_all()

    def wait(self, timeout: float | None = None) -> None:
        """Barrier: block until every queued snapshot is durably written
        (the epoch-end sync point). Re-raises a background write failure
        as ``CheckpointWriteError``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._pending is not None or self._inflight:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        "checkpoint writer did not drain in time")
                self._cond.wait(remaining)
        self._raise_pending_error()

    def close(self, raise_errors: bool = True) -> None:
        """Shutdown barrier: drain the queued snapshot (a valid snapshot
        is never thrown away — crash paths still seal their last state)
        and stop the writer thread. ``raise_errors=False`` is for
        crash-path ``finally`` blocks where raising would mask the
        original exception; failures are logged either way. The manager
        is reusable after close (a later ``save_async`` restarts the
        writer)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            writer = self._writer
        if writer is not None:
            writer.join()
        with self._cond:
            self._writer = None
            self._closed = False
        # drop the registry view (the manager stays usable for saves,
        # but a closed manager must not pin itself in the per-process
        # registry forever — tests build thousands of these)
        obs_metrics.unregister(self._obs)
        if raise_errors:
            self._raise_pending_error()

    def _raise_pending_error(self) -> None:
        with self._cond:
            exc, self._write_error = self._write_error, None
        if exc is not None:
            raise CheckpointWriteError(
                "background checkpoint write failed") from exc

    def stats(self) -> dict:
        """Save-stall / write accounting. ``save_stall_ms_total`` is the
        step-loop-visible time across BOTH paths: full save duration for
        sync saves, snapshot-copy duration for async ones."""
        with self._cond:
            s = dict(self._stats)
        saves = s["saves_async"] + s["saves_sync"]
        s["save_stall_ms_mean"] = (s["save_stall_ms_total"] / saves
                                   if saves else 0.0)
        if self.last_restore_s is not None:
            s["restore_s"] = self.last_restore_s
        return s

    # -- load --------------------------------------------------------------

    def restore_raw(self, version: int | None = None
                    ) -> tuple[dict, TrainStatus] | None:
        """Structure-FREE restore of a replicated checkpoint: the raw
        nested state dict (``{'params': ..., 'batch_stats': ..., ...}``)
        with no target pytree. For consumers that only want a sub-tree —
        a teacher server restoring params saved by a trainer whose
        optimizer state it neither has nor wants (serialization
        `from_bytes` would reject the opt_state structure mismatch)."""
        if version is None:
            version = self.latest_version()
            if self.remote is not None:
                # Same prefer-remote-when-newer rule as restore(): a
                # teacher pod restarted in place must not serve stale
                # local params while the trainer's mirror moved on.
                from edl_tpu.utils import fs
                try:
                    remote_latest = fs.remote_latest_version(self.remote)
                except fs.EdlFsError as exc:
                    log.warning("mirror %s unreachable for restore_raw: "
                                "%s", self.remote, exc)
                    remote_latest = None
                if remote_latest is not None and (
                        version is None or remote_latest > version):
                    version = fs.fetch_latest_checkpoint(self.remote,
                                                         self.directory)
        if version is None:
            return None
        if (not os.path.isdir(self._path(version))
                and self.remote is not None):
            from edl_tpu.utils import fs
            fs.fetch_latest_checkpoint(self.remote, self.directory,
                                       version=version)
        path = self._path(version)
        if sc.is_sharded_dir(path):
            raise ValueError(
                f"{path} is a sharded checkpoint; restore_raw serves the "
                "replicated msgpack format (pass a target to restore())")
        with open(os.path.join(path, "state.msgpack"), "rb") as f:
            raw = serialization.msgpack_restore(f.read())
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        return raw, TrainStatus.from_dict(meta["status"])

    def restore(self, target: Any, version: int | None = None
                ) -> tuple[Any, TrainStatus] | None:
        """Restore into the structure of ``target``; None if no checkpoint.

        Auto-detects the version's format. Sharded checkpoints re-place
        each leaf per ``target``'s shardings (so pass the new world's
        freshly built state — any mesh shape); replicated checkpoints
        deserialize to host numpy in ``target``'s structure. Sharded
        chunk regions are read through a per-file handle cache on a
        thread pool (``EDL_TPU_CKPT_RESTORE_THREADS``) — restore wall
        time is the elastic-downtime term this call owns.

        Integrity: a chunk failing its sealed crc32 raises the typed
        ``EdlCheckpointCorrupt``; with ``version=None`` the manager
        falls back to the next older sealed version (loudly) instead of
        loading garbage — only an explicit ``version`` surfaces the
        corruption to the caller.
        """
        from edl_tpu.utils.exceptions import EdlCheckpointCorrupt
        if version is not None:
            return self._restore_version(target, version)
        try:
            return self._restore_version(target, None)
        except EdlCheckpointCorrupt as exc:
            last_exc = exc
        # The auto-picked latest (mirror fetches land locally first, so
        # latest_version() names it) is corrupt: walk older sealed
        # versions, newest first, loudly.
        bad = self.latest_version()
        flight.record("corruption", plane="checkpoint", version=bad,
                      directory=self.directory, error=str(last_exc))
        log.error("checkpoint ckpt-%s corrupt (%s) — falling back to "
                  "the previous sealed version", bad, last_exc)
        older = [v for v in self.versions() if bad is None or v < bad]
        for v in reversed(older):
            try:
                return self._restore_version(target, v)
            except EdlCheckpointCorrupt as exc:
                last_exc = exc
                log.error("checkpoint ckpt-%d also corrupt (%s)", v, exc)
        raise EdlCheckpointCorrupt(
            "every local sealed checkpoint failed its integrity check "
            f"under {self.directory}") from last_exc

    def _restore_version(self, target: Any, version: int | None
                         ) -> tuple[Any, TrainStatus] | None:
        t_start = time.perf_counter()
        if version is None:
            version = self.latest_version()
            if self.remote is not None:
                # The mirror may be ahead of this pod's local dir (e.g. a
                # container restarted in place while rank 0 kept saving);
                # restoring the stale local latest would diverge from the
                # rest of the world, so prefer the remote LATEST marker
                # whenever it is newer.
                from edl_tpu.utils import fs
                try:
                    remote_latest = fs.remote_latest_version(self.remote)
                except fs.EdlFsError as exc:
                    log.warning("mirror %s unreachable for restore: %s",
                                self.remote, exc)
                    remote_latest = None
                if remote_latest is not None and (version is None
                                                  or remote_latest > version):
                    version = fs.fetch_latest_checkpoint(self.remote,
                                                         self.directory)
        if version is None:
            return None
        if (not os.path.isdir(self._path(version))
                and self.remote is not None):
            from edl_tpu.utils import fs
            fs.fetch_latest_checkpoint(self.remote, self.directory,
                                       version=version)
        path = self._path(version)
        if (self.remote is not None and os.path.isdir(path)
                and sc.is_sharded_dir(path)
                and not _local_sharded_complete(path)):
            # Non-shared dirs: a pod's locally sealed sharded version
            # holds only its OWN chunks + index (rank 0's, after an
            # in-place restart). Reassembling from it would miss every
            # region other ranks owned — refetch the complete mirrored
            # copy instead of trusting local presence. Verify the mirror
            # actually HAS a complete copy before deleting the local dir
            # (it is this pod's only copy of its own chunks).
            from edl_tpu.utils import fs
            # Fetch into a temp dir FIRST and only then swap: the local
            # dir is this pod's only copy of its own chunks, so it must
            # survive a fetch that fails mid-flight (remote GC race,
            # transient transport error).
            fetch_tmp = tempfile.mkdtemp(prefix=".tmp-refetch-",
                                         dir=self.directory)
            got = None
            try:
                got = fs.fetch_latest_checkpoint(self.remote, fetch_tmp,
                                                 version=version)
            except Exception as exc:  # noqa: BLE001 — mirror-only
                log.warning("mirror refetch of ckpt-%d failed: %s",
                            version, exc)
            if got is not None:
                log.info("local %s incomplete for its saved world — "
                         "replaced with the mirror's complete copy", path)
                shutil.rmtree(path, ignore_errors=True)
                os.rename(os.path.join(fetch_tmp, f"ckpt-{version}"), path)
            else:
                log.warning(
                    "local %s incomplete and mirror has no complete "
                    "copy — restoring from local (may fail coverage)",
                    path)
            shutil.rmtree(fetch_tmp, ignore_errors=True)
        if sc.is_sharded_dir(path):
            state = sc.restore_sharded(path, target)
        else:
            with open(os.path.join(path, "state.msgpack"), "rb") as f:
                state = serialization.from_bytes(target, f.read())
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        status = TrainStatus.from_dict(meta["status"])
        self.last_restore_s = time.perf_counter() - t_start
        log.info("restored checkpoint %s (epoch=%d step=%d) in %.3fs", path,
                 status.epoch, status.step, self.last_restore_s)
        return state, status
