"""Versioned atomic checkpoints with resume status.

Contract (capability of the reference's fleet save/load_check_point per
doc/fault_tolerance.md and train_with_fleet.py:422-434,562-570):

- rank 0 (JAX process 0) writes; all processes load;
- write to a temp dir then atomic ``os.rename`` to ``ckpt-{version}``;
- monotonically increasing integer versions; ``latest`` picks the max
  complete one (a crashed half-written temp dir is never visible);
- ``TrainStatus`` (epoch/step/world_size) saved in meta.json next to the
  state so an elastic restart knows where to resume and how the world was
  shaped at save time;
- keep the newest ``max_to_keep`` checkpoints.

Two state-payload formats behind one manager:

- replicated (default): a flax msgpack of the host-gathered pytree,
  written by rank 0 — right for data-parallel states, where every value
  is fully addressable and resharding is trivial re-placement;
- sharded (``sharded=True``): every process writes only its own array
  chunks + an index (train/sharded_checkpoint.py), and restore
  re-assembles each leaf onto the TARGET state's shardings — including a
  different mesh shape/device count — without ever materializing a full
  replica on host. ``restore`` auto-detects which format a version holds,
  so an elastic restart can move between formats.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
from typing import Any

import jax
from flax import serialization

from edl_tpu.train import sharded_checkpoint as sc
from edl_tpu.train.state import TrainStatus
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.train.checkpoint")

_CKPT_RE = re.compile(r"^ckpt-(\d+)$")
_INDEX_FILE_RE = re.compile(r"^index\.(\d+)\.json$")


def _local_sharded_complete(path: str) -> bool:
    """Does this sealed sharded dir hold every rank's index of the world
    that SAVED it (meta.json's world.process_count)? False on a pod-local
    dir that only ever received its own rank's files."""
    try:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return False
    world = (meta.get("world") or {}).get("process_count")
    if not world:
        return True  # pre-world-record format: nothing to check against
    names = set(os.listdir(path))
    return all(f"index.{r}.json" in names for r in range(world))


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3,
                 process_index: int | None = None, sharded: bool = False,
                 remote: str | None = None):
        """`remote`: optional URI root (file://, gs://, hdfs:// — see
        utils/fs.py) mirroring the local dir. Rank 0 uploads each sealed
        version after save; restore on a pod whose local dir lacks the
        wanted version fetches it from the mirror first — the rank-0-
        writes / everyone-reads story on clusters without a shared FS
        (reference doc/fault_tolerance.md:30-45)."""
        self.directory = directory
        self.max_to_keep = max_to_keep
        self._process_index = process_index
        self.sharded = sharded
        self.remote = remote
        # replicated save folds the remote LATEST into its version choice
        # once per manager lifetime (single mirror writer — see save())
        self._remote_folded = False

    @property
    def process_index(self) -> int:
        if self._process_index is not None:
            return self._process_index
        return jax.process_index()

    # -- discovery ---------------------------------------------------------

    def versions(self) -> list[int]:
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            m = _CKPT_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_version(self) -> int | None:
        versions = self.versions()
        return versions[-1] if versions else None

    def _path(self, version: int) -> str:
        return os.path.join(self.directory, f"ckpt-{version}")

    # -- save --------------------------------------------------------------

    def save(self, state: Any, status: TrainStatus) -> int | None:
        """Save a new checkpoint; returns its version (None on non-writers).

        Replicated mode: rank 0 does everything. Sharded mode: every
        process writes its chunks into the same pending dir (all callers
        of the world must call save together), then rank 0 seals it with
        meta.json + atomic rename after a world barrier.
        """
        if self.sharded:
            return self._save_sharded(state, status)
        if self.process_index != 0:
            # Non-writers still accumulate sealed ckpt-N dirs locally via
            # restore-time mirror fetches — prune them (sealed-only: no
            # pending dirs exist in replicated mode, but keep symmetry
            # with the sharded branch).
            self._gc(sealed_only=True)
            return None
        latest = self.latest_version()
        mirror_this = self.remote is not None
        folded_now = False
        if self.remote is not None and not self._remote_folded:
            latest, folded_now = self._fold_remote_latest(latest)
            mirror_this = folded_now
        version = 0 if latest is None else latest + 1
        os.makedirs(self.directory, exist_ok=True)
        host_state = jax.device_get(state)
        tmp = tempfile.mkdtemp(prefix=".tmp-ckpt-", dir=self.directory)
        try:
            with open(os.path.join(tmp, "state.msgpack"), "wb") as f:
                f.write(serialization.to_bytes(host_state))
            meta = {"version": version, "status": status.to_dict()}
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump(meta, f)
            os.rename(tmp, self._path(version))
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        log.info("saved checkpoint %s (epoch=%d step=%d)",
                 self._path(version), status.epoch, status.step)
        if folded_now:
            # Single mirror writer: once a fold reaches a SEALED local
            # version, local latest >= remote latest by construction —
            # skip the remote round-trip on subsequent saves. Only now:
            # marking before the seal would let a failed write + retry
            # skip the fold and renumber over a published checkpoint.
            self._remote_folded = True
        if mirror_this:
            self._mirror(version)
        self._gc()
        return version

    def _fold_remote_latest(self, latest: int | None
                            ) -> tuple[int | None, bool]:
        """Fold the mirror's LATEST into the version choice — a
        cold-restarted rank 0 whose local dir is empty would otherwise
        recompute a PUBLISHED version number, and mirroring it would
        overwrite the published checkpoint / flip LATEST backwards.
        Returns (folded latest, read_ok); on read_ok=False the caller
        must skip this save's mirror (the next successful read resumes
        numbering above the remote's)."""
        from edl_tpu.utils import fs
        try:
            remote_latest = fs.remote_latest_version(self.remote)
        except Exception as exc:  # noqa: BLE001 — mirror-only
            log.warning("remote LATEST unreadable (%s) — skipping "
                        "this save's mirror", exc)
            return latest, False
        if remote_latest is not None:
            latest = remote_latest if latest is None else max(
                latest, remote_latest)
        return latest, True

    def _mirror(self, version: int) -> None:
        if self.remote is None:
            return
        from edl_tpu.utils import fs
        try:
            fs.mirror_checkpoint(self.directory, version, self.remote,
                                 keep=self.max_to_keep)
        except fs.EdlFsError as exc:
            # The local version is already sealed — a transient mirror
            # failure (GCS 5xx etc.) must not kill the trainer; the next
            # save's upload + LATEST flip supersedes this one.
            log.warning("mirror of ckpt-%d to %s failed: %s", version,
                        self.remote, exc)

    def _sync(self, tag: str) -> None:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(tag)

    def _broadcast_int(self, value: int) -> int:
        """Rank 0's value, world-wide (identity in a 1-process world)."""
        if jax.process_count() > 1:
            import numpy as np
            from jax.experimental import multihost_utils
            return int(multihost_utils.broadcast_one_to_all(
                np.int32(value)))
        return value

    def _save_sharded(self, state: Any, status: TrainStatus) -> int | None:
        # All processes must agree on the version. A per-process
        # latest_version() listing diverges when local dirs are NOT
        # shared (only rank 0 ever seals locally, so other pods would
        # recompute version 0 forever and overwrite the published remote
        # ckpt-0 with later-step chunks) — so rank 0 decides, folding in
        # the remote mirror's LATEST (its own local dir may be cold
        # after an in-place restart), and broadcasts.
        self._sync("edl_ckpt_begin")
        latest = self.latest_version()
        remote_read_ok = True
        if self.remote is not None and self.process_index == 0:
            latest, remote_read_ok = self._fold_remote_latest(latest)
        version = self._broadcast_int(0 if latest is None else latest + 1)
        os.makedirs(self.directory, exist_ok=True)
        tmp = os.path.join(self.directory, f".tmp-ckpt-{version}")
        # A crashed earlier save may have left stale chunks/indexes under
        # the same deterministic name (possibly from a different world
        # shape); sealing them in would corrupt the restore, so rank 0
        # clears the dir before anyone writes.
        if self.process_index == 0:
            shutil.rmtree(tmp, ignore_errors=True)
        # Every rank clears its OWN stale pending dirs from earlier
        # versions: on non-shared dirs only rank 0 ever renames or runs
        # _gc, so without this each save would leak a full shard copy
        # per pod (at most the CURRENT pending dir remains between
        # saves). Safe on shared dirs too — anything below the agreed
        # version is an orphan by the begin barrier.
        for n in os.listdir(self.directory):
            if (n.startswith(".tmp-ckpt-")
                    and n != os.path.basename(tmp)):
                shutil.rmtree(os.path.join(self.directory, n),
                              ignore_errors=True)
        self._sync("edl_ckpt_clean")
        # A process that fails mid-write must still reach the barrier
        # (otherwise the healthy ranks hang in it until the coordination
        # timeout); it drops a poison marker so every rank raises after.
        failure: BaseException | None = None
        my_files: list[str] = []
        try:
            my_files = sc.save_sharded(tmp, state)
        except BaseException as exc:  # noqa: BLE001 — re-raised below
            failure = exc
            try:
                os.makedirs(tmp, exist_ok=True)
                with open(os.path.join(
                        tmp, f"save_failed.{self.process_index}"), "w"):
                    pass
            except OSError:
                pass
        self._sync("edl_ckpt_chunks")
        poisoned = [n for n in (os.listdir(tmp) if os.path.isdir(tmp) else [])
                    if n.startswith("save_failed.")]
        ok = failure is None and not poisoned
        if self.remote is not None:
            # The mirror block runs its barriers on EVERY rank — healthy
            # or not — before any raise below: on non-shared dirs a
            # healthy rank cannot see a failed rank's poison marker, so
            # raising first would strand the healthy world in the mirror
            # barriers until the coordination timeout. A rank that
            # failed (or saw poison) participates without uploading.
            mirror_ok = self._mirror_sharded_upload(
                tmp, version, my_files, ok=ok and remote_read_ok)
        else:
            mirror_ok = False
        if not ok:
            if self.process_index == 0:
                shutil.rmtree(tmp, ignore_errors=True)
            if failure is not None:
                raise failure
            raise RuntimeError(
                f"sharded save aborted: {poisoned} failed")
        try:
            if self.process_index == 0:
                meta = {"version": version, "status": status.to_dict(),
                        "format": "sharded",
                        "world": {"process_count": jax.process_count(),
                                  "device_count": jax.device_count()}}
                with open(os.path.join(tmp, "meta.json"), "w") as f:
                    json.dump(meta, f)
                os.rename(tmp, self._path(version))
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        if self.process_index != 0:
            # Non-zero pods never seal versions locally, but restore-time
            # mirror fetches accumulate sealed ckpt-N dirs in their
            # (non-shared) local dirs — prune those here; rank 0's full
            # _gc below covers the shared/rank-0 case. Sealed-only: this
            # rank's pending .tmp-ckpt dir must survive until rank 0
            # renames it (shared dir) or the next save's clean sweeps it.
            self._gc(sealed_only=True)
            return None
        log.info("saved sharded checkpoint %s (epoch=%d step=%d)",
                 self._path(version), status.epoch, status.step)
        if self.remote is not None and mirror_ok:
            # mirror_ok=False means nobody uploaded (remote clean or
            # LATEST read failed) — finalizing would gate against STALE
            # files from a crashed earlier attempt at this version,
            # which (same world shape) could pass the exact-set check
            # and flip LATEST to old-step data.
            self._mirror_sharded_finalize(version)
        self._gc()
        return version

    def _mirror_sharded_upload(self, tmp: str, version: int,
                               my_files: list[str], *, ok: bool) -> bool:
        """EVERY process uploads its own chunks + index from its pending
        dir (local dirs need not be shared across pods); rank 0 uploads
        meta.json + flips LATEST only in `_mirror_sharded_finalize`, so
        the marker is last world-wide. `ok=False` ranks (their own write
        failed, they saw a poison marker, or rank 0 could not read the
        remote LATEST) run the barriers without uploading. Returns
        whether the world proceeded with uploads (rank 0's clean
        succeeded) — the caller gates `_mirror_sharded_finalize` on it,
        since finalizing after a failed clean would gate against STALE
        files from a crashed earlier attempt at this version."""
        from edl_tpu.utils import fs
        clean_ok = 1 if ok else 0  # rank 0's value wins via broadcast
        if self.process_index == 0 and ok:
            # A crashed earlier save at this version (possibly a
            # different world shape) may have left stale chunks/indexes
            # in the remote dir; merging them in would corrupt the
            # restore — same hazard the local tmp-clean guards against.
            # If the clean FAILS, a stale index.{r}.json could survive a
            # rank's failed re-upload and defeat the finalize gate's
            # exact-set check (old-attempt chunks merged into restores),
            # so the whole world skips this version's mirror instead.
            try:
                fs.resolve(self.remote).delete(
                    fs.join_uri(self.remote, f"ckpt-{version}"))
            except Exception as exc:  # noqa: BLE001 — mirror-only
                log.warning("remote clean of ckpt-%d failed — skipping "
                            "this version's mirror: %s", version, exc)
                clean_ok = 0
        clean_ok = self._broadcast_int(clean_ok)
        if ok and clean_ok:
            try:
                fs.mirror_checkpoint_files(tmp, version, self.remote,
                                           my_files)
            except Exception as exc:  # noqa: BLE001 — any transfer error
                # Swallow so this rank still reaches the barrier (a raw
                # OSError from LocalFS would strand the world in _sync).
                # The missing index.{rank}.json is what the finalize
                # gate keys on, so LATEST never flips to this
                # incomplete version.
                log.warning(
                    "sharded mirror of ckpt-%d (rank %d) failed: %s",
                    version, self.process_index, exc)
        self._sync("edl_ckpt_mirror")
        return bool(clean_ok)

    def _mirror_sharded_finalize(self, version: int) -> None:
        """Rank 0 only. NOT `_mirror`: a whole-dir upload would replace
        the remote version dir, wiping the other ranks' uploads."""
        from edl_tpu.utils import fs
        try:
            # Completeness gate before the LATEST flip: the remote dir
            # must hold EXACTLY index.{0..world-1}.json. A rank's index
            # uploads last (save_sharded returns it last), so presence
            # implies its chunks made it; an UNEXPECTED extra index —
            # survivor of a failed remote clean, e.g. from a crashed
            # save at a different world shape — would merge stale chunks
            # into every restore, so it also blocks the flip. Skipping
            # the flip keeps LATEST on the previous complete version
            # (and skips its GC).
            have = set(fs.resolve(self.remote).listdir(
                fs.join_uri(self.remote, f"ckpt-{version}")))
            want = {f"index.{r}.json" for r in range(jax.process_count())}
            got = {n for n in have if _INDEX_FILE_RE.match(n)}
            if got != want:
                log.warning(
                    "mirror of ckpt-%d inconsistent (missing indexes %s, "
                    "stale extras %s) — LATEST not flipped", version,
                    sorted(want - got), sorted(got - want))
                return
            fs.mirror_checkpoint_files(self._path(version), version,
                                       self.remote, ["meta.json"])
            fs.finalize_mirror(self.remote, version, keep=self.max_to_keep)
            log.info("mirrored sharded ckpt-%d -> %s", version, self.remote)
        except Exception as exc:  # noqa: BLE001 — a mirror failure must
            log.warning("mirror of ckpt-%d to %s failed: %s", version,
                        self.remote, exc)  # not kill a sealed local save

    def _gc(self, *, sealed_only: bool = False) -> None:
        versions = self.versions()
        for version in versions[: max(0, len(versions) - self.max_to_keep)]:
            shutil.rmtree(self._path(version), ignore_errors=True)
        if sealed_only:
            return
        # clean any orphaned temp dirs from crashed saves
        for name in os.listdir(self.directory):
            if name.startswith(".tmp-ckpt-"):
                path = os.path.join(self.directory, name)
                shutil.rmtree(path, ignore_errors=True)

    # -- load --------------------------------------------------------------

    def restore_raw(self, version: int | None = None
                    ) -> tuple[dict, TrainStatus] | None:
        """Structure-FREE restore of a replicated checkpoint: the raw
        nested state dict (``{'params': ..., 'batch_stats': ..., ...}``)
        with no target pytree. For consumers that only want a sub-tree —
        a teacher server restoring params saved by a trainer whose
        optimizer state it neither has nor wants (serialization
        `from_bytes` would reject the opt_state structure mismatch)."""
        if version is None:
            version = self.latest_version()
            if self.remote is not None:
                # Same prefer-remote-when-newer rule as restore(): a
                # teacher pod restarted in place must not serve stale
                # local params while the trainer's mirror moved on.
                from edl_tpu.utils import fs
                try:
                    remote_latest = fs.remote_latest_version(self.remote)
                except fs.EdlFsError as exc:
                    log.warning("mirror %s unreachable for restore_raw: "
                                "%s", self.remote, exc)
                    remote_latest = None
                if remote_latest is not None and (
                        version is None or remote_latest > version):
                    version = fs.fetch_latest_checkpoint(self.remote,
                                                         self.directory)
        if version is None:
            return None
        if (not os.path.isdir(self._path(version))
                and self.remote is not None):
            from edl_tpu.utils import fs
            fs.fetch_latest_checkpoint(self.remote, self.directory,
                                       version=version)
        path = self._path(version)
        if sc.is_sharded_dir(path):
            raise ValueError(
                f"{path} is a sharded checkpoint; restore_raw serves the "
                "replicated msgpack format (pass a target to restore())")
        with open(os.path.join(path, "state.msgpack"), "rb") as f:
            raw = serialization.msgpack_restore(f.read())
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        return raw, TrainStatus.from_dict(meta["status"])

    def restore(self, target: Any, version: int | None = None
                ) -> tuple[Any, TrainStatus] | None:
        """Restore into the structure of ``target``; None if no checkpoint.

        Auto-detects the version's format. Sharded checkpoints re-place
        each leaf per ``target``'s shardings (so pass the new world's
        freshly built state — any mesh shape); replicated checkpoints
        deserialize to host numpy in ``target``'s structure.
        """
        if version is None:
            version = self.latest_version()
            if self.remote is not None:
                # The mirror may be ahead of this pod's local dir (e.g. a
                # container restarted in place while rank 0 kept saving);
                # restoring the stale local latest would diverge from the
                # rest of the world, so prefer the remote LATEST marker
                # whenever it is newer.
                from edl_tpu.utils import fs
                try:
                    remote_latest = fs.remote_latest_version(self.remote)
                except fs.EdlFsError as exc:
                    log.warning("mirror %s unreachable for restore: %s",
                                self.remote, exc)
                    remote_latest = None
                if remote_latest is not None and (version is None
                                                  or remote_latest > version):
                    version = fs.fetch_latest_checkpoint(self.remote,
                                                         self.directory)
        if version is None:
            return None
        if (not os.path.isdir(self._path(version))
                and self.remote is not None):
            from edl_tpu.utils import fs
            fs.fetch_latest_checkpoint(self.remote, self.directory,
                                       version=version)
        path = self._path(version)
        if (self.remote is not None and os.path.isdir(path)
                and sc.is_sharded_dir(path)
                and not _local_sharded_complete(path)):
            # Non-shared dirs: a pod's locally sealed sharded version
            # holds only its OWN chunks + index (rank 0's, after an
            # in-place restart). Reassembling from it would miss every
            # region other ranks owned — refetch the complete mirrored
            # copy instead of trusting local presence. Verify the mirror
            # actually HAS a complete copy before deleting the local dir
            # (it is this pod's only copy of its own chunks).
            from edl_tpu.utils import fs
            # Fetch into a temp dir FIRST and only then swap: the local
            # dir is this pod's only copy of its own chunks, so it must
            # survive a fetch that fails mid-flight (remote GC race,
            # transient transport error).
            fetch_tmp = tempfile.mkdtemp(prefix=".tmp-refetch-",
                                         dir=self.directory)
            got = None
            try:
                got = fs.fetch_latest_checkpoint(self.remote, fetch_tmp,
                                                 version=version)
            except Exception as exc:  # noqa: BLE001 — mirror-only
                log.warning("mirror refetch of ckpt-%d failed: %s",
                            version, exc)
            if got is not None:
                log.info("local %s incomplete for its saved world — "
                         "replaced with the mirror's complete copy", path)
                shutil.rmtree(path, ignore_errors=True)
                os.rename(os.path.join(fetch_tmp, f"ckpt-{version}"), path)
            else:
                log.warning(
                    "local %s incomplete and mirror has no complete "
                    "copy — restoring from local (may fail coverage)",
                    path)
            shutil.rmtree(fetch_tmp, ignore_errors=True)
        if sc.is_sharded_dir(path):
            state = sc.restore_sharded(path, target)
        else:
            with open(os.path.join(path, "state.msgpack"), "rb") as f:
                state = serialization.from_bytes(target, f.read())
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        status = TrainStatus.from_dict(meta["status"])
        log.info("restored checkpoint %s (epoch=%d step=%d)", path,
                 status.epoch, status.step)
        return state, status
