"""Versioned atomic checkpoints with resume status.

Contract (capability of the reference's fleet save/load_check_point per
doc/fault_tolerance.md and train_with_fleet.py:422-434,562-570):

- rank 0 (JAX process 0) writes; all processes load;
- write to a temp dir then atomic ``os.rename`` to ``ckpt-{version}``;
- monotonically increasing integer versions; ``latest`` picks the max
  complete one (a crashed half-written temp dir is never visible);
- ``TrainStatus`` (epoch/step/world_size) saved in meta.json next to the
  state so an elastic restart knows where to resume and how the world was
  shaped at save time;
- keep the newest ``max_to_keep`` checkpoints.

State payload is a flax-serialized msgpack of the TrainState pytree (fully
addressable values are gathered to host; on elastic resize the loaded host
arrays are simply re-placed onto the new mesh — data-parallel state is
replicated so resharding is trivial; sharded states re-place per the
sharding rules in parallel/sharding.py).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
from typing import Any

import jax
from flax import serialization

from edl_tpu.train.state import TrainStatus
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.train.checkpoint")

_CKPT_RE = re.compile(r"^ckpt-(\d+)$")


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3,
                 process_index: int | None = None):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self._process_index = process_index

    @property
    def process_index(self) -> int:
        if self._process_index is not None:
            return self._process_index
        return jax.process_index()

    # -- discovery ---------------------------------------------------------

    def versions(self) -> list[int]:
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            m = _CKPT_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_version(self) -> int | None:
        versions = self.versions()
        return versions[-1] if versions else None

    def _path(self, version: int) -> str:
        return os.path.join(self.directory, f"ckpt-{version}")

    # -- save --------------------------------------------------------------

    def save(self, state: Any, status: TrainStatus) -> int | None:
        """Save a new checkpoint; returns its version (None on non-rank-0)."""
        if self.process_index != 0:
            return None
        latest = self.latest_version()
        version = 0 if latest is None else latest + 1
        os.makedirs(self.directory, exist_ok=True)
        host_state = jax.device_get(state)
        tmp = tempfile.mkdtemp(prefix=".tmp-ckpt-", dir=self.directory)
        try:
            with open(os.path.join(tmp, "state.msgpack"), "wb") as f:
                f.write(serialization.to_bytes(host_state))
            meta = {"version": version, "status": status.to_dict()}
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump(meta, f)
            os.rename(tmp, self._path(version))
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        log.info("saved checkpoint %s (epoch=%d step=%d)",
                 self._path(version), status.epoch, status.step)
        self._gc()
        return version

    def _gc(self) -> None:
        versions = self.versions()
        for version in versions[: max(0, len(versions) - self.max_to_keep)]:
            shutil.rmtree(self._path(version), ignore_errors=True)
        # clean any orphaned temp dirs from crashed saves
        for name in os.listdir(self.directory):
            if name.startswith(".tmp-ckpt-"):
                path = os.path.join(self.directory, name)
                shutil.rmtree(path, ignore_errors=True)

    # -- load --------------------------------------------------------------

    def restore(self, target: Any, version: int | None = None
                ) -> tuple[Any, TrainStatus] | None:
        """Restore into the structure of ``target``; None if no checkpoint."""
        if version is None:
            version = self.latest_version()
        if version is None:
            return None
        path = self._path(version)
        with open(os.path.join(path, "state.msgpack"), "rb") as f:
            state = serialization.from_bytes(target, f.read())
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        status = TrainStatus.from_dict(meta["status"])
        log.info("restored checkpoint %s (epoch=%d step=%d)", path,
                 status.epoch, status.step)
        return state, status
