"""DCN-aware gradient path: bucketed, hierarchical, optionally
compressed dp reductions with comm/compute overlap.

The plain jitted step (train/step.py) leaves the gradient allreduce to
XLA's SPMD partitioner: one dense, unoverlapped reduction per parameter
tensor, which on a hybrid ICI×DCN world (parallel/mesh.make_hybrid_mesh)
ships every gradient byte across the slow cross-slice edge exactly as it
falls out of backward. This module is the manual-collective variant the
reference exposed only as opaque fleet flags (`DGCMomentum`,
`use_hierarchical_allreduce` — SURVEY §2.3, train_with_fleet.py:93-112):

- **Bucketing**: gradient leaves are packed, in deterministic tree
  order, into size-bounded flat buckets (one concat buffer per dtype
  group, `CommConfig.bucket_mb`). Each bucket's reduction is an
  INDEPENDENT collective op, so XLA's scheduler can launch bucket i's
  reduction while bucket i+1's producers are still computing — the
  comm/compute overlap the single fused-graph reduction can never have.
  (The reduction itself is elementwise, so bucketing is numerics-free:
  psum(concat(g)) == concat(psum(g)) bitwise.)

- **Hierarchical decomposition**: on a multi-slice topology each
  bucket's dp-reduction becomes dense ICI reduce-scatter within the
  slice -> the cross-slice DCN leg on 1/C of the bytes per chip -> ICI
  all-gather. Only the middle leg crosses DCN, and every chip in a
  slice carries a disjoint 1/C of it.

- **Compressed DCN leg** (`CommConfig.compress`): the cross-slice hop
  optionally ships top-k (values, int32 indices) pairs — the
  `dgc.sparse_psum` wire, here with a persistent error-feedback
  residual so dropped mass is re-contributed on later steps, never
  lost — or int8 values with one per-chip fp32 scale
  (`ops.pack.pack_int8`; Pallas on TPU). ICI legs stay dense and
  bitwise.

Everything sits behind a loss-parity gate (`loss_parity_gate`, the
`smoke` CLI, tests/test_comm_overlap.py): the bucketed-dense path must
be BITWISE-equal to the jit path on the dryrun worlds before the bench
reports its numbers, and compressed paths must hold a pinned loss
envelope on the CNN + transformer convergence smokes.

Scope: the manual path owns dp-only meshes (every other axis size 1 —
dp gradients are the cross-slice traffic ROADMAP 4 names); fsdp/tp
worlds keep the XLA-partitioned step. Power-of-two dp worlds keep the
bitwise guarantee exactly (1/W gradient scaling is then exact); other
world sizes hold it to float tolerance.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from edl_tpu.parallel import mesh as mesh_lib
from edl_tpu.parallel.compat import shard_map
from edl_tpu.utils.logging import get_logger

log = get_logger("edl_tpu.train.comm")

COMPRESS_MODES = ("off", "topk", "int8")


@dataclass(frozen=True)
class CommConfig:
    """Knobs of the manual gradient path.

    bucket_mb: target bucket payload in MiB (EDL_TPU_COMM_BUCKET_MB).
      A leaf larger than the target gets its own bucket.
    compress: DCN-leg wire format (EDL_TPU_DCN_COMPRESS) —
      'off' (dense), 'topk' (values+indices, error feedback), 'int8'
      (per-chip scale, error feedback).
    topk_frac: fraction of each chip's DCN shard shipped under 'topk'.
    min_compress_elems: shards smaller than this stay dense (index/scale
      overhead would exceed the payload).
    """

    bucket_mb: float = 4.0
    compress: str = "off"
    topk_frac: float = 0.01
    min_compress_elems: int = 1024

    def __post_init__(self):
        if self.compress not in COMPRESS_MODES:
            raise ValueError(
                f"compress must be one of {COMPRESS_MODES}, "
                f"got {self.compress!r}")
        if self.bucket_mb <= 0:
            raise ValueError(f"bucket_mb must be > 0, got {self.bucket_mb}")
        if not 0.0 < self.topk_frac <= 1.0:
            raise ValueError(
                f"topk_frac must be in (0, 1], got {self.topk_frac}")


# -- bucket planning (host-side, static) ------------------------------------


@dataclass(frozen=True)
class _Slot:
    """One gradient leaf's home inside a bucket buffer."""

    leaf: int            # index into the tree-flatten order
    offset: int          # start inside the bucket's flat buffer
    size: int
    shape: tuple


@dataclass(frozen=True)
class _Bucket:
    dtype: Any
    slots: tuple[_Slot, ...]
    size: int            # payload elements (sum of slot sizes)
    padded: int          # payload + pad, a multiple of align


@dataclass(frozen=True)
class BucketPlan:
    """Static partition of a gradient tree into reduction buckets.

    Deterministic in (tree structure, leaf shapes/dtypes, bucket_mb,
    align): the same params always produce the same wire layout — the
    seeded-exact contract tools/comm_bench.py and the parity tests
    rely on.
    """

    buckets: tuple[_Bucket, ...]
    treedef: Any
    n_leaves: int
    align: int

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    def padded_elems(self) -> int:
        return sum(b.padded for b in self.buckets)


def plan_buckets(params: Any, bucket_mb: float, align: int) -> BucketPlan:
    """Greedy, tree-order bucket partition of a param/grad pytree.

    Leaves are grouped by dtype (one flat buffer cannot mix dtypes
    without a cast that would break bitwise parity), then packed in
    flatten order into buckets of at most ``bucket_mb`` MiB payload —
    an oversized leaf gets a bucket of its own, never split. Each
    bucket is padded up to a multiple of ``align`` (the dp world size,
    so reduce-scatter shards stay integral for every slice factor).
    """
    leaves, treedef = jax.tree.flatten(params)
    budget = max(1, int(bucket_mb * (1 << 20)))
    by_dtype: dict[Any, list[tuple[int, Any]]] = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.asarray(leaf).dtype
                            if not hasattr(leaf, "dtype") else leaf.dtype,
                            []).append((i, leaf))
    buckets: list[_Bucket] = []
    for dtype in sorted(by_dtype, key=str):
        pending: list[_Slot] = []
        pend_bytes = 0
        itemsize = np.dtype(dtype).itemsize

        def flush():
            nonlocal pending, pend_bytes
            if not pending:
                return
            size = sum(s.size for s in pending)
            padded = -(-size // align) * align
            buckets.append(_Bucket(dtype=dtype, slots=tuple(pending),
                                   size=size, padded=padded))
            pending, pend_bytes = [], 0

        offset = 0
        for i, leaf in by_dtype[dtype]:
            size = int(np.prod(leaf.shape)) if leaf.shape else 1
            if pending and pend_bytes + size * itemsize > budget:
                flush()
                offset = 0
            pending.append(_Slot(leaf=i, offset=offset, size=size,
                                 shape=tuple(leaf.shape)))
            offset += size
            pend_bytes += size * itemsize
            if pend_bytes >= budget:
                flush()
                offset = 0
        flush()
    return BucketPlan(buckets=tuple(buckets), treedef=treedef,
                      n_leaves=len(leaves), align=align)


def pack_buckets(grads: Any, plan: BucketPlan) -> list[jnp.ndarray]:
    """Gradient tree -> list of flat padded bucket buffers."""
    leaves = jax.tree.leaves(grads)
    out = []
    for b in plan.buckets:
        parts = [leaves[s.leaf].reshape(-1) for s in b.slots]
        if b.padded > b.size:
            parts.append(jnp.zeros((b.padded - b.size,), b.dtype))
        out.append(jnp.concatenate(parts) if len(parts) > 1
                   else parts[0])
    return out


def unpack_buckets(buffers: list[jnp.ndarray], plan: BucketPlan) -> Any:
    """Inverse of :func:`pack_buckets` (padding discarded)."""
    leaves: list[Any] = [None] * plan.n_leaves
    for buf, b in zip(buffers, plan.buckets):
        for s in b.slots:
            leaves[s.leaf] = lax.slice(buf, (s.offset,),
                                       (s.offset + s.size,)
                                       ).reshape(s.shape)
    return jax.tree.unflatten(plan.treedef, leaves)


# -- wire accounting (static per plan) --------------------------------------


def dcn_bytes_per_step(plan: BucketPlan, config: CommConfig,
                       n_slices: int, chips_per_slice: int) -> int:
    """Bytes ONE chip contributes to the cross-slice leg per step.

    The canonical regression metric (payload actually crossing DCN;
    fabric-level duplication — ring passes, headers — is topology
    noise this deliberately excludes). Dense: the chip's reduce-scatter
    shard at native width. topk: k * (value + int32 index). int8: one
    byte per element + the fp32 scale. Single-slice worlds cross no
    DCN at all and report 0.
    """
    if n_slices <= 1:
        return 0
    total = 0
    for b in plan.buckets:
        total += _leg_bytes(b.padded // chips_per_slice,
                            np.dtype(b.dtype).itemsize, config)
    return total


def _leg_bytes(m: int, itemsize: int, config: CommConfig) -> int:
    """Cross-slice bytes one chip sends for an m-element shard."""
    if config.compress == "off" or m < config.min_compress_elems:
        return m * itemsize
    if config.compress == "topk":
        k = _topk_k(m, config.topk_frac)
        return k * (itemsize + 4)
    return m * 1 + 4  # int8 payload + fp32 scale


def _topk_k(m: int, frac: float) -> int:
    return max(1, int(round(m * frac)))


# -- the reduction (inside shard_map) ---------------------------------------


def _cross_dense(shard, axis, groups):
    return lax.psum(shard, axis, axis_index_groups=groups)


def _cross_topk(shard, resid, axis, groups, k):
    """Top-k values+indices over the DCN edge with error feedback.

    Every chip in the cross group contributes its k largest-|.| entries
    of (shard + residual); the gathered (S, k) pairs scatter-add into a
    dense result identical across the group. Unsent mass stays in the
    residual — re-contributed later, never lost (Lin et al.'s DGC
    invariant, applied to the hierarchical leg instead of the whole
    gradient)."""
    u = shard + resid
    _, idx = lax.top_k(jnp.abs(u), k)
    vals = u[idx]
    all_vals = lax.all_gather(vals, axis, axis_index_groups=groups)
    all_idx = lax.all_gather(idx, axis, axis_index_groups=groups)
    dense = jnp.zeros_like(u).at[all_idx.reshape(-1)].add(
        all_vals.reshape(-1))
    sent = jnp.zeros_like(u).at[idx].add(vals)
    return dense, u - sent


def _cross_int8(shard, resid, axis, groups):
    """int8 DCN edge: per-chip symmetric scale, error feedback keeps
    the quantization error local and re-contributed. Rides the shared
    gather wire (ops/pack.all_gather_int8) — same codec as the DGC
    value wire and the MoE dispatch wire."""
    from edl_tpu.ops.pack import all_gather_int8
    u = shard + resid
    gathered, local = all_gather_int8(u, axis, axis_index_groups=groups)
    dense = jnp.sum(gathered.astype(u.dtype), axis=0)
    return dense, u - local.astype(u.dtype)


def _reduce_bucket(buf, resid, *, axis: str, n_slices: int, chips: int,
                   config: CommConfig):
    """One bucket's dp reduction. Returns (reduced full bucket, new
    residual shard) — residual is a zero-width array when dense."""
    if n_slices <= 1:
        # No DCN edge: one dense allreduce — the exact op XLA's
        # partitioner emits, so the flat bucketed path is bitwise with
        # the jit path by construction.
        return lax.psum(buf, axis), resid
    intra, cross = mesh_lib.dp_comm_groups(n_slices, chips)
    if chips > 1:
        shard = lax.psum_scatter(buf, axis, scatter_dimension=0,
                                 axis_index_groups=intra, tiled=True)
    else:
        shard = buf
    m = shard.shape[0]
    if config.compress == "off" or m < config.min_compress_elems \
            or not jnp.issubdtype(shard.dtype, jnp.floating):
        out = _cross_dense(shard, axis, cross)
    elif config.compress == "topk":
        out, resid = _cross_topk(shard, resid, axis, cross,
                                 _topk_k(m, config.topk_frac))
    else:
        out, resid = _cross_int8(shard, resid, axis, cross)
    if chips > 1:
        out = lax.all_gather(out, axis, axis_index_groups=intra,
                             tiled=True)
    return out, resid


def _needs_residual(bucket: _Bucket, chips: int, n_slices: int,
                    config: CommConfig) -> bool:
    return (config.compress != "off" and n_slices > 1
            and bucket.padded // chips >= config.min_compress_elems
            and jnp.issubdtype(jnp.dtype(bucket.dtype), jnp.floating))


# -- the step ----------------------------------------------------------------


def _validate_dp_mesh(mesh) -> str:
    """The manual path owns dp-only meshes; return the dp axis name."""
    if "dp" not in mesh.axis_names:
        raise ValueError(
            f"comm step needs a dp axis; mesh axes {mesh.axis_names}")
    for name in mesh.axis_names:
        if name != "dp" and mesh.shape[name] != 1:
            raise ValueError(
                "comm step owns dp-only meshes (dp gradients are the "
                f"cross-slice traffic); axis {name!r} has size "
                f"{mesh.shape[name]} — keep the XLA-partitioned step "
                "for fsdp/tp worlds")
    return "dp"


def _validate_ep_mesh(mesh) -> str:
    """The manual MoE path owns ep-only meshes; return the axis name."""
    if "ep" not in mesh.axis_names:
        raise ValueError(
            f"MoE comm step needs an ep axis; mesh axes "
            f"{mesh.axis_names}")
    for name in mesh.axis_names:
        if name != "ep" and mesh.shape[name] != 1:
            raise ValueError(
                "MoE comm step owns ep-only meshes (token dispatch is "
                f"the cross-slice traffic); axis {name!r} has size "
                f"{mesh.shape[name]} — keep the XLA-partitioned step "
                "for mixed meshes")
    return "ep"


class CommTrainStep:
    """``(state, batch) -> (state, metrics)`` with the manual bucketed
    gradient path. Drop-in for TrainLoop; the error-feedback residuals
    ride a closure cell exactly like the amp path's loss-scale state
    (they are transient comm state, deliberately not checkpointed — a
    restart re-contributes at most one step's dropped mass late).

    Built lazily: the bucket plan needs real leaf shapes, so the first
    call plans, initializes residuals and jits; later calls dispatch.

    loss_fn runs INSIDE the manual region: it must be mesh-free — no
    `with_sharding_constraint` / nested shard_map over the same mesh
    (build the model with mesh=None; under shard_map each shard
    computes exactly one chip's backward, so constraints are
    meaningless there and jax rejects them on manual axes).
    """

    def __init__(self, loss_fn: Callable, *, mesh, config: CommConfig,
                 topology=None, donate: bool = True,
                 batch_axes: tuple[str, ...] | None = None):
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.config = config
        self.axis = self._validate_mesh(mesh)
        self.world = int(mesh.shape[self.axis])
        topology = topology or mesh_lib.SliceTopology(1, self.world)
        if self.world % topology.n_slices:
            raise ValueError(
                f"dp={self.world} not divisible by n_slices="
                f"{topology.n_slices}")
        self.topology = topology
        # flat world + compression: the whole dp axis IS the slow edge
        # (every chip is its own slice) — how CPU worlds exercise the
        # compressed wire without emulated slices, and how a
        # single-chip-per-slice fleet degenerates.
        if config.compress != "off" and not topology.is_multi_slice:
            self.n_slices, self.chips = self.world, 1
        else:
            self.n_slices = topology.n_slices
            self.chips = self.world // topology.n_slices
        self.donate = donate
        self.batch_axes = batch_axes
        self.plan: BucketPlan | None = None
        self._jitted = None
        self._comm = None
        self.steps = 0
        self._bytes_counter = None
        try:
            from edl_tpu.obs import metrics as obs_metrics
            self._bytes_counter = obs_metrics.registry().counter(
                "step_dcn_bytes",
                help="bytes this process contributed to cross-slice "
                     "(DCN) gradient legs")
        except Exception:  # noqa: BLE001 — observability is optional
            pass

    def _validate_mesh(self, mesh) -> str:
        return _validate_dp_mesh(mesh)

    # -- static accounting (bench/obs surface) ------------------------------

    def dcn_bytes_per_step(self) -> int:
        """Per-chip cross-slice payload bytes each step (0 until the
        first call plans the buckets; 0 on single-slice topologies
        unless compression treats the flat dp axis as the slow edge)."""
        if self.plan is None:
            return 0
        return dcn_bytes_per_step(
            self.plan, self.config,
            n_slices=self.n_slices,
            chips_per_slice=self.chips)

    def dcn_overlap_pct(self) -> float:
        """Share of cross-slice bytes whose reduction can be in flight
        before the LAST bucket's gradients exist — the schedulable
        overlap the bucketed decomposition exposes (buckets fill in
        backward order; every bucket but the final one is dispatchable
        under remaining compute). A SCHEDULE property, not a
        measurement: the CPU harness has no DCN to overlap — on
        hardware, read the profiler. 0 for a single fused bucket."""
        if self.plan is None or self.plan.n_buckets <= 1 \
                or self.n_slices <= 1:
            return 0.0
        per_bucket = [
            _leg_bytes(b.padded // self.chips,
                       np.dtype(b.dtype).itemsize, self.config)
            for b in self.plan.buckets]
        total = sum(per_bucket)
        if total <= 0:
            return 0.0
        return round(100.0 * (total - per_bucket[-1]) / total, 2)

    def stats(self) -> dict:
        return {"comm_buckets": self.plan.n_buckets if self.plan else 0,
                "comm_bucket_mb": self.config.bucket_mb,
                "dcn_compress": self.config.compress,
                "dcn_bytes_per_step": self.dcn_bytes_per_step(),
                "dcn_overlap_pct": self.dcn_overlap_pct(),
                "comm_steps": self.steps}

    # -- build ---------------------------------------------------------------

    def _residual_init(self):
        res = []
        for b in self.plan.buckets:
            m = b.padded // self.chips if _needs_residual(
                b, self.chips, self.n_slices, self.config) else 0
            res.append(jnp.zeros((self.world, m), b.dtype))
        # one distinct row per axis position (dp_row_sharding
        # generalized to whichever axis this step owns — ep for MoE)
        sharding = NamedSharding(self.mesh, P(self.axis))
        return tuple(jax.device_put(r, sharding) for r in res)

    def _build(self, state, batch):
        self.plan = plan_buckets(state.params, self.config.bucket_mb,
                                 align=self.world)
        plan, axis, world = self.plan, self.axis, self.world
        n_slices, chips, config = self.n_slices, self.chips, self.config
        loss_fn = self.loss_fn
        inv_w = 1.0 / world  # power-of-two worlds: an EXACT scaling

        def shard_fn(state, batch, comm):
            def compute(p):
                return loss_fn(state, p, batch)

            (loss, aux), grads = jax.value_and_grad(
                compute, has_aux=True)(state.params)
            # local grads are d(local-mean); x inv_w then sum = global
            # mean, matching the jit path's 1/B_global backward seed
            grads = jax.tree.map(lambda g: g * jnp.asarray(inv_w, g.dtype),
                                 grads)
            bufs = pack_buckets(grads, plan)
            out, new_comm = [], []
            for buf, resid in zip(bufs, comm):
                r, e = _reduce_bucket(buf, resid.reshape(-1),
                                      axis=axis, n_slices=n_slices,
                                      chips=chips, config=config)
                out.append(r)
                new_comm.append(e.reshape(1, -1))
            grads = unpack_buckets(out, plan)
            loss = lax.psum(loss * inv_w, axis)
            # aux (metrics + BN batch_stats) is per-shard under
            # shard_map; average it so the replicated out_spec is
            # truthful. Global-batch variance != mean-of-shard
            # variances — a documented delta of the manual path, inside
            # the smoke's loss envelope.
            aux = jax.tree.map(
                lambda a: lax.pmean(a, axis)
                if jnp.issubdtype(jnp.asarray(a).dtype, jnp.inexact)
                else a, aux)
            return loss, aux, grads, tuple(new_comm)

        # pytree-PREFIX specs: state/grads/aux replicated, batch and
        # residuals sharded over dp on dim 0
        mapped = shard_map(
            shard_fn, mesh=self.mesh,
            in_specs=(P(), P(self.axis), P(self.axis)),
            out_specs=(P(), P(), P(), P(self.axis)))

        def step(state, batch, comm):
            loss, aux, grads, comm = mapped(state, batch, comm)
            new_stats = aux.pop("batch_stats", None)
            if new_stats is not None:
                state = state.apply_gradients(grads=grads,
                                              batch_stats=new_stats)
            else:
                state = state.apply_gradients(grads=grads)
            return state, {"loss": loss, **aux}, comm

        donate = (0, 2) if self.donate else ()
        self._jitted = jax.jit(step, donate_argnums=donate)
        self._comm = self._residual_init()
        log.info(
            "comm step: %d buckets (%.1f MiB target, align %d), "
            "%dx%d topology, compress=%s, dcn_bytes/step=%d, "
            "schedulable overlap %.1f%%", plan.n_buckets,
            config.bucket_mb, world, self.n_slices, self.chips,
            config.compress, self.dcn_bytes_per_step(),
            self.dcn_overlap_pct())

    # -- dispatch ------------------------------------------------------------

    def __call__(self, state, batch):
        if self._jitted is None:
            self._build(state, batch)
        from edl_tpu.obs import trace
        if trace.enabled():
            with trace.span("step.dcn_reduce",
                            attrs={"buckets": self.plan.n_buckets,
                                   "compress": self.config.compress,
                                   "dcn_bytes":
                                       self.dcn_bytes_per_step()}):
                state, metrics, self._comm = self._jitted(
                    state, batch, self._comm)
        else:
            state, metrics, self._comm = self._jitted(state, batch,
                                                      self._comm)
        self.steps += 1
        if self._bytes_counter is not None:
            self._bytes_counter.inc(self.dcn_bytes_per_step())
        return state, metrics


def make_comm_train_step(loss_fn: Callable, *, mesh,
                         config: CommConfig | None = None,
                         topology=None, donate: bool = True
                         ) -> CommTrainStep:
    """Build the manual-collective step. Same ``loss_fn(state, params,
    batch) -> (loss, aux)`` contract as `make_train_step`; returns a
    TrainLoop-compatible ``step(state, batch)`` callable carrying its
    bucket plan and wire accounting (`.stats()`)."""
    return CommTrainStep(loss_fn, mesh=mesh,
                         config=config or CommConfig(),
                         topology=topology, donate=donate)


# -- MoE: hierarchical all-to-all dispatch -----------------------------------
#
# The expert-parallel analogue of the bucketed gradient path above: an
# MoE layer's hot collective is the token all-to-all (dispatch to the
# expert owners, combine back), and on a hybrid topology it decomposes
# the same way the dp reduction does — an ICI leg inside the slice
# (tokens reach their slice's E/S co-resident experts without touching
# DCN) and a cross-slice DCN leg carrying only the OVERFLOW tokens
# routed to another slice's experts, optionally int8 on the wire
# (ops/pack.all_to_all_int8 — the same codec as the gradient legs).
# The decomposition is a pure permutation: uncompressed it is BITWISE
# identical to the single flat collective (moe_parity_gate pins this),
# and the int8 leg sits behind the same loss-envelope + convergence
# discipline as the gradient wire.

MOE_DISPATCH_MODES = ("flat", "hier")
MOE_COMPRESS_MODES = ("off", "int8")


@dataclass(frozen=True)
class MoEDispatchConfig:
    """Knobs of the manual MoE dispatch path.

    mode: 'flat' (one all-to-all over the whole ep axis — the single-
      collective baseline) or 'hier' (ICI leg + DCN overflow leg;
      EDL_TPU_MOE_DISPATCH).
    compress: DCN-leg wire format (EDL_TPU_MOE_COMPRESS) — 'off'
      (dense, bitwise with flat) or 'int8' (per-destination-block
      symmetric scale). int8 requires mode='hier': only the
      decomposed path has a separate DCN leg to compress.
    """

    mode: str = "hier"
    compress: str = "off"

    def __post_init__(self):
        if self.mode not in MOE_DISPATCH_MODES:
            raise ValueError(
                f"mode must be one of {MOE_DISPATCH_MODES}, "
                f"got {self.mode!r}")
        if self.compress not in MOE_COMPRESS_MODES:
            raise ValueError(
                f"compress must be one of {MOE_COMPRESS_MODES}, "
                f"got {self.compress!r}")
        if self.compress != "off" and self.mode != "hier":
            raise ValueError(
                "compress needs mode='hier' — the flat single "
                "collective has no separate DCN leg to compress")


def moe_all_to_all(x, *, axis: str, n_slices: int, chips: int,
                   mode: str = "hier", compress: str = "off"):
    """Destination-major block transport over the ep axis.

    ``x`` is (W, ...) on every chip: block ``x[w]`` is this chip's
    payload bound for chip ``w`` (W = n_slices * chips, slice-major).
    Returns the source-major received buffer of the same shape.

    'flat': one ``lax.all_to_all`` over the whole axis. 'hier': the
    two-level decomposition — an ICI all-to-all within each slice
    delivers every block to the chip IN ITS OWN SLICE holding the
    destination's intra-slice position, then a cross-slice all-to-all
    over the stride-C columns (mesh.ep_comm_groups) carries the
    off-slice blocks over DCN. A pure permutation: bitwise identical
    to 'flat' when uncompressed. compress='int8' quantizes only the
    DCN leg (per-destination-slice symmetric scales); the slice-local
    blocks never leave ICI and stay exact.
    """
    w = n_slices * chips
    if x.shape[0] != w:
        raise ValueError(
            f"dest-major dim {x.shape[0]} != world {n_slices}x{chips}")
    if mode == "flat" or n_slices <= 1:
        return lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                              tiled=True)
    intra, cross = mesh_lib.ep_comm_groups(n_slices, chips)
    b = x.reshape((n_slices, chips) + x.shape[1:])
    if chips > 1:
        b = lax.all_to_all(b, axis, split_axis=1, concat_axis=1,
                           tiled=True, axis_index_groups=intra)
    if compress == "int8" and jnp.issubdtype(b.dtype, jnp.floating):
        from edl_tpu.ops.pack import all_to_all_int8
        b = all_to_all_int8(b, axis,
                            axis_index_groups=cross).astype(x.dtype)
    else:
        b = lax.all_to_all(b, axis, split_axis=0, concat_axis=0,
                           tiled=True, axis_index_groups=cross)
    return b.reshape(x.shape)


def moe_leg_bytes(block_elems: int, itemsize: int, n_slices: int,
                  chips: int, compress: str) -> int:
    """Cross-slice bytes ONE chip sends for one dispatch/combine leg
    whose per-destination-chip block has ``block_elems`` elements —
    the same payload-only accounting as :func:`dcn_bytes_per_step`
    (off-slice blocks only; the slice-local blocks ride ICI free)."""
    if n_slices <= 1:
        return 0
    off = (n_slices - 1) * chips * block_elems
    if compress == "int8":
        return off * 1 + (n_slices - 1) * 4  # int8 payload + fp32 scales
    return off * itemsize


@dataclass
class MoEWire:
    """The transport a manual-region MoE layer dispatches through
    (models/transformer.MoEMLP's ``cfg.moe_wire`` contract): buffer
    reshapes + the grouped collectives, with a trace-time ``on_leg``
    hook so the owning step can account wire bytes statically.

    dispatch: (E, cap, d) per-chip dispatch buffer -> (E/W, W*cap, d)
      tokens received for this chip's local experts.
    combine: the inverse — (E/W, W*cap, d) expert outputs back to
      (E, cap, d) at the token owners.
    local_slice: (E, ...) replicated table -> this chip's (E/W, ...)
      expert rows (the in-region view of what the checkpoint stores
      ep-sharded).
    """

    axis: str
    n_slices: int
    chips: int
    config: MoEDispatchConfig
    on_leg: Callable | None = None

    @property
    def world(self) -> int:
        return self.n_slices * self.chips

    def _transport(self, x):
        if self.on_leg is not None:
            self.on_leg(tuple(int(v) for v in x.shape),
                        np.dtype(x.dtype).itemsize)
        return moe_all_to_all(x, axis=self.axis,
                              n_slices=self.n_slices, chips=self.chips,
                              mode=self.config.mode,
                              compress=self.config.compress)

    def dispatch(self, buf):
        e, cap, d = buf.shape
        w = self.world
        if e % w:
            raise ValueError(f"n_experts={e} not divisible by ep "
                             f"world {w}")
        el = e // w
        r = self._transport(buf.reshape(w, el, cap, d))
        return r.transpose(1, 0, 2, 3).reshape(el, w * cap, d)

    def combine(self, out):
        el, wcap, d = out.shape
        w = self.world
        cap = wcap // w
        r = self._transport(
            out.reshape(el, w, cap, d).transpose(1, 0, 2, 3))
        return r.reshape(w * el, cap, d)

    def local_slice(self, table):
        el = table.shape[0] // self.world
        i = lax.axis_index(self.axis)
        return lax.dynamic_slice_in_dim(table, i * el, el, axis=0)


class MoECommStep(CommTrainStep):
    """``(state, batch) -> (state, metrics)`` for an expert-parallel
    transformer over an ep-only mesh: the bucketed gradient reduction
    of :class:`CommTrainStep` (over ep — each chip's local-mean grads
    x 1/W then psum; an expert table's grad is nonzero only on its
    owner chip, so the same reduction assembles every expert exactly
    once) plus the hierarchical token dispatch injected into the model
    as its ``moe_wire``.

    Built from a loss FACTORY rather than a loss_fn: the factory
    receives the wire and returns a mesh-free ``loss_fn(state, params,
    batch)`` whose MoE layers transport through it (rebind the model
    config's ``moe_wire`` — params are untouched, so states move
    between the jit path and this one freely).
    """

    def __init__(self, loss_factory: Callable, *, mesh,
                 config: CommConfig | None = None,
                 moe_config: MoEDispatchConfig | None = None,
                 topology=None, donate: bool = True):
        moe_config = moe_config or MoEDispatchConfig()
        _validate_ep_mesh(mesh)
        world = int(mesh.shape["ep"])
        topology = topology or mesh_lib.SliceTopology(1, world)
        # flat world + hier dispatch: every chip its own slice (S=W,
        # C=1) — the same degeneration the gradient path uses, so CPU
        # worlds exercise the full DCN wire (incl. int8) without
        # emulated slices.
        if moe_config.mode == "hier" and not topology.is_multi_slice:
            self._moe_slices, self._moe_chips = world, 1
        else:
            self._moe_slices = topology.n_slices
            self._moe_chips = world // topology.n_slices
        self.moe_config = moe_config
        self._legs: list[tuple[tuple, int]] = []
        self.wire = MoEWire(axis="ep", n_slices=self._moe_slices,
                            chips=self._moe_chips, config=moe_config,
                            on_leg=self._record_leg)
        super().__init__(loss_factory(self.wire), mesh=mesh,
                         config=config or CommConfig(),
                         topology=topology, donate=donate)
        self._moe_counter = None
        try:
            from edl_tpu.obs import metrics as obs_metrics
            self._moe_counter = obs_metrics.registry().counter(
                "step_moe_dcn_bytes",
                help="bytes this process contributed to cross-slice "
                     "(DCN) MoE dispatch/combine legs")
        except Exception:  # noqa: BLE001 — observability is optional
            pass

    def _validate_mesh(self, mesh) -> str:
        return _validate_ep_mesh(mesh)

    def _record_leg(self, shape: tuple, itemsize: int):
        # trace-time hook: legs are recorded once, during the first
        # call's trace (self.steps is still 0) — retraces don't
        # double-count
        if self.steps == 0:
            self._legs.append((shape, itemsize))

    # -- static accounting (bench/obs surface) ------------------------------

    def moe_dcn_bytes_per_step(self) -> int:
        """Per-chip cross-slice dispatch+combine payload bytes each
        step (0 until the first call traces the wire)."""
        compress = (self.moe_config.compress
                    if self.moe_config.mode == "hier" else "off")
        total = 0
        for shape, itemsize in self._legs:
            block = int(np.prod(shape[1:])) if len(shape) > 1 else 1
            total += moe_leg_bytes(block, itemsize, self._moe_slices,
                                   self._moe_chips, compress)
        return total

    def moe_dispatch_overlap_pct(self) -> float:
        """Share of cross-slice dispatch bytes whose leg can be in
        flight under other layers' expert compute — every leg except
        the final combine (legs of layer i overlap layer i±1's expert
        FFNs). A SCHEDULE property like dcn_overlap_pct: the CPU
        harness has no DCN to overlap; on hardware, read the
        profiler."""
        n = len(self._legs)
        if n <= 1 or self._moe_slices <= 1:
            return 0.0
        return round(100.0 * (n - 1) / n, 2)

    def stats(self) -> dict:
        out = super().stats()
        out.update({"moe_dispatch": self.moe_config.mode,
                    "moe_compress": self.moe_config.compress,
                    "moe_dispatch_legs": len(self._legs),
                    "moe_dcn_bytes_per_step":
                        self.moe_dcn_bytes_per_step(),
                    "moe_dispatch_overlap_pct":
                        self.moe_dispatch_overlap_pct()})
        return out

    def __call__(self, state, batch):
        from edl_tpu.obs import trace
        if trace.enabled():
            with trace.span("step.moe_dispatch",
                            attrs={"mode": self.moe_config.mode,
                                   "compress": self.moe_config.compress,
                                   "moe_dcn_bytes":
                                       self.moe_dcn_bytes_per_step()}):
                out = super().__call__(state, batch)
        else:
            out = super().__call__(state, batch)
        if self._moe_counter is not None:
            self._moe_counter.inc(self.moe_dcn_bytes_per_step())
        return out


def make_moe_comm_step(loss_factory: Callable, *, mesh,
                       config: CommConfig | None = None,
                       moe_config: MoEDispatchConfig | None = None,
                       topology=None, donate: bool = True
                       ) -> MoECommStep:
    """Build the manual MoE step. ``loss_factory(wire) -> loss_fn``;
    returns a TrainLoop-compatible ``step(state, batch)`` callable
    carrying dispatch wire accounting in ``.stats()``."""
    return MoECommStep(loss_factory, mesh=mesh, config=config,
                       moe_config=moe_config, topology=topology,
                       donate=donate)


# -- the parity gate ---------------------------------------------------------


def tree_bitwise_equal(a, b) -> bool:
    """Bitwise pytree equality (NaNs at equal positions count equal)."""
    ok = [True]

    def cmp(x, y):
        x, y = np.asarray(x), np.asarray(y)
        if x.shape != y.shape or x.dtype != y.dtype:
            ok[0] = False
            return
        if np.issubdtype(x.dtype, np.floating):
            same = (x == y) | (np.isnan(x) & np.isnan(y))
            ok[0] = ok[0] and bool(same.all())
        else:
            ok[0] = ok[0] and bool(np.array_equal(x, y))

    jax.tree.map(cmp, jax.device_get(a), jax.device_get(b))
    return ok[0]


def loss_parity_gate(loss_fn: Callable, state, batch, *, mesh,
                     config: CommConfig, topology=None, steps: int = 3,
                     envelope: float = 5e-3) -> dict:
    """The gate the bench must pass before reporting DCN numbers.

    1. bucketed-DENSE vs the plain jit step: identical params AND loss
       after ``steps`` steps, bitwise (``bitwise_dense``).
    2. if ``config.compress != off``: the compressed path's per-step
       loss stays within ``envelope`` of the jit path's
       (``loss_envelope_ok`` / ``max_loss_delta``).

    Callers hand in a throwaway state (both paths train from it).
    """
    from edl_tpu.train.step import make_train_step

    placed = mesh_lib.shard_batch(mesh, batch)
    rep = lambda t: jax.device_put(  # noqa: E731
        t, NamedSharding(mesh, P()))
    jit_step = make_train_step(loss_fn, donate=False)
    s_jit = jax.tree.map(rep, state)
    jit_losses = []
    for _ in range(steps):
        s_jit, m = jit_step(s_jit, placed)
        jit_losses.append(float(m["loss"]))

    dense = make_comm_train_step(
        loss_fn, mesh=mesh, topology=topology, donate=False,
        config=dataclasses.replace(config, compress="off"))
    s_dense = jax.tree.map(rep, state)
    dense_loss = None
    for _ in range(steps):
        s_dense, m = dense(s_dense, placed)
        dense_loss = float(m["loss"])
    gate = {"bitwise_dense": tree_bitwise_equal(s_jit.params,
                                                s_dense.params)
            and dense_loss == jit_losses[-1],
            # float-tolerance parity of the dense path (what a
            # hierarchically re-associated sum can hold when bitwise
            # cannot)
            "dense_loss_delta": abs(dense_loss - jit_losses[-1]),
            "envelope": envelope, "steps": steps}
    if config.compress != "off":
        comp = make_comm_train_step(loss_fn, mesh=mesh,
                                    topology=topology, donate=False,
                                    config=config)
        s_comp = jax.tree.map(rep, state)
        deltas = []
        for i in range(steps):
            s_comp, m = comp(s_comp, placed)
            deltas.append(abs(float(m["loss"]) - jit_losses[i]))
        gate["max_loss_delta"] = max(deltas)
        gate["loss_envelope_ok"] = max(deltas) <= envelope
    gate["ok"] = bool(gate["bitwise_dense"]
                      and gate.get("loss_envelope_ok", True))
    return gate


# -- convergence-parity smoke (the CI gate) ----------------------------------


def _smoke_cnn(world: int):
    """Tiny BN CNN on separable synthetic images: dense-jit vs topk."""
    import optax

    from edl_tpu.models.resnet import ResNetTiny
    from edl_tpu.train import classification as cls

    rng = np.random.default_rng(7)
    n, hw, classes = 8 * world, 16, 4
    labels = rng.integers(0, classes, size=n).astype(np.int32)
    # class-colored images + noise: learnable in a few dozen steps
    images = (rng.normal(0, 0.3, size=(n, hw, hw, 3))
              + labels[:, None, None, None] / classes).astype(np.float32)
    model = ResNetTiny(num_classes=classes, dtype=jnp.float32)
    state = cls.create_state(model, jax.random.PRNGKey(0),
                             (1, hw, hw, 3), optax.sgd(0.05, momentum=0.9))

    def loss_fn(state, params, batch):
        variables = {"params": params, "batch_stats": state.batch_stats}
        logits, mutated = state.apply_fn(variables, batch["image"],
                                         train=True,
                                         mutable=["batch_stats"])
        targets = cls.smoothed_labels(batch["label"], classes, 0.0)
        loss = cls.soft_cross_entropy(logits, targets)
        return loss, {"batch_stats": mutated["batch_stats"]}

    return loss_fn, state, {"image": images, "label": labels}


def _smoke_transformer(world: int, mesh):
    """Tiny markov-LM transformer: the no-BN, bitwise-testable model."""
    import optax
    from flax.core import meta

    from edl_tpu.models.transformer import (Transformer,
                                            TransformerConfig, lm_loss_fn)
    from edl_tpu.train.state import TrainState

    vocab, seq = 32, 16
    gen = np.random.default_rng(11)
    successors = gen.integers(0, vocab, size=(vocab, 4))
    toks = np.empty((4 * world, seq), np.int32)
    toks[:, 0] = gen.integers(0, vocab, size=4 * world)
    for t in range(1, seq):
        pick = gen.integers(0, 4, size=4 * world)
        toks[:, t] = successors[toks[:, t - 1], pick]
    del mesh  # the comm region is mesh-free: constraints would clash
    # with shard_map's manual axes (see CommTrainStep docstring)
    cfg = TransformerConfig(vocab_size=vocab, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=seq,
                            dtype=jnp.float32, mesh=None)
    model = Transformer(cfg)
    variables = meta.unbox(model.init(jax.random.PRNGKey(0),
                                      jnp.asarray(toks), train=False))
    # momentum-SGD: the optimizer DGC's error-feedback analysis (and
    # the reference's DGCMomentum) is built for — adam's second moment
    # amplifies early sparsification noise and needs a longer horizon
    state = TrainState.create(apply_fn=model.apply,
                              params=variables["params"],
                              tx=optax.sgd(0.5, momentum=0.9))
    return lm_loss_fn, state, {"tokens": toks}


def convergence_smoke(compress: str = "topk", steps: int = 40,
                      envelope: float = 0.25,
                      topology=None) -> dict:
    """CNN + transformer convergence smokes: train the compressed path
    against dense-jit from the same init; both must LEARN (final loss
    below initial) and the compressed run must keep at least
    ``1 - envelope`` of dense's loss improvement (|dense - compressed|
    <= envelope * (initial - dense) — a RELATIVE envelope, so one pin
    serves models whose loss scales differ by 40x). The topk wire runs
    at 1/8 density = exactly the 4x DCN byte reduction the bench
    gates on. Returns the report dict; `smoke` CLI exits nonzero
    unless every gate holds."""
    world = jax.device_count()
    mesh = (mesh_lib.make_hybrid_mesh(mesh_lib.MeshSpec({"dp": -1}),
                                      topology)
            if topology is not None and topology.is_multi_slice
            else mesh_lib.make_mesh(mesh_lib.MeshSpec({"dp": -1})))
    report: dict = {"compress": compress, "steps": steps,
                    "envelope": envelope, "world": world,
                    "n_slices": topology.n_slices if topology else 1}

    def run(name, loss_fn, state, batch):
        placed = mesh_lib.shard_batch(mesh, batch)
        rep = lambda t: jax.device_put(  # noqa: E731
            t, NamedSharding(mesh, P()))
        from edl_tpu.train.step import make_train_step
        jit_step = make_train_step(loss_fn, donate=False)
        comp = make_comm_train_step(
            loss_fn, mesh=mesh, topology=topology, donate=False,
            config=CommConfig(bucket_mb=0.25, compress=compress,
                              topk_frac=0.125, min_compress_elems=64))
        s_a = jax.tree.map(rep, state)
        s_b = jax.tree.map(rep, state)
        first = last_a = last_b = None
        for _ in range(steps):
            s_a, m_a = jit_step(s_a, placed)
            s_b, m_b = comp(s_b, placed)
            if first is None:
                first = float(m_a["loss"])
            last_a, last_b = float(m_a["loss"]), float(m_b["loss"])
        delta = abs(last_a - last_b)
        improvement = max(first - last_a, 1e-9)
        report[name] = {
            "loss_initial": round(first, 4),
            "loss_dense": round(last_a, 4),
            "loss_compressed": round(last_b, 4),
            "delta": round(delta, 5),
            "delta_rel": round(delta / improvement, 5),
            "learned": last_a < first and last_b < first,
            "within_envelope": delta <= envelope * improvement}

    run("cnn", *_smoke_cnn(world))
    run("transformer", *_smoke_transformer(world, mesh))
    report["ok"] = all(report[k]["learned"] and report[k]["within_envelope"]
                      for k in ("cnn", "transformer"))
    return report


# -- MoE dispatch gates -------------------------------------------------------


def moe_parity_gate(loss_factory: Callable, state, batch, *, mesh,
                    moe_config: MoEDispatchConfig | None = None,
                    comm_config: CommConfig | None = None,
                    topology=None, steps: int = 3,
                    envelope: float = 0.1) -> dict:
    """The gate the bench must pass before reporting MoE DCN numbers —
    the r21 discipline applied to the dispatch wire.

    1. hier-UNCOMPRESSED vs the flat single collective: identical
       params AND per-step losses after ``steps`` steps, bitwise
       (``bitwise_hier`` — the decomposition is a pure permutation, so
       anything less is a wiring bug, not float noise).
    2. if ``moe_config.compress != 'off'``: the compressed path's
       per-step loss stays within ``envelope`` of flat's
       (``loss_envelope_ok`` / ``max_loss_delta``). The default is
       wider than the gradient gate's: int8 here rides ACTIVATIONS
       (transient — no error-feedback residual to reclaim rounding),
       so per-step deltas are real quantization noise, a few percent
       of a from-init loss; the convergence smoke's RELATIVE envelope
       is the binding check on whether that noise costs learning.

    Both arms are MoECommStep instances — jit-vs-manual is NOT gated
    here: the manual region routes per CHIP (local capacity) while the
    jit dense path routes per GLOBAL batch, a documented semantic
    delta covered by the convergence smoke's relative envelope.
    Callers hand in a throwaway state (every arm trains from it).
    """
    moe_config = moe_config or MoEDispatchConfig()
    placed = mesh_lib.shard_batch(mesh, batch, batch_axes=("ep",))
    rep = lambda t: jax.device_put(  # noqa: E731
        t, NamedSharding(mesh, P()))

    def run(mcfg):
        step = MoECommStep(loss_factory, mesh=mesh,
                           config=comm_config, moe_config=mcfg,
                           topology=topology, donate=False)
        s = jax.tree.map(rep, state)
        losses = []
        for _ in range(steps):
            s, m = step(s, placed)
            losses.append(float(m["loss"]))
        return s, losses

    s_flat, l_flat = run(MoEDispatchConfig(mode="flat"))
    s_hier, l_hier = run(MoEDispatchConfig(mode="hier"))
    gate = {"bitwise_hier": tree_bitwise_equal(s_flat.params,
                                               s_hier.params)
            and l_flat == l_hier,
            "hier_loss_delta": max(abs(a - b)
                                   for a, b in zip(l_flat, l_hier)),
            "envelope": envelope, "steps": steps}
    if moe_config.compress != "off":
        _, l_comp = run(moe_config)
        deltas = [abs(a - b) for a, b in zip(l_flat, l_comp)]
        gate["max_loss_delta"] = max(deltas)
        gate["loss_envelope_ok"] = max(deltas) <= envelope
    gate["ok"] = bool(gate["bitwise_hier"]
                      and gate.get("loss_envelope_ok", True))
    return gate


def _smoke_moe(world: int):
    """Tiny MoE markov-LM: returns ``(loss_factory, jit_loss_fn,
    state, batch)``. The factory closes over the wire for the manual
    step; the jit loss runs the dense-einsum dispatch (wire=None) on
    the same params."""
    import functools

    import optax
    from flax.core import meta

    from edl_tpu.models.transformer import (Transformer,
                                            TransformerConfig,
                                            lm_loss_moe)
    from edl_tpu.train.state import TrainState

    vocab, seq = 32, 16
    gen = np.random.default_rng(23)
    successors = gen.integers(0, vocab, size=(vocab, 4))
    toks = np.empty((4 * world, seq), np.int32)
    toks[:, 0] = gen.integers(0, vocab, size=4 * world)
    for t in range(1, seq):
        pick = gen.integers(0, 4, size=4 * world)
        toks[:, t] = successors[toks[:, t - 1], pick]
    cfg = TransformerConfig(vocab_size=vocab, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=seq,
                            dtype=jnp.float32, mesh=None, moe=True,
                            n_experts=2 * world, moe_top_k=2)
    model = Transformer(cfg)
    variables = meta.unbox(model.init(jax.random.PRNGKey(0),
                                      jnp.asarray(toks), train=False))
    state = TrainState.create(apply_fn=model.apply,
                              params=variables["params"],
                              tx=optax.sgd(0.5, momentum=0.9))

    def loss_factory(wire):
        wired = Transformer(dataclasses.replace(cfg, moe_wire=wire))
        return functools.partial(lm_loss_moe,
                                 aux_weight=cfg.moe_aux_weight,
                                 apply_fn=wired.apply)

    jit_loss = functools.partial(lm_loss_moe,
                                 aux_weight=cfg.moe_aux_weight)
    return loss_factory, jit_loss, state, {"tokens": toks}


def moe_convergence_smoke(compress: str = "int8", steps: int = 40,
                          envelope: float = 0.25,
                          topology=None) -> dict:
    """MoE convergence smoke (the ``smoke --moe`` CI gate): train the
    hierarchical+compressed dispatch against the uncompressed manual
    baseline from the same init. Both must LEARN and the compressed
    run must keep at least ``1 - envelope`` of the baseline's loss
    improvement — the relative envelope of `convergence_smoke`, but
    SIGNED: only a compressed run that converges WORSE by more than
    ``envelope * improvement`` fails (at lr high enough to learn in
    40 steps, trajectories diverge chaotically under any per-step
    noise, and int8 dispatch noise can just as well land ahead of the
    baseline — penalizing |delta| would fail runs that beat dense).
    The flat/off MoECommStep is the dense reference so the
    envelope isolates the wire (per-chip routing is identical in both
    arms; the jit path's global-capacity routing delta is reported as
    ``jit_loss_final`` for the learned check, not gated). Runs the
    bitwise flat-vs-hier parity gate first; a red gate fails the
    smoke regardless of convergence."""
    world = jax.device_count()
    mesh = (mesh_lib.make_hybrid_mesh(mesh_lib.MeshSpec({"ep": -1}),
                                      topology)
            if topology is not None and topology.is_multi_slice
            else mesh_lib.make_mesh(mesh_lib.MeshSpec({"ep": -1})))
    loss_factory, jit_loss, state, batch = _smoke_moe(world)
    placed = mesh_lib.shard_batch(mesh, batch, batch_axes=("ep",))
    rep = lambda t: jax.device_put(  # noqa: E731
        t, NamedSharding(mesh, P()))
    comm_cfg = CommConfig(bucket_mb=0.25)

    gate = moe_parity_gate(
        loss_factory, state, batch, mesh=mesh, topology=topology,
        comm_config=comm_cfg,
        moe_config=MoEDispatchConfig(mode="hier", compress=compress))

    def run(mcfg):
        step = MoECommStep(loss_factory, mesh=mesh, config=comm_cfg,
                           moe_config=mcfg, topology=topology,
                           donate=False)
        s = jax.tree.map(rep, state)
        first = last = None
        for _ in range(steps):
            s, m = step(s, placed)
            if first is None:
                first = float(m["loss"])
            last = float(m["loss"])
        return first, last, step

    first, dense_last, _ = run(MoEDispatchConfig(mode="flat"))
    _, comp_last, comp_step = run(
        MoEDispatchConfig(mode="hier", compress=compress))

    from edl_tpu.train.step import make_train_step
    jit_step = make_train_step(jit_loss, donate=False)
    s_jit = jax.tree.map(rep, state)
    jit_last = None
    for _ in range(steps):
        s_jit, m = jit_step(s_jit, placed)
        jit_last = float(m["loss"])

    delta = comp_last - dense_last  # signed: + = compression cost
    improvement = max(first - dense_last, 1e-9)
    report = {
        "compress": compress, "steps": steps, "envelope": envelope,
        "world": world,
        "n_slices": topology.n_slices if topology else 1,
        "parity_gate": gate,
        "loss_initial": round(first, 4),
        "loss_dense": round(dense_last, 4),
        "loss_compressed": round(comp_last, 4),
        "jit_loss_final": round(jit_last, 4),
        "delta": round(delta, 5),
        "delta_rel": round(delta / improvement, 5),
        "learned": dense_last < first and comp_last < first,
        "within_envelope": delta <= envelope * improvement,
        "moe_dcn_bytes_per_step": comp_step.moe_dcn_bytes_per_step(),
        "moe_dispatch_overlap_pct":
            comp_step.moe_dispatch_overlap_pct()}
    report["ok"] = bool(gate["ok"] and report["learned"]
                        and report["within_envelope"])
    return report


def _main(argv=None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(prog="edl_tpu.train.comm")
    sub = parser.add_subparsers(dest="cmd", required=True)
    smoke = sub.add_parser(
        "smoke", help="convergence-parity smoke: compressed DCN leg vs "
                      "dense jit on the CNN + transformer tinies")
    smoke.add_argument("--compress", choices=("topk", "int8"),
                       default=None,
                       help="DCN wire format (default: topk; int8 "
                            "with --moe, which has no topk wire)")
    smoke.add_argument("--moe", action="store_true",
                       help="run the MoE dispatch smoke (hier+int8 "
                            "all-to-all vs the flat/off manual "
                            "baseline, bitwise parity gate first) "
                            "instead of the gradient-wire smokes")
    smoke.add_argument("--steps", type=int, default=40)
    smoke.add_argument("--envelope", type=float, default=0.25,
                       help="RELATIVE loss envelope: the compressed "
                            "run must keep >= 1-envelope of dense's "
                            "loss improvement")
    smoke.add_argument("--slices", type=int, default=2,
                       help="emulated slice count (1 = flat dp)")
    args = parser.parse_args(argv)
    world = jax.device_count()
    topo = None
    if args.slices > 1:
        if world % args.slices:
            raise SystemExit(f"{world} devices not divisible by "
                             f"--slices {args.slices}")
        topo = mesh_lib.SliceTopology(args.slices, world // args.slices)
    if args.moe:
        compress = args.compress or "int8"
        if compress == "topk":
            raise SystemExit("--moe supports --compress int8 only "
                             "(token blocks have no sparse wire)")
        report = moe_convergence_smoke(compress=compress,
                                       steps=args.steps,
                                       envelope=args.envelope,
                                       topology=topo)
        print(json.dumps({"moe_smoke": report}))
        return 0 if report["ok"] else 1
    report = convergence_smoke(compress=args.compress or "topk",
                               steps=args.steps,
                               envelope=args.envelope, topology=topo)
    print(json.dumps({"comm_smoke": report}))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    import sys

    sys.exit(_main())
