"""Classification losses + step builders (label smoothing, mixup, distill).

Feature parity with the reference trainer's loss menu
(`example/collective/resnet50/train_with_fleet.py:227-276`: mixup with
Beta(alpha, alpha), label smoothing epsilon, softmax-CE; distill variant adds
a soft-label CE against teacher scores,
`example/distill/resnet/train_with_fleet.py:254-259`; NLP distill uses
temperature-T KL, `example/distill/nlp/distill.py`).

JAX-first: mixup randomness is derived inside the jitted step from
`fold_in(seed, state.step)` so a resumed elastic run replays the identical
augmentation stream — no host RNG state to checkpoint.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

# The on-device pixel ops live with the rest of the device-side
# augmentation plane (ops/augment.py, the packed-records feed path);
# re-exported here because every step builder and its callers import
# them from this module.
from edl_tpu.ops.augment import (IMAGENET_MEAN, IMAGENET_STD,  # noqa: F401
                                 mixup, normalize_image)
from edl_tpu.train.state import TrainState
from edl_tpu.train.step import make_train_step


def smoothed_labels(labels: jax.Array, num_classes: int,
                    smoothing: float = 0.0) -> jax.Array:
    """Integer labels -> (optionally smoothed) one-hot targets, fp32."""
    one_hot = jax.nn.one_hot(labels, num_classes, dtype=jnp.float32)
    if smoothing > 0.0:
        one_hot = one_hot * (1.0 - smoothing) + smoothing / num_classes
    return one_hot


def soft_cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean CE between logits and a target distribution."""
    return -jnp.mean(jnp.sum(targets * jax.nn.log_softmax(logits), axis=-1))


def distill_kl(student_logits: jax.Array, teacher_logits: jax.Array,
               temperature: float = 1.0) -> jax.Array:
    """Temperature-scaled KL(teacher || student), scaled by T^2 (Hinton)."""
    t = temperature
    teacher = jax.nn.softmax(teacher_logits / t)
    return soft_cross_entropy(student_logits / t, teacher) * t * t


def accuracy_topk(logits: jax.Array, labels: jax.Array, k: int = 1
                  ) -> jax.Array:
    topk = jax.lax.top_k(logits, k)[1]
    hit = jnp.any(topk == labels[:, None], axis=-1)
    return jnp.mean(hit.astype(jnp.float32))


def create_state(model, rng: jax.Array, input_shape: tuple,
                 tx: optax.GradientTransformation,
                 input_dtype=jnp.float32) -> TrainState:
    """Init a TrainState for a flax classification model (BN-aware).

    Init runs under jit: eager init dispatches each layer op separately,
    which is pathologically slow over a remote-device tunnel.
    """
    variables = jax.jit(lambda r: model.init(
        r, jnp.zeros(input_shape, input_dtype), train=False))(rng)
    params = variables["params"]
    batch_stats = variables.get("batch_stats")
    return TrainState.create(apply_fn=model.apply, params=params, tx=tx,
                             batch_stats=batch_stats)


def make_classification_step(num_classes: int, *, smoothing: float = 0.0,
                             mixup_alpha: float = 0.0, seed: int = 0,
                             weight_decay_in_loss: float = 0.0,
                             normalize: str | None = None,
                             donate: bool = True, comm=None, mesh=None,
                             topology=None) -> Callable:
    """Jitted (state, batch)->(state, metrics) for {'image','label'} batches.

    Handles flax BN mutable batch_stats; mixup/smoothing optional. L2 can be
    added here (reference uses optimizer regularizer; prefer optax wd).
    `normalize` runs on-device pixel normalization (see `normalize_image`)
    so uint8 batches off the JPEG plane train directly.
    `comm`/`mesh`/`topology` route the gradient reduction through the
    manual DCN-aware bucketed path (train/comm.py) — see make_train_step.
    """

    def loss_fn(state: TrainState, params: Any, batch: dict):
        targets = smoothed_labels(batch["label"], num_classes, smoothing)
        images = normalize_image(batch["image"], normalize)
        if mixup_alpha > 0.0:
            key = jax.random.fold_in(jax.random.PRNGKey(seed), state.step)
            images, targets = mixup(key, images, targets, mixup_alpha)
        variables = {"params": params}
        if state.batch_stats is not None:
            variables["batch_stats"] = state.batch_stats
            logits, mutated = state.apply_fn(
                variables, images, train=True, mutable=["batch_stats"])
            new_stats = mutated["batch_stats"]
        else:
            logits = state.apply_fn(variables, images, train=True)
            new_stats = None
        loss = soft_cross_entropy(logits, targets)
        if weight_decay_in_loss > 0.0:
            l2 = sum(jnp.sum(jnp.square(p))
                     for p in jax.tree.leaves(params))
            loss = loss + 0.5 * weight_decay_in_loss * l2
        aux = {"acc1": accuracy_topk(logits, batch["label"], 1)}
        if new_stats is not None:
            aux["batch_stats"] = new_stats
        return loss, aux

    return make_train_step(loss_fn, donate=donate, comm=comm, mesh=mesh,
                           topology=topology)


def _make_kd_step(kd_loss: Callable, num_classes: int, *,
                  hard_weight: float, smoothing: float, donate: bool,
                  input_key: str, normalize: str | None = None) -> Callable:
    """Shared KD step plumbing: `kd_loss(logits, batch) -> loss` is the
    only thing that differs between the dense and sparse variants."""

    def loss_fn(state: TrainState, params: Any, batch: dict):
        images = normalize_image(batch[input_key], normalize)
        variables = {"params": params}
        if state.batch_stats is not None:
            variables["batch_stats"] = state.batch_stats
            logits, mutated = state.apply_fn(
                variables, images, train=True,
                mutable=["batch_stats"])
            new_stats = mutated["batch_stats"]
        else:
            logits = state.apply_fn(variables, images, train=True)
            new_stats = None
        loss = kd_loss(logits, batch)
        if hard_weight > 0.0:
            targets = smoothed_labels(batch["label"], num_classes, smoothing)
            loss = ((1.0 - hard_weight) * loss
                    + hard_weight * soft_cross_entropy(logits, targets))
        aux = {"acc1": accuracy_topk(logits, batch["label"], 1)}
        if new_stats is not None:
            aux["batch_stats"] = new_stats
        return loss, aux

    return make_train_step(loss_fn, donate=donate)


def make_distill_step(num_classes: int, *, temperature: float = 1.0,
                      hard_weight: float = 0.0, smoothing: float = 0.0,
                      donate: bool = True, input_key: str = "image",
                      predict_key: str = "teacher_logits",
                      normalize: str | None = None) -> Callable:
    """Step for {input_key,'label',predict_key} batches: KD loss
    (+ optional hard-label CE mix). The student-side consumer of the
    DistillReader pipeline (reference distill/resnet train_with_fleet.py
    soft-label path)."""

    def kd_loss(logits, batch):
        return distill_kl(logits, batch[predict_key], temperature)

    return _make_kd_step(kd_loss, num_classes, hard_weight=hard_weight,
                         smoothing=smoothing, donate=donate,
                         input_key=input_key, normalize=normalize)


def sparse_distill_kl(student_logits: jax.Array, teacher_idx: jax.Array,
                      teacher_val: jax.Array,
                      temperature: float = 1.0) -> jax.Array:
    """`distill_kl` against a TOP-K teacher: (B, K) indices + values from
    the compressed teacher wire (distill/teacher_server.py
    `compress_outputs`). Teacher probs renormalize over the k classes
    (exactly what scatter-expanding with a -inf fill yields), and the
    student's log-probs are gathered at the teacher's indices — the full
    (B, C) dense teacher tensor never exists on device."""
    t = temperature
    teacher = jax.nn.softmax(teacher_val.astype(jnp.float32) / t, axis=-1)
    logp = jax.nn.log_softmax(student_logits.astype(jnp.float32) / t,
                              axis=-1)
    logp_k = jnp.take_along_axis(logp, teacher_idx.astype(jnp.int32),
                                 axis=-1)
    return -jnp.mean(jnp.sum(teacher * logp_k, axis=-1)) * t * t


def make_sparse_distill_step(num_classes: int, *, temperature: float = 1.0,
                             hard_weight: float = 0.0,
                             smoothing: float = 0.0, donate: bool = True,
                             input_key: str = "image",
                             predict_key: str = "teacher_logits",
                             normalize: str | None = None) -> Callable:
    """`make_distill_step` for sparse teacher targets: batches carry
    ``{predict_key}.idx`` / ``{predict_key}.val`` (DistillReader with
    ``compress_topk=K, sparse_predicts=True``) instead of dense logits.
    """

    def kd_loss(logits, batch):
        return sparse_distill_kl(logits, batch[predict_key + ".idx"],
                                 batch[predict_key + ".val"], temperature)

    return _make_kd_step(kd_loss, num_classes, hard_weight=hard_weight,
                         smoothing=smoothing, donate=donate,
                         input_key=input_key, normalize=normalize)


def make_eval_step(input_key: str = "image",
                   normalize: str | None = None) -> Callable:
    """Jitted eval: (state, batch) -> {'acc1','acc5'} (train=False)."""

    @jax.jit
    def eval_step(state: TrainState, batch: dict) -> dict:
        variables = {"params": state.params}
        if state.batch_stats is not None:
            variables["batch_stats"] = state.batch_stats
        logits = state.apply_fn(
            variables, normalize_image(batch[input_key], normalize),
            train=False)
        return {"acc1": accuracy_topk(logits, batch["label"], 1),
                "acc5": accuracy_topk(logits, batch["label"],
                                      min(5, logits.shape[-1]))}

    return eval_step
