"""Learning-rate schedules.

Feature parity with the reference's schedule set
(example/collective/resnet50/train_with_fleet.py:114-225): piecewise decay
with linear warmup, cosine decay [with warmup], exponential decay with
warmup, plus linear-scaling helpers for elastic batch-size changes
(doc/edl_collective_design_doc.md:14-16 — LR rescales when the world
resizes). All schedules are pure functions of the global step, safe inside
jit (optax-style), built on optax combinators.
"""

from __future__ import annotations

import optax


def linear_warmup(base_lr: float, warmup_steps: int) -> optax.Schedule:
    return optax.linear_schedule(0.0, base_lr, max(1, warmup_steps))


def piecewise_with_warmup(boundaries: list[int], values: list[float],
                          warmup_steps: int = 0) -> optax.Schedule:
    """Step decay: lr = values[i+1] once global step >= boundaries[i];
    linear warmup from 0 to values[0] over the first warmup_steps (so the
    schedule is continuous at the warmup/decay join). Boundaries are in
    GLOBAL steps (optax.join_schedules re-bases the inner schedule's step
    count to the join point, so boundaries are shifted back by warmup_steps
    here)."""
    assert len(values) == len(boundaries) + 1
    assert all(b > warmup_steps for b in boundaries), \
        "decay boundaries must come after warmup"

    def make_piecewise(offset: int) -> optax.Schedule:
        return optax.piecewise_constant_schedule(
            values[0],
            {b - offset: values[i + 1] / values[i]
             for i, b in enumerate(boundaries)},
        )

    if warmup_steps <= 0:
        return make_piecewise(0)
    return optax.join_schedules(
        [linear_warmup(values[0], warmup_steps),
         make_piecewise(warmup_steps)],
        [warmup_steps])


def cosine_with_warmup(base_lr: float, total_steps: int,
                       warmup_steps: int = 0, end_lr: float = 0.0
                       ) -> optax.Schedule:
    if warmup_steps <= 0:
        return optax.cosine_decay_schedule(base_lr, max(1, total_steps),
                                           alpha=end_lr / max(base_lr, 1e-12))
    return optax.warmup_cosine_decay_schedule(
        0.0, base_lr, warmup_steps, max(total_steps, warmup_steps + 1),
        end_value=end_lr)


def exponential_with_warmup(base_lr: float, warmup_steps: int,
                            decay_steps: int, decay_rate: float,
                            staircase: bool = True) -> optax.Schedule:
    decay = optax.exponential_decay(base_lr, decay_steps, decay_rate,
                                    staircase=staircase)
    if warmup_steps <= 0:
        return decay
    return optax.join_schedules(
        [linear_warmup(base_lr, warmup_steps), decay], [warmup_steps])


def scale_for_world(base_lr: float, base_world: int, world: int) -> float:
    """Linear-scaling rule on elastic resize: lr ∝ global batch size."""
    return base_lr * world / max(1, base_world)
