"""Checkpoint chunk I/O: the numpy-only half of the sharded format.

Split out of ``train/sharded_checkpoint.py`` (which keeps the
jax-dependent halves: device snapshots and the resharding restore
planner) so consumers that only move or verify chunk FILES — the chaos
plane's corruption drills, the soak worker pods, future repair tools —
can use the format without importing jax. Everything here is numpy +
stdlib.

Integrity: every chunk written by ``write_snapshot`` records a crc32 of
its raw array bytes in the chunk table (``"crc32"``). Readers verify on
load (``ChunkFiles`` for disk, the migration plane's peer fetch for the
wire) and raise the typed ``EdlCheckpointCorrupt`` on mismatch — a
truncated or bit-flipped chunk becomes a recoverable error with a
fallback (previous sealed version / another donor), never a silently
garbage restore. ``EDL_TPU_CKPT_VERIFY=0`` disables verification (the
chaos plane's weakened-audit drill proves the auditor still catches the
corruption downstream). Tables written before this field existed simply
have no ``crc32`` keys and skip verification chunk-by-chunk.
"""

from __future__ import annotations

import glob
import json
import os
import re
import threading
import zlib

import numpy as np

from edl_tpu.utils import config
from edl_tpu.utils.exceptions import EdlCheckpointCorrupt

_INDEX_RE = re.compile(r"^index\.(\d+)\.json$")


def chunk_name(leaf_i: int, offset: tuple[int, ...]) -> str:
    tag = "_".join(str(o) for o in offset) if offset else "scalar"
    return f"leaf{leaf_i}-o{tag}.npy"


def slices_to_offset_shape(index: tuple, shape: tuple[int, ...]
                           ) -> tuple[tuple[int, ...], tuple[int, ...]]:
    offset, size = [], []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        offset.append(start)
        size.append(stop - start)
    return tuple(offset), tuple(size)


def chunk_crc32(arr: np.ndarray) -> int:
    """crc32 of the array's raw bytes (C order). This is the seal-time
    fingerprint recorded in the chunk table and re-computed on every
    load path — disk mmap and peer wire alike — so the same number
    guards both."""
    arr = np.ascontiguousarray(arr)
    return zlib.crc32(memoryview(arr).cast("B")) & 0xFFFFFFFF


def verify_enabled() -> bool:
    """Integrity verification on restore (EDL_TPU_CKPT_VERIFY; default
    on). The off switch exists for the chaos plane's weakened-audit
    drill and for measuring the verify cost, not for production."""
    return config.env_flag("EDL_TPU_CKPT_VERIFY", True)


def write_snapshot(directory: str, snap: dict) -> list[str]:
    """Write a ``snapshot_shards``-shaped dict into ``directory``.

    Safe on a background thread (pure numpy + file I/O). Records each
    chunk's crc32 into the leaf table IN PLACE before writing the index
    — the same table object a retained sealed snapshot serves to
    migration peers, so donor manifests carry the checksums for free.
    Returns the basenames this process wrote (chunks + its index file),
    index last so its presence implies the chunks made it.
    """
    os.makedirs(directory, exist_ok=True)
    written: list[str] = []
    crcs: dict[str, int] = {}
    for fname, arr in snap["chunks"]:
        np.save(os.path.join(directory, fname), arr)
        crcs[fname] = chunk_crc32(arr)
        written.append(fname)
    for leaf in snap["leaves"]:
        for chunk in leaf["chunks"]:
            crc = crcs.get(chunk["file"])
            if crc is not None:
                chunk["crc32"] = crc
    index_name = f"index.{snap['process_index']}.json"
    with open(os.path.join(directory, index_name), "w") as f:
        json.dump({"leaves": snap["leaves"]}, f)
    written.append(index_name)
    return written


def merge_leaf_tables(tables: list[list[dict]]) -> dict[str, dict]:
    """key -> {shape, dtype, chunks[]} merged across per-process leaf
    tables (the `leaves` list of an index file, a `snapshot_shards`
    result, or a migration donor's manifest)."""
    merged: dict[str, dict] = {}
    for leaves in tables:
        for leaf in leaves:
            entry = merged.setdefault(
                leaf["key"], {"shape": leaf["shape"], "dtype": leaf["dtype"],
                              "chunks": []})
            if entry["shape"] != leaf["shape"]:
                raise ValueError(
                    f"shape mismatch across leaf tables for {leaf['key']}")
            entry["chunks"].extend(leaf["chunks"])
    return merged


def read_merged_index(directory: str) -> dict[str, dict]:
    """key -> {shape, dtype, chunks[]} merged across all process indexes."""
    paths = glob.glob(os.path.join(directory, "index.*.json"))
    if not paths:
        raise FileNotFoundError(f"no index.*.json under {directory}")
    tables = []
    for p in sorted(paths):
        with open(p) as f:
            tables.append(json.load(f)["leaves"])
    return merge_leaf_tables(tables)


def checksum_map(merged: dict[str, dict]) -> dict[str, int]:
    """chunk file -> expected crc32 from a merged leaf table (chunks
    from pre-integrity checkpoints are absent: no crc, no check)."""
    out: dict[str, int] = {}
    for entry in merged.values():
        for chunk in entry["chunks"]:
            crc = chunk.get("crc32")
            if crc is not None:
                out[chunk["file"]] = int(crc)
    return out


class ChunkFiles:
    """Per-restore cache of memory-mapped chunk files.

    A resharding restore reads the same chunk for every target region it
    intersects; re-running np.load per region paid a file open + header
    parse each time. One handle per file, shared across regions (and
    across reader threads — numpy memmap reads are thread-safe).

    With ``crcs`` set (the merged index's checksum_map), each file is
    verified ONCE on first load — a full read of the chunk, which the
    intersecting regions were about to page in anyway — and a mismatch
    or an unloadable file raises ``EdlCheckpointCorrupt`` naming the
    chunk, so the caller can fall back instead of assembling garbage."""

    def __init__(self, directory: str, crcs: dict[str, int] | None = None,
                 verify: bool | None = None):
        self.directory = directory
        self._crcs = crcs or {}
        self._verify = verify_enabled() if verify is None else verify
        self._handles: dict[str, np.ndarray] = {}
        self._lock = threading.Lock()

    def load(self, fname: str) -> np.ndarray:
        with self._lock:
            h = self._handles.get(fname)
            if h is None:
                path = os.path.join(self.directory, fname)
                try:
                    h = np.load(path, mmap_mode="r")
                except (OSError, ValueError, EOFError) as exc:
                    raise EdlCheckpointCorrupt(
                        f"chunk {fname} unreadable under {self.directory}:"
                        f" {exc}") from exc
                expect = self._crcs.get(fname)
                if self._verify and expect is not None:
                    got = chunk_crc32(np.asarray(h))
                    if got != expect:
                        raise EdlCheckpointCorrupt(
                            f"chunk {fname} failed integrity check "
                            f"(crc32 {got:#010x} != sealed "
                            f"{expect:#010x}) under {self.directory}")
                self._handles[fname] = h
            return h

    def close(self) -> None:
        self._handles.clear()  # memmaps close when the views are collected


def read_region(load, entry: dict, index: tuple) -> np.ndarray:
    """Assemble the region `index` (tuple of slices) from saved chunks.

    ``load(fname) -> ndarray`` is the chunk source — a `ChunkFiles`
    mmap cache for on-disk checkpoints, or a peer-fetch cache when the
    chunks live in a migration donor's memory."""
    shape = tuple(entry["shape"])
    offset, size = slices_to_offset_shape(index, shape)
    out = np.empty(size, dtype=np.dtype(entry["dtype"]))
    # Coverage mask (not an element count): overlapping chunks — e.g. a
    # half-written dir mixing two world shapes — must not mask a hole.
    covered = np.zeros(size, dtype=bool)
    for chunk in entry["chunks"]:
        coff, cshape = chunk["offset"], chunk["shape"]
        lo = [max(o, co) for o, co in zip(offset, coff)]
        hi = [min(o + s, co + cs)
              for o, s, co, cs in zip(offset, size, coff, cshape)]
        if any(a >= b for a, b in zip(lo, hi)):
            continue
        src = load(chunk["file"])
        src_sel = tuple(slice(a - co, b - co)
                        for a, b, co in zip(lo, hi, coff))
        dst_sel = tuple(slice(a - o, b - o)
                        for a, b, o in zip(lo, hi, offset))
        out[dst_sel] = src[src_sel]
        covered[dst_sel] = True
    if not covered.all():
        missing = int(covered.size - np.count_nonzero(covered))
        raise ValueError(
            f"chunks leave {missing}/{covered.size} elements of region "
            f"{offset}+{size} unwritten — checkpoint incomplete for this "
            f"resharding")
    return out


def is_sharded_dir(directory: str) -> bool:
    try:
        return any(_INDEX_RE.match(n) for n in os.listdir(directory))
    except FileNotFoundError:
        return False
