"""Train-side package. Lazy (PEP 562) like ``edl_tpu.data``/``distill``:
``import edl_tpu.train`` must not pull jax/flax, so jax-free consumers
(the chaos plane's checkpoint drills via ``train.ckpt_io``) can import
the package on a box with no accelerator stack."""

_LAZY = {
    "TrainState": ("edl_tpu.train.state", "TrainState"),
    "TrainStatus": ("edl_tpu.train.state", "TrainStatus"),
    "CheckpointManager": ("edl_tpu.train.checkpoint", "CheckpointManager"),
    "CheckpointWriteError": ("edl_tpu.train.checkpoint",
                             "CheckpointWriteError"),
    "DynamicLossScale": ("edl_tpu.train.amp", "DynamicLossScale"),
    "lr": ("edl_tpu.train.lr", None),
}

__all__ = list(_LAZY)


def __getattr__(name: str):
    try:
        module_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module 'edl_tpu.train' has no attribute {name!r}") from None
    import importlib
    module = importlib.import_module(module_name)
    value = module if attr is None else getattr(module, attr)
    globals()[name] = value
    return value
