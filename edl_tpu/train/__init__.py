from edl_tpu.train.state import TrainState, TrainStatus
from edl_tpu.train.amp import DynamicLossScale
from edl_tpu.train.checkpoint import CheckpointManager, CheckpointWriteError
from edl_tpu.train import lr

__all__ = ["TrainState", "TrainStatus", "CheckpointManager",
           "CheckpointWriteError", "DynamicLossScale", "lr"]
