"""Jitted train/eval step builders.

One compiled SPMD program replaces the reference's per-GPU process + NCCL
allreduce (fleet.distributed_optimizer(...).minimize, train_with_fleet.py:326):
with the batch sharded over the mesh's data axes and params replicated (or
sharded by rules), XLA's partitioner inserts the gradient reductions over
ICI — there is no explicit collective call in user code.
"""

from __future__ import annotations

from typing import Any, Callable

import jax

LossFn = Callable[..., tuple[jax.Array, dict]]


def make_train_step(loss_fn: LossFn, donate: bool = True) -> Callable:
    """Build a jitted step from loss_fn(state, params, batch)->(loss, aux).

    If the model has batch_stats (BN), loss_fn should return aux containing
    'batch_stats' with the new stats; they are folded into the state.
    """

    def step(state, batch):
        def compute(params):
            return loss_fn(state, params, batch)

        (loss, aux), grads = jax.value_and_grad(compute, has_aux=True)(
            state.params)
        new_stats = aux.pop("batch_stats", None)
        if new_stats is not None:
            state = state.apply_gradients(grads=grads, batch_stats=new_stats)
        else:
            state = state.apply_gradients(grads=grads)
        return state, {"loss": loss, **aux}

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_eval_step(metric_fn: Callable[[Any, Any], dict]) -> Callable:
    return jax.jit(metric_fn)
