"""Jitted train/eval step builders.

One compiled SPMD program replaces the reference's per-GPU process + NCCL
allreduce (fleet.distributed_optimizer(...).minimize, train_with_fleet.py:326):
with the batch sharded over the mesh's data axes and params replicated (or
sharded by rules), XLA's partitioner inserts the gradient reductions over
ICI — there is no explicit collective call in user code.
"""

from __future__ import annotations

from typing import Any, Callable

import jax

LossFn = Callable[..., tuple[jax.Array, dict]]


def make_train_step(loss_fn: LossFn, donate: bool = True,
                    loss_scale: bool = False, comm=None, mesh=None,
                    topology=None) -> Callable:
    """Build a jitted step from loss_fn(state, params, batch)->(loss, aux).

    If the model has batch_stats (BN), loss_fn should return aux containing
    'batch_stats' with the new stats; they are folded into the state.

    `loss_scale=True` wraps the backward in dynamic loss scaling
    (train/amp.py — the reference's fp16 `--scale_loss` capability,
    train_with_fleet.py:68-72,318-321): the step signature becomes
    `step(state, batch, ls) -> (state, metrics, ls)` and metrics gain
    'loss_scale'/'finite'. Unneeded for bf16 (the TPU default).

    `comm` (a train/comm.CommConfig, with `mesh` and optionally the
    slice `topology`) swaps the XLA-partitioned gradient reduction for
    the manual DCN-aware path: size-bucketed, hierarchically decomposed
    (ICI reduce-scatter -> cross-slice leg -> ICI all-gather) and
    optionally compressed dp reductions with a loss-parity gate
    (doc/design_comm.md). dp-only meshes; bucketed-dense is bitwise
    with the plain jit path on flat worlds.
    """
    if comm is not None:
        if loss_scale:
            raise ValueError(
                "comm= and loss_scale= are mutually exclusive (the "
                "manual gradient path owns the backward's reduction; "
                "fp16 scaling is an amp-path feature)")
        if mesh is None:
            raise ValueError("comm= needs the mesh the step trains on")
        from edl_tpu.train.comm import make_comm_train_step
        return make_comm_train_step(loss_fn, mesh=mesh, config=comm,
                                    topology=topology, donate=donate)
    def apply(state, grads, aux):
        """Fold optional BN stats + apply the update (shared by both
        branches so the batch_stats contract lives in one place)."""
        new_stats = aux.pop("batch_stats", None)
        if new_stats is not None:
            return state.apply_gradients(grads=grads,
                                         batch_stats=new_stats)
        return state.apply_gradients(grads=grads)

    if loss_scale:
        from edl_tpu.train import amp

        def amp_step(state, batch, ls):
            def compute(params):
                return loss_fn(state, params, batch)

            (loss, aux), grads = amp.scaled_value_and_grad(
                compute, state.params, ls)
            new_state = apply(state, grads, aux)
            ls, selected, finite = amp.update_scale_and_select(
                ls, grads, new_state, state)
            return selected, {"loss": loss, "loss_scale": ls.scale,
                              "finite": finite, **aux}, ls

        return jax.jit(amp_step, donate_argnums=(0,) if donate else ())

    def step(state, batch):
        def compute(params):
            return loss_fn(state, params, batch)

        (loss, aux), grads = jax.value_and_grad(compute, has_aux=True)(
            state.params)
        state = apply(state, grads, aux)
        return state, {"loss": loss, **aux}

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_eval_step(metric_fn: Callable[[Any, Any], dict]) -> Callable:
    return jax.jit(metric_fn)


def donation_coverage(step_fn: Callable, *args) -> dict:
    """Compile-time donated-buffer audit of a jitted train step.

    Lowers (does not run) the step on ``args`` and counts the
    input->output buffer aliases XLA recorded for the donated state —
    the in-place-update guarantee that keeps peak HBM at one copy of
    params+moments instead of two. A step whose params/opt_state
    leaves all alias reports ``full=True``; a refactor that breaks
    donation (e.g. an op capturing the old params beyond the update)
    shows up as a structural drop, which tests assert on rather than
    eyeballing profiler output.

    Returns {aliased, state_leaves, full}. ``state_leaves`` counts the
    array leaves of args[0] (the donated TrainState) — quantized
    moment planes count like any other leaf; their int8 buffers alias
    the same way.
    """
    import re

    header = step_fn.lower(*args).compile().as_text().split("\n", 1)[0]
    aliased = len(re.findall(r"-alias", header))
    donatable = sum(1 for leaf in jax.tree_util.tree_leaves(args[0])
                    if hasattr(leaf, "dtype"))
    return {"aliased": aliased, "state_leaves": donatable,
            "full": aliased >= donatable}
